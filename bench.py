"""Benchmark: batched P-256 signature verification, device vs native CPU.

Prints ONE JSON line:
  {"metric": "p256_sig_verify_p50_us", "value": <device us/sig>,
   "unit": "us/sig", "vs_baseline": <speedup over single-core OpenSSL>}

The metric is BASELINE.md's "p50 sig-verify us/sig".  The baseline is
single-threaded OpenSSL ECDSA-P256 verify (via the `cryptography` wheel) —
the same class of optimized native code as the reference's Go
crypto/ecdsa, which verifies one commit signature per goroutine
(/root/reference/internal/bft/view.go:537-541).  vs_baseline > 1 means one
device kernel launch beats a CPU core by that factor per signature.

Platform: uses whatever JAX platform the environment provides (the axon TPU
tunnel on the driver; CPU elsewhere).  A subprocess probe guards against a
wedged tunnel — if device init doesn't come up in time, the bench re-execs
itself pinned to CPU so it always completes.

Env knobs: SMARTBFT_BENCH_BATCH (default 4096), SMARTBFT_BENCH_REPS (5),
SMARTBFT_BN_UNROLL (default 33 here: full carry-chain unrolling — measured
best on TPU at large batch; tests/engines keep the library default of 1).
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REPS = int(os.environ.get("SMARTBFT_BENCH_REPS", "9"))  # tunnel run-to-run
# variance is +/-15%; a 9-rep median costs ~1.5s and stabilizes the metric


def _resolve_batch(cpu: bool) -> int:
    """TPU: batch 131072 on the comb kernel.  Per-launch overhead through
    the axon tunnel is a fixed ~110 ms regardless of kernel size (measured
    round 3: a trivial pallas kernel with result readback costs the same
    ~110 ms as the full verify), so per-sig cost is dominated by batch
    amortization: 4096 -> 26 us/sig floor from overhead alone; 32768 ->
    8.3; 131072 -> 5.75 us/sig measured end-to-end.  CPU fallback: small
    batch, no unroll — anything bigger compiles for tens of minutes."""
    if cpu:
        os.environ.setdefault("SMARTBFT_BN_UNROLL", "1")
        return int(os.environ.get("SMARTBFT_BENCH_BATCH", "128"))
    os.environ.setdefault("SMARTBFT_BN_UNROLL", "33")
    return int(os.environ.get("SMARTBFT_BENCH_BATCH", "131072"))


PROBE_TIMEOUT = float(os.environ.get("SMARTBFT_BENCH_PROBE_TIMEOUT", "120"))


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def ncores_hint() -> int:
    return os.cpu_count() or 1


def _probe_platform() -> str:
    """Probe default-platform JAX init in a subprocess (tunnel may hang).

    Returns the default backend's platform name ('tpu', 'cpu', ...) or ''
    when initialization fails/hangs.
    """
    code = ("import jax; jax.devices(); import jax.numpy as jnp; "
            "(jnp.ones(4)+1).block_until_ready(); "
            "print(jax.default_backend())")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], timeout=PROBE_TIMEOUT,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        )
    except subprocess.TimeoutExpired:
        return ""
    if proc.returncode != 0:
        return ""
    return proc.stdout.decode().strip().splitlines()[-1] if proc.stdout else ""


def _openssl_prepare(items):
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        encode_dss_signature,
    )

    pubs = {}
    prepared = []
    for msg, r, s, pub in items:
        if pub not in pubs:
            pubs[pub] = ec.EllipticCurvePublicNumbers(
                pub[0], pub[1], ec.SECP256R1()
            ).public_key()
        prepared.append((msg, encode_dss_signature(r, s), pubs[pub]))
    return prepared


def _openssl_baseline(items) -> float:
    """Single-threaded OpenSSL verify; returns us/sig."""
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec

    prepared = _openssl_prepare(items)
    for msg, der, key in prepared[:32]:  # warm up EVP/allocator state
        key.verify(der, msg, ec.ECDSA(hashes.SHA256()))
    best = float("inf")
    for _ in range(3):  # best-of-3: give the baseline its least-noise run
        t0 = time.perf_counter()
        for msg, der, key in prepared:
            key.verify(der, msg, ec.ECDSA(hashes.SHA256()))
        best = min(best, time.perf_counter() - t0)
    return 1e6 * best / len(prepared)


def _openssl_all_cores_baseline(items) -> tuple[float, int]:
    """OpenSSL verify across all host cores (thread pool; the cryptography
    wheel releases the GIL around EVP verify) — the honest CPU baseline:
    the reference verifies one goroutine per signature across every core
    (/root/reference/internal/bft/view.go:537-541).  Returns (us/sig
    effective, ncores)."""
    from concurrent.futures import ThreadPoolExecutor

    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec

    ncores = os.cpu_count() or 1
    prepared = _openssl_prepare(items)

    def verify_one(job):
        msg, der, key = job
        key.verify(der, msg, ec.ECDSA(hashes.SHA256()))

    chunk = max(1, len(prepared) // (4 * ncores))
    best = float("inf")
    with ThreadPoolExecutor(max_workers=ncores) as pool:
        list(pool.map(verify_one, prepared[:64], chunksize=chunk))  # ramp up
        for _ in range(3):  # best-of-3, like the single-core baseline
            t0 = time.perf_counter()
            list(pool.map(verify_one, prepared, chunksize=chunk))
            best = min(best, time.perf_counter() - t0)
    return 1e6 * best / len(prepared), ncores


def main() -> None:
    if os.environ.get("_SMARTBFT_BENCH_CPU") != "1":
        plat = _probe_platform()
        if not plat:
            _log("bench: default JAX platform unavailable (tunnel down?); "
                 "re-exec pinned to CPU")
            env = dict(os.environ, _SMARTBFT_BENCH_CPU="1")
            os.execve(sys.executable, [sys.executable, __file__], env)
        cpu_mode = plat == "cpu"  # healthy init, but no accelerator present
    else:
        cpu_mode = True
    BATCH = _resolve_batch(cpu_mode)  # must precede the first p256 import
    if os.environ.get("_SMARTBFT_BENCH_CPU") == "1":
        from smartbft_tpu.utils.jaxenv import force_cpu

        force_cpu()
    import jax

    from smartbft_tpu.utils.jaxenv import enable_compile_cache

    enable_compile_cache()
    import jax.numpy as jnp

    from smartbft_tpu.crypto import p256

    platform = jax.devices()[0].platform
    _log(f"bench: platform={platform} batch={BATCH} reps={REPS}")

    # workload: BATCH commit votes, 64 distinct replica keys, distinct msgs.
    # Signing goes through sign_raw (native OpenSSL when available, ~60 us;
    # the pure-Python RFC 6979 signer would take minutes at this scale).
    keys = [p256.keygen(b"bench-%d" % i) for i in range(64)]
    t0 = time.perf_counter()
    items = []
    for i in range(BATCH):
        d, pub = keys[i % 64]
        msg = b"proposal-%d" % i
        sig = p256.sign_raw(d, msg)
        r, s = int.from_bytes(sig[:32], "big"), int.from_bytes(sig[32:], "big")
        items.append((msg, r, s, pub))
    _log(f"bench: signed {BATCH} items in {time.perf_counter() - t0:.1f}s")

    import numpy as np

    # Kernel ladder: static-key comb kernel (fastest; per-replica
    # precomputed tables) -> generic fused Pallas kernel -> XLA kernel.
    # Every timed call includes the RESULT READBACK (np.asarray): round-3
    # measurement showed block_until_ready does not reliably wait through
    # the tunnel, and readback is what the engine does in production.
    kern = None
    kern_name = "xla"
    if not cpu_mode and os.environ.get("SMARTBFT_BENCH_PALLAS", "1") == "1":
        tile = int(os.environ.get("SMARTBFT_BENCH_TILE", "512"))
        try:
            from smartbft_tpu.crypto import pallas_comb

            reg = pallas_comb.CombKeyRegistry()
            t0 = time.perf_counter()
            e8, r8, s8, kidx = pallas_comb.pack_items(items, reg)
            _log(f"bench: host prep (tables for 64 keys + packing) "
                 f"{time.perf_counter() - t0:.1f}s")
            gtab = jnp.asarray(pallas_comb.g_table(), jnp.bfloat16)
            qtab = jnp.asarray(reg.stacked(), jnp.bfloat16)
            cargs = tuple(jnp.asarray(a) for a in (e8, r8, s8, kidx))

            def comb_kern(*_ignored):
                return pallas_comb.ecdsa_verify_comb(
                    *cargs, gtab, qtab, tile=tile
                )

            t0 = time.perf_counter()
            mask = np.asarray(comb_kern())
            _log(f"bench: comb kernel first call (compile+run) "
                 f"{time.perf_counter() - t0:.1f}s (tile={tile})")
            kern, kern_name = comb_kern, "comb"
        except Exception as exc:  # noqa: BLE001 — any compile failure
            _log(f"bench: comb kernel unavailable ({type(exc).__name__}: "
                 f"{exc}); trying the generic pallas kernel")
    args = None
    if kern is None:
        args = tuple(jnp.asarray(a) for a in p256.verify_inputs(items))
    if kern is None and not cpu_mode \
            and os.environ.get("SMARTBFT_BENCH_PALLAS", "1") == "1":
        import functools

        from smartbft_tpu.crypto import pallas_ecdsa

        tile = int(os.environ.get("SMARTBFT_BENCH_TILE", "128"))
        kern = functools.partial(pallas_ecdsa.ecdsa_verify, tile=tile)
        try:
            t0 = time.perf_counter()
            mask = np.asarray(kern(*args))
            _log(f"bench: pallas first call (compile+run) "
                 f"{time.perf_counter() - t0:.1f}s (tile={tile})")
            kern_name = "pallas"
        except Exception as exc:  # noqa: BLE001 — any compile failure
            _log(f"bench: pallas kernel unavailable ({type(exc).__name__}); "
                 "falling back to the XLA kernel")
            kern = None
    if kern is None:
        kern = jax.jit(p256.ecdsa_verify_kernel)
        t0 = time.perf_counter()
        mask = np.asarray(kern(*args))
        _log(f"bench: first call (compile+run) {time.perf_counter() - t0:.1f}s")

    if not np.asarray(mask).all():
        _log("bench: ERROR device kernel rejected valid signatures")
        raise SystemExit(1)

    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        np.asarray(kern(*args) if args is not None else kern())
        times.append(time.perf_counter() - t0)
    device_us = 1e6 * statistics.median(times) / BATCH
    _log(f"bench: kernel={kern_name}")
    _log(f"bench: device {device_us:.1f} us/sig "
         f"({BATCH / statistics.median(times):.0f} sigs/s)")

    base_n = min(BATCH, 256)
    base_us = _openssl_baseline(items[:base_n])
    _log(f"bench: openssl single-core {base_us:.1f} us/sig")
    mc_us, ncores = _openssl_all_cores_baseline(items[: max(base_n, 64 * ncores_hint())])
    _log(f"bench: openssl all-cores ({ncores}) {mc_us:.1f} us/sig effective")

    print(json.dumps({
        "metric": "p256_sig_verify_p50_us",
        "value": round(device_us, 2),
        "unit": "us/sig",
        "vs_baseline": round(base_us / device_us, 3),
        "vs_all_cores": round(mc_us / device_us, 3),
        "cores": ncores,
    }), flush=True)


if __name__ == "__main__":
    main()
