"""Benchmark: the BASELINE north star — end-to-end committed tx/s at n=64.

Prints ONE JSON line:
  {"metric": "committed_tx_per_sec_n64", "value": <device tx/s>,
   "unit": "tx/s", "vs_baseline": <device / best-CPU-configuration>}

The device row runs the full consensus cluster (64 replicas, RequestBatch
500, real P-256 signatures on every commit vote, group-commit WALs) with
the pipelined in-flight window (pipeline_depth=16, launch-shadow overlap)
in SUSTAINED-BURST mode (32 back-to-back decisions, so the first launch's
fixed cost is amortized over the burst) and the shared device verify
engine + dedupe coalescer; the baseline row is the SAME cluster at its
best CPU configuration: OpenSSL verify (the reference's Go crypto/ecdsa
class, /root/reference/internal/bft/view.go:537-541) at pipeline_depth=1
(pipelining measurably hurts the GIL-serialized CPU verify path, so k=1
is the baseline's best foot forward).  Every row records its warm-launch
probe (launch_probe_ms) and the output carries BOTH the raw ratio and the
probe-normalized ratio (projected to the rig's historical 110 ms launch
floor) so cross-round comparisons survive tunnel weather.

Platform: uses whatever JAX platform the environment provides (the axon
TPU tunnel on the driver; CPU elsewhere).  A subprocess probe guards
against a wedged tunnel; with no accelerator the e2e bench shrinks to
n=16 to bound runtime.  If the cluster bench fails for any reason, the
kernel-level micro bench (p256_sig_verify_p50_us, the round-1..4 headline)
runs instead so the driver always records a line.

Env knobs: SMARTBFT_BENCH_E2E=0 forces the kernel micro bench;
SMARTBFT_BENCH_NODES / SMARTBFT_BENCH_REQUESTS / SMARTBFT_BENCH_PIPELINE
/ SMARTBFT_BENCH_DECISIONS (sustained-burst length, 0 = legacy
request-count mode) resize the cluster; SMARTBFT_BENCH_BATCH /
SMARTBFT_BENCH_REPS / SMARTBFT_BN_UNROLL tune the kernel micro bench as
before.

Sharded mode: ``--shards 1,2,4`` (or SMARTBFT_BENCH_SHARDS) additionally
runs the benchmarks/sharded.py sweep — S consensus groups over ONE shared
verify plane — and prints a second JSON line whose ``shard`` block
carries the per-shard + aggregate numbers (tx/s, launch fill, cross-shard
wave mix) plus the S=top-vs-S=1 scaling ratio, and whose ``reshard``
block carries the LIVE-resize walk (epoch transitions under load:
per-phase tx/s tracking S, moved-key fraction, drain ms, paused-submit
window — PERF.md round 11).

Transport mode: ``--transport {inproc,tcp,uds}`` (or
SMARTBFT_BENCH_TRANSPORT) additionally runs benchmarks/transport.py —
the SAME workload through the in-process Network and through real
sockets on localhost (the ``smartbft_tpu.net`` subsystem) — and prints a
JSON line whose ``transport`` block carries bytes on the wire, frames
per flush (write coalescing), reconnects, and drops, paired against the
in-process tx/s.

Open-loop mode: ``--open-loop`` (or SMARTBFT_BENCH_OPENLOOP=1) runs
benchmarks/openloop.py — Poisson arrivals at swept offered loads over
Zipf-skewed clients against the admission-controlled sharded front door
— and prints a JSON line whose ``latency`` block carries the
submit→commit percentiles (p50/p95/p99, log-scale histogram), shed
counts, the saturation knee, and the per-degraded-phase percentiles
(breaker-open / view-change / reshard) of the fixed-rate degraded run.
The subprocess timeout is DERIVED from the sweep size and phase plan so
a stuck point degrades inside the child (which salvages the other rows)
instead of this parent killing the whole block.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REPS = int(os.environ.get("SMARTBFT_BENCH_REPS", "9"))  # tunnel run-to-run
# variance is +/-15%; a 9-rep median costs ~1.5s and stabilizes the metric

#: every headline row emitted this run, in order — the input to the
#: longitudinal baseline guard (--check-baseline)
EMITTED_ROWS: list = []


def _emit(row: dict) -> None:
    """Print one headline JSON row AND retain it for --check-baseline."""
    EMITTED_ROWS.append(row)
    print(json.dumps(row), flush=True)


def _resolve_batch(cpu: bool) -> int:
    """TPU: batch 131072 on the comb kernel.  Per-launch overhead through
    the axon tunnel is a fixed ~110 ms regardless of kernel size (measured
    round 3: a trivial pallas kernel with result readback costs the same
    ~110 ms as the full verify), so per-sig cost is dominated by batch
    amortization: 4096 -> 26 us/sig floor from overhead alone; 32768 ->
    8.3; 131072 -> 5.75 us/sig measured end-to-end.  CPU fallback: small
    batch, no unroll — anything bigger compiles for tens of minutes."""
    if cpu:
        os.environ.setdefault("SMARTBFT_BN_UNROLL", "1")
        return int(os.environ.get("SMARTBFT_BENCH_BATCH", "128"))
    os.environ.setdefault("SMARTBFT_BN_UNROLL", "33")
    return int(os.environ.get("SMARTBFT_BENCH_BATCH", "131072"))


PROBE_TIMEOUT = float(os.environ.get("SMARTBFT_BENCH_PROBE_TIMEOUT", "120"))


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def ncores_hint() -> int:
    return os.cpu_count() or 1


def _probe_platform() -> str:
    """Probe default-platform JAX init in a subprocess (tunnel may hang).

    Returns the default backend's platform name ('tpu', 'cpu', ...) or ''
    when initialization fails/hangs.
    """
    code = ("import jax; jax.devices(); import jax.numpy as jnp; "
            "(jnp.ones(4)+1).block_until_ready(); "
            "print(jax.default_backend())")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], timeout=PROBE_TIMEOUT,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        )
    except subprocess.TimeoutExpired:
        return ""
    if proc.returncode != 0:
        return ""
    return proc.stdout.decode().strip().splitlines()[-1] if proc.stdout else ""


def _openssl_prepare(items):
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        encode_dss_signature,
    )

    pubs = {}
    prepared = []
    for msg, r, s, pub in items:
        if pub not in pubs:
            pubs[pub] = ec.EllipticCurvePublicNumbers(
                pub[0], pub[1], ec.SECP256R1()
            ).public_key()
        prepared.append((msg, encode_dss_signature(r, s), pubs[pub]))
    return prepared


def _openssl_baseline(items) -> float:
    """Single-threaded OpenSSL verify; returns us/sig."""
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec

    prepared = _openssl_prepare(items)
    for msg, der, key in prepared[:32]:  # warm up EVP/allocator state
        key.verify(der, msg, ec.ECDSA(hashes.SHA256()))
    best = float("inf")
    for _ in range(3):  # best-of-3: give the baseline its least-noise run
        t0 = time.perf_counter()
        for msg, der, key in prepared:
            key.verify(der, msg, ec.ECDSA(hashes.SHA256()))
        best = min(best, time.perf_counter() - t0)
    return 1e6 * best / len(prepared)


def _openssl_all_cores_baseline(items) -> tuple[float, int]:
    """OpenSSL verify across all host cores (thread pool; the cryptography
    wheel releases the GIL around EVP verify) — the honest CPU baseline:
    the reference verifies one goroutine per signature across every core
    (/root/reference/internal/bft/view.go:537-541).  Returns (us/sig
    effective, ncores)."""
    from concurrent.futures import ThreadPoolExecutor

    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec

    ncores = os.cpu_count() or 1
    prepared = _openssl_prepare(items)

    def verify_one(job):
        msg, der, key = job
        key.verify(der, msg, ec.ECDSA(hashes.SHA256()))

    chunk = max(1, len(prepared) // (4 * ncores))
    best = float("inf")
    with ThreadPoolExecutor(max_workers=ncores) as pool:
        list(pool.map(verify_one, prepared[:64], chunksize=chunk))  # ramp up
        for _ in range(3):  # best-of-3, like the single-core baseline
            t0 = time.perf_counter()
            list(pool.map(verify_one, prepared, chunksize=chunk))
            best = min(best, time.perf_counter() - t0)
    return 1e6 * best / len(prepared), ncores


def _run_throughput_row(extra_args: list[str], cpu_mode: bool,
                        timeout: float) -> dict:
    """One benchmarks/throughput.py row in a subprocess; returns its JSON."""
    here = os.path.dirname(os.path.abspath(__file__))
    cmd = [sys.executable, os.path.join(here, "benchmarks", "throughput.py")]
    cmd += extra_args
    if cpu_mode:
        cmd.append("--cpu")
    proc = subprocess.run(
        cmd, timeout=timeout, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"throughput row {extra_args} failed: "
            f"{proc.stderr.decode(errors='replace')[-400:]}"
        )
    rows = [json.loads(l) for l in proc.stdout.decode().splitlines() if l.strip()]
    rows = [r for r in rows if "tx_per_sec" in r]
    if not rows:
        raise RuntimeError(f"throughput row {extra_args} produced no result")
    return rows[-1]


#: historical best warm-launch probe on this rig (ms) — the normalization
#: anchor for weather-independent cross-round ratio comparisons
LAUNCH_PROBE_FLOOR_MS = 110.0


def _probe_normalized_tx(row: dict) -> float:
    """Project a row's tx/s to the rig's historical launch floor: subtract
    the excess (probe - floor) paid on each launch from the elapsed time.
    Returns 0.0 when the row lacks the inputs (old rows, no launches)."""
    probe = row.get("launch_probe_ms") or 0.0
    launches = row.get("launches") or 0
    elapsed = row.get("elapsed_s") or 0.0
    tx = row.get("tx_per_sec") or 0.0
    if not (probe and launches and elapsed and tx):
        return 0.0
    excess_s = launches * max(probe - LAUNCH_PROBE_FLOOR_MS, 0.0) / 1e3
    adj = elapsed - excess_s
    if adj <= 0:
        return 0.0
    return round(tx * elapsed / adj, 1)


def e2e_bench(cpu_mode: bool) -> None:
    """The north-star metric: device cluster vs best-CPU cluster.

    Sustained-burst protocol (round 6): both rows commit
    SMARTBFT_BENCH_DECISIONS (default 32) back-to-back decisions so the
    first launch's fixed cost is actually amortized; every row carries the
    warm-launch probe (launch_probe_ms) and the output reports the raw AND
    the probe-normalized ratio (tunnel-weather-independent)."""
    nodes = int(os.environ.get(
        "SMARTBFT_BENCH_NODES", "16" if cpu_mode else "64"))
    requests = int(os.environ.get(
        "SMARTBFT_BENCH_REQUESTS", "1200" if cpu_mode else "4000"))
    decisions = int(os.environ.get("SMARTBFT_BENCH_DECISIONS", "32"))
    pipeline = int(os.environ.get("SMARTBFT_BENCH_PIPELINE", "16"))
    timeout = float(os.environ.get("SMARTBFT_BENCH_E2E_TIMEOUT", "580"))
    # rigs without the `cryptography` wheel can still run the e2e with the
    # pure-Python CPU engine (SMARTBFT_BENCH_CPU_ENGINE=host) — the ratio
    # is then NOT comparable to the OpenSSL baseline, only the row shape
    cpu_engine = os.environ.get("SMARTBFT_BENCH_CPU_ENGINE", "openssl")
    common = ["--nodes", str(nodes), "--requests", str(requests),
              "--batch", "500"]
    if decisions > 0:
        common += ["--burst-decisions", str(decisions)]
    _log(f"bench: e2e n={nodes} requests={requests} decisions={decisions} "
         f"pipeline={pipeline} (cpu_mode={cpu_mode})")
    cpu_row = _run_throughput_row(
        common + ["--engines", cpu_engine, "--pipeline", "1"],
        cpu_mode=False, timeout=timeout,  # openssl row needs no device
    )
    _log(f"bench: cpu-best row {cpu_row}")
    dev_row = _run_throughput_row(
        common + ["--engines", "jax", "--pipeline", str(pipeline)],
        cpu_mode=cpu_mode, timeout=timeout,
    )
    _log(f"bench: device row {dev_row}")
    _emit(assemble_e2e_row(dev_row, cpu_row, nodes=nodes,
                           pipeline=pipeline, decisions=decisions))


def assemble_e2e_row(dev_row: dict, cpu_row: dict, *, nodes: int,
                     pipeline: int, decisions: int) -> dict:
    """Fold the device + best-CPU throughput rows into the ONE north-star
    bench line.  Pure function, importable — the schema drift gate
    (obs.benchschema, tests) pins the ``committed_tx_per_sec_n*`` family
    through it exactly as tests pin the open-loop and mesh rows."""
    norm_tx = _probe_normalized_tx(dev_row)
    return {
        "metric": f"committed_tx_per_sec_n{nodes}",
        "value": dev_row["tx_per_sec"],
        "unit": "tx/s",
        "vs_baseline": round(dev_row["tx_per_sec"] / cpu_row["tx_per_sec"], 3)
        if cpu_row["tx_per_sec"] else 0.0,
        "baseline_tx_per_sec": cpu_row["tx_per_sec"],
        "pipeline": pipeline,
        "burst_decisions": decisions,
        "launches": dev_row.get("launches"),
        "decisions": dev_row.get("decisions"),
        "launches_per_decision": dev_row.get("launches_per_decision"),
        "window_launches": dev_row.get("window_launches"),
        "batch_fill_pct": dev_row.get("batch_fill_pct"),
        "launch_probe_ms": dev_row.get("launch_probe_ms"),
        "baseline_launch_probe_ms": cpu_row.get("launch_probe_ms"),
        # breaker accounting rides along so a degraded (host-fallback)
        # device row is never mistaken for a healthy device run
        "breaker": dev_row.get("breaker"),
        # which verify plane ran: single device or an N-device mesh
        # (devices, fill per device, pad waste, loud downgrades)
        "mesh": dev_row.get("mesh"),
        # per-phase message-plane timers (ingest/route/vote-reg/codec) from
        # the device row's timed window — the PERF.md decomposition inputs
        "protocol_plane": dev_row.get("protocol_plane"),
        "baseline_protocol_plane": cpu_row.get("protocol_plane"),
        "tx_per_sec_probe_normalized": norm_tx,
        "vs_baseline_probe_normalized": round(
            norm_tx / cpu_row["tx_per_sec"], 3)
        if norm_tx and cpu_row["tx_per_sec"] else 0.0,
    }


def sharded_bench(shards: str, cpu_mode: bool) -> None:
    """Run the benchmarks/sharded.py sweep in a subprocess and print ONE
    JSON line with the scaling headline + the full ``shard`` block (per-
    shard and aggregate numbers) — the sharded-mode contract of ISSUE 5."""
    here = os.path.dirname(os.path.abspath(__file__))
    cmd = [sys.executable, os.path.join(here, "benchmarks", "sharded.py"),
           "--shards", shards]
    if cpu_mode:
        cmd.append("--cpu")
    # cover the sweep's own worst case (3 reps x points x the per-point
    # salvage deadline, see benchmarks/sharded.py POINT_TIMEOUT) so a
    # stuck point degrades to fewer reps instead of this parent killing
    # the whole shard block before the sweep's internal deadline can fire
    points = max(1, len([s for s in shards.split(",") if s.strip()]))
    point_timeout = float(os.environ.get(
        "SMARTBFT_BENCH_SHARD_POINT_TIMEOUT", "120"))
    # + the live-resize walk (3 phases x worst case of a full drain
    # deadline PLUS a full settle wait each) so a stuck transition
    # degrades inside the child (which salvages the sweep rows) instead
    # of this parent SIGKILLing the whole shard block
    timeout = float(os.environ.get(
        "SMARTBFT_BENCH_SHARD_TIMEOUT",
        str((3 * points + 6) * point_timeout + 120)))
    proc = subprocess.run(
        cmd, timeout=timeout, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"sharded sweep failed: {proc.stderr.decode(errors='replace')[-400:]}"
        )
    rows = [json.loads(l) for l in proc.stdout.decode().splitlines() if l.strip()]
    _emit(assemble_sharded_row(rows))


def assemble_sharded_row(rows: list) -> dict:
    """Fold benchmarks/sharded.py's JSON lines into the ONE bench.py
    sharded row.  Pure function, importable — the schema drift gate pins
    the ``sharded_committed_tx_per_sec`` family through it (PR 8
    idiom)."""
    points = [r for r in rows if "shards" in r and "tx_per_sec" in r]
    scaling = next((r for r in rows if r.get("metric") == "sharded_scaling"), {})
    resize = next((r for r in rows if r.get("metric") == "live_resize"), {})
    if not points:
        raise RuntimeError("sharded sweep produced no rows")
    peak = max(points, key=lambda r: r["shards"])
    return {
        "metric": "sharded_committed_tx_per_sec",
        "value": peak["tx_per_sec"],
        "unit": "tx/s",
        "vs_baseline": scaling.get("value", 0.0),  # S=top vs S=1 aggregate
        "shard": {
            "sweep": [
                {k: r.get(k) for k in (
                    "shards", "tx_per_sec", "launches", "batch_fill_pct",
                    "items_per_launch", "mixed_waves", "elapsed_s",
                    "launch_probe_ms",
                )}
                for r in points
            ],
            "scaling": scaling,
            # full attribution for the top point: per-shard blocks (plane
            # deltas, pool, decisions) + the shared-plane aggregate
            "top": peak.get("shard"),
        },
        # the elastic-shards contract (ISSUE 7): aggregate tx/s tracking S
        # across a LIVE resize, plus the epoch-transition costs (moved
        # keys, drain ms, paused-submit window) per reshard
        "reshard": {
            "path": resize.get("path"),
            "phases": resize.get("phases"),
            "tracking_vs_first": resize.get("tracking_vs_first"),
            **(resize.get("reshard") or {}),
        } if resize else None,
    }


def assemble_mesh_row(rows: list) -> dict:
    """Fold benchmarks/mesh.py's JSON lines into the ONE bench.py mesh
    row.  Pure function, importable — tests/test_mesh_plane.py pins the
    ``mesh`` block schema against it exactly as tests/test_overload.py
    pins the open-loop ``latency`` block.

    The row contract: ``mesh.sweep`` carries the devices ∈ {1,2,4,8}
    points at the fixed shard count (tx/s, launches, items/launch,
    per-launch capacity, fill, pad waste — gated values, with the
    ungated control's launches/fill riding along), ``mesh.gating`` the
    top point's gated-vs-ungated deltas plus the coalescer's hold
    decisions (waves_held, held_ms, depth_gain_items),
    ``mesh.verdict_parity`` / ``mesh.verdict_parity_2d`` the
    bit-for-bit checks against the single-device engine (1D batch mesh
    and 2D seq×vote quorum mesh), ``mesh.capacity_scaling`` the
    top-vs-1 capacity ratio, and ``shard_map_available`` /
    ``downgrades`` record which path ran."""
    sweep = [r for r in rows if r.get("bench") == "mesh"]
    parity = next((r for r in rows if r.get("metric") == "mesh_parity"), {})
    parity_2d = next(
        (r for r in rows if r.get("metric") == "mesh_parity_2d"), {}
    )
    scaling = next((r for r in rows if r.get("metric") == "mesh_scaling"), {})
    if not sweep:
        raise RuntimeError("mesh sweep produced no rows")
    top = max(sweep, key=lambda r: r["devices"])
    base = min(sweep, key=lambda r: r["devices"])
    top_mesh = top.get("mesh") or {}
    return {
        "metric": "mesh_committed_tx_per_sec",
        "value": top["tx_per_sec"],
        "unit": "tx/s",
        "vs_baseline": round(top["tx_per_sec"] / base["tx_per_sec"], 3)
        if base["tx_per_sec"] else 0.0,
        "devices": top["devices"],
        "mesh": {
            "fixed_shards": top.get("shards"),
            "crypto": top.get("crypto"),
            "sweep": [
                {k: r.get(k) for k in (
                    "devices", "tx_per_sec", "launches", "items_per_launch",
                    "capacity_items_per_launch", "batch_fill_pct",
                    "pad_waste_pct", "mixed_waves", "elapsed_s",
                    "launch_probe_ms", "hold_s", "launches_ungated",
                    "batch_fill_ungated_pct", "tx_per_sec_ungated",
                )}
                for r in sweep
            ],
            "capacity_scaling": scaling.get("value"),
            "items_per_launch_ratio": scaling.get("items_per_launch_ratio"),
            "tx_ratio": scaling.get("tx_ratio"),
            # the ISSUE 11 wave-deepening claim at the top point: gated
            # fill up, launches strictly below the ungated control
            "gating": {
                "hold_s": top.get("hold_s"),
                "launches": top.get("launches"),
                "launches_ungated": top.get("launches_ungated"),
                "fill_pct": top.get("batch_fill_pct"),
                "fill_ungated_pct": top.get("batch_fill_ungated_pct"),
                "hold": top_mesh.get("hold"),
            },
            "verdict_parity": {
                "match": parity.get("match"),
                "devices_checked": parity.get("devices_checked"),
                "items": parity.get("items"),
            },
            "verdict_parity_2d": {
                "match": parity_2d.get("match"),
                "counts_match": parity_2d.get("counts_match"),
                "devices_checked": parity_2d.get("devices_checked"),
                "items": parity_2d.get("items"),
            },
            "topology": top_mesh.get("topology", "1d"),
            "shard_map_available": top_mesh.get("shard_map_available"),
            "downgrades": top_mesh.get("downgrades", 0),
            "top": top_mesh,
        },
    }


def mesh_bench(devices: str, cpu_mode: bool) -> None:
    """Run the benchmarks/mesh.py sweep in a subprocess and print ONE
    JSON line whose ``mesh`` block carries the devices sweep at fixed S
    (the ISSUE 10 contract)."""
    here = os.path.dirname(os.path.abspath(__file__))
    cmd = [sys.executable, os.path.join(here, "benchmarks", "mesh.py"),
           "--devices", devices]
    if cpu_mode:
        cmd.append("--cpu")
    points = max(1, len([d for d in devices.split(",") if d.strip()]))
    point_timeout = float(os.environ.get(
        "SMARTBFT_BENCH_MESH_POINT_TIMEOUT", "120"))
    # derived, not guessed: every point runs TWICE (ungated control +
    # gated run) and may burn its commit deadline plus a stuck-cluster
    # teardown each time, and the two parity stages pay one compile per
    # width — the child's per-point salvage fires before this parent
    # kills it
    timeout = float(os.environ.get(
        "SMARTBFT_BENCH_MESH_TIMEOUT",
        str((2 * points + 3) * point_timeout + 120)
    ))
    proc = subprocess.run(
        cmd, timeout=timeout, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"mesh sweep failed: {proc.stderr.decode(errors='replace')[-400:]}"
        )
    rows = [json.loads(l) for l in proc.stdout.decode().splitlines()
            if l.strip()]
    _emit(assemble_mesh_row(rows))


def assemble_open_loop_row(rows: list) -> dict:
    """Fold benchmarks/openloop.py's JSON lines into the ONE bench.py
    open-loop row.  Pure function, importable — tests/test_overload.py
    pins the ``latency`` block schema against it exactly as
    tests/test_verify_plane.py pins the breaker block.

    The row contract: ``latency`` carries the sweep-wide percentiles and
    histogram of the HIGHEST offered load that still met the SLO (or the
    top point when everything overloaded — worst honest number, never an
    empty block), the shed counts, the knee, and ``phases`` with the
    degraded run's per-phase (breaker_open / view_change / reshard)
    percentiles."""
    sweep = [r for r in rows if r.get("bench") == "openloop"]
    knee = next((r for r in rows if r.get("metric") == "open_loop_knee"), {})
    degraded = next(
        (r for r in rows if r.get("metric") == "open_loop_degraded"), {}
    )
    if not sweep:
        raise RuntimeError("open-loop sweep produced no rows")
    last_ok = (knee.get("last_ok") or {}).get("offered_per_sec")
    anchor = next(
        (r for r in sweep if r["offered_per_sec"] == last_ok),
        max(sweep, key=lambda r: r["offered_per_sec"]),
    )
    latency = dict(anchor["latency"])
    latency["shed"] = dict(
        latency.get("shed") or {},
        **{k: anchor["open_loop"][k]
           for k in ("shed_admission", "shed_timeout")},
    )
    latency["knee"] = {
        k: knee.get(k) for k in ("slo", "last_ok", "first_overloaded",
                                 "beyond_sweep")
    }
    latency["phases"] = degraded.get("phases", {})
    return {
        "metric": "open_loop_p99_ms",
        "value": latency.get("p99_ms", 0.0),
        "unit": "ms",
        "offered_per_sec": anchor["offered_per_sec"],
        "goodput_per_sec": anchor["goodput_per_sec"],
        "shards": anchor.get("shards"),
        "zipf_skew": anchor.get("zipf_skew"),
        "admission_high_water": anchor.get("admission_high_water"),
        # ISSUE 12: the degraded run's measured VC sub-phase decomposition
        # + merged flight-recorder summary ride every open-loop row
        "viewchange": degraded.get("viewchange"),
        "trace": degraded.get("trace"),
        # ISSUE 13: the per-request critical-path decomposition (segment
        # sums == end-to-end within the stated residual; per-phase
        # sub-blocks name each degraded phase's dominant segment)
        "critical_path": degraded.get("critical_path"),
        # ISSUE 14: the continuous SLO verdict over the degraded walk
        # (final state + every healthy/degraded/critical transition with
        # the breaching SLO names)
        "health": degraded.get("health"),
        "sweep": [
            {k: r.get(k) for k in ("offered_per_sec", "goodput_per_sec")}
            | {"p99_ms": r["latency"]["p99_ms"],
               "shed_rate": r["open_loop"]["shed_rate"],
               "peak_occupancy": r["open_loop"]["peak_occupancy"]}
            for r in sweep
        ],
        "degraded_notes": degraded.get("notes"),
        "latency": latency,
    }


def viewchange_guard_rows(rows: list) -> list:
    """The ISSUE 15 longitudinal failover pins: scalar rows derived from
    the degraded run so ``--check-baseline`` catches a failover
    regression — the forced-VC phase's request p99 (the round-12
    degraded-table cell that crowned view change the worst failure mode)
    and the detection arm-to-fire p99 under the muted leader.  Pure
    function, importable; returns [] when the degraded run is absent."""
    degraded = next(
        (r for r in rows if r.get("metric") == "open_loop_degraded"), None
    )
    if not degraded:
        return []
    out = []
    phases = degraded.get("phases") or {}
    vc_phase = phases.get("view_change") or {}
    p99 = vc_phase.get("p99_ms")
    if isinstance(p99, (int, float)):
        healthy = (phases.get("healthy") or {}).get("p99_ms")
        row = {
            "metric": "viewchange_phase_p99_ms",
            "value": p99,
            "unit": "ms",
            "offered_per_sec": degraded.get("offered_per_sec"),
            "shards": degraded.get("shards"),
        }
        if isinstance(healthy, (int, float)):
            row["healthy_p99_ms"] = healthy
            if healthy:
                row["vs_healthy"] = round(p99 / healthy, 2)
        out.append(row)
    det = (degraded.get("viewchange") or {}).get("detection") or {}
    if det.get("count") and isinstance(det.get("p99_ms"), (int, float)):
        out.append({
            "metric": "viewchange_detection_p99_ms",
            "value": det["p99_ms"],
            "unit": "ms",
            "count": det.get("count"),
            "offered_per_sec": degraded.get("offered_per_sec"),
            "shards": degraded.get("shards"),
            # the effective-timer derivation that produced it, verbatim
            "timer": (degraded.get("viewchange") or {}).get("timer"),
        })
    return out


def commitpath_guard_rows(rows: list) -> list:
    """The ISSUE 16 commit-path pins: scalar rows derived from the
    open-loop child's output so ``--check-baseline`` catches a raw-speed
    regression — the saturation knee (tx/s, higher is better) and the
    healthy-phase ``propose_wait`` / ``deliver`` critpath shares (unit
    ``share``, lower is better: the two segments the arrival-driven
    proposer and the batched deliver fan-out cut).  Pure function,
    importable; rows degrade to [] when their source block is absent."""
    out = []
    knee = next((r for r in rows if r.get("metric") == "open_loop_knee"), {})
    last_ok = knee.get("last_ok") or {}
    if isinstance(last_ok.get("offered_per_sec"), (int, float)):
        out.append({
            "metric": "open_loop_knee_tx_per_sec",
            "value": last_ok["offered_per_sec"],
            "unit": "tx/s",
            "goodput_per_sec": last_ok.get("goodput_per_sec"),
            "p99_ms": last_ok.get("p99_ms"),
            "beyond_sweep": knee.get("beyond_sweep"),
        })
    degraded = next(
        (r for r in rows if r.get("metric") == "open_loop_degraded"), None
    )
    healthy = (((degraded or {}).get("critical_path") or {})
               .get("phases") or {}).get("healthy") or {}
    segments = healthy.get("segments") or {}
    for seg in ("propose_wait", "deliver"):
        share = (segments.get(seg) or {}).get("share")
        if isinstance(share, (int, float)):
            out.append({
                "metric": f"critpath_{seg}_share",
                "value": share,
                "unit": "share",
                "phase": "healthy",
                "requests": healthy.get("requests"),
                "dominant_segment": healthy.get("dominant_segment"),
                "sums_consistent": healthy.get("sums_consistent"),
                "offered_per_sec": (degraded or {}).get("offered_per_sec"),
            })
    for kr in rows:
        if kr.get("metric") != "open_loop_affinity_knee":
            continue
        s, ok = kr.get("shards"), kr.get("last_ok") or {}
        if isinstance(ok.get("offered_per_sec"), (int, float)):
            out.append({
                "metric": f"open_loop_affinity_s{s}_knee_tx_per_sec",
                "value": ok["offered_per_sec"],
                "unit": "tx/s",
                "shards": s,
                "loop_affinity": kr.get("loop_affinity"),
                "goodput_per_sec": ok.get("goodput_per_sec"),
                "beyond_sweep": kr.get("beyond_sweep"),
            })
    return out


def open_loop_bench(cpu_mode: bool) -> None:
    """Run benchmarks/openloop.py in a subprocess and print ONE JSON line
    whose ``latency`` block carries percentiles + histogram + shed counts
    + knee + degraded-phase percentiles (the round-12 contract)."""
    here = os.path.dirname(os.path.abspath(__file__))
    # default grid raised in round 18 (commit-path raw speed): the knee
    # moved from 800/s to the 8-9k/s band, so the old 200-1600 sweep
    # would read "beyond sweep" and pin nothing
    rates = os.environ.get("SMARTBFT_BENCH_OPENLOOP_RATES",
                           "1000,2000,4000,8000,9000")
    duration = float(os.environ.get("SMARTBFT_BENCH_OPENLOOP_DURATION", "8"))
    phase = float(os.environ.get("SMARTBFT_BENCH_OPENLOOP_PHASE", "6"))
    drain = 3.0
    sweep_shards = os.environ.get("SMARTBFT_BENCH_OPENLOOP_SWEEP_SHARDS", "")
    cmd = [sys.executable, os.path.join(here, "benchmarks", "openloop.py"),
           "--rates", rates, "--duration", str(duration),
           "--phase-duration", str(phase)]
    if sweep_shards:
        cmd += ["--sweep-shards", sweep_shards]
    if cpu_mode:
        cmd.append("--cpu")
    points = len([r for r in rates.split(",") if r.strip()])
    # each affinity-sweep point runs its S workers CONCURRENTLY, so a
    # point costs one duration+drain+salvage budget regardless of S
    affinity_points = (points * len([s for s in sweep_shards.split(",")
                                     if s.strip()])
                       if sweep_shards else 0)
    phase_timeout = float(os.environ.get(
        "SMARTBFT_BENCH_OPENLOOP_PHASE_TIMEOUT", "60"))
    # derived, not guessed (the PR-5/7 salvage lesson): every sweep point
    # may burn its duration + drain + a stuck-cluster teardown, and the
    # degraded run is 5 pumped phases plus 4 bounded waits (breaker
    # open/close, depose, quiesce x2 share one budget each) plus a drain
    # deadline — the child's own salvage fires before this parent kills it
    timeout = float(os.environ.get(
        "SMARTBFT_BENCH_OPENLOOP_TIMEOUT",
        str((points + affinity_points) * (duration + drain + phase_timeout)
            + 5 * (phase + drain) + 5 * phase_timeout + 120)))
    proc = subprocess.run(
        cmd, timeout=timeout, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"open-loop bench failed: "
            f"{proc.stderr.decode(errors='replace')[-400:]}"
        )
    rows = [json.loads(l) for l in proc.stdout.decode().splitlines()
            if l.strip()]
    _emit(assemble_open_loop_row(rows))
    for guard_row in viewchange_guard_rows(rows):
        _emit(guard_row)
    for guard_row in commitpath_guard_rows(rows):
        _emit(guard_row)


def transport_bench(flavor: str) -> None:
    """Run benchmarks/transport.py paired (inproc + the chosen socket
    flavor, SAME workload/protocol stack, only the Comm seam differs) and
    print ONE JSON line whose ``transport`` block carries both rows —
    bytes on the wire, frames per flush (write coalescing), reconnects —
    next to the usual ``protocol_plane`` block."""
    here = os.path.dirname(os.path.abspath(__file__))
    flavors = "inproc" if flavor == "inproc" else f"inproc,{flavor}"
    nodes = os.environ.get("SMARTBFT_BENCH_TRANSPORT_NODES", "4")
    requests = os.environ.get("SMARTBFT_BENCH_TRANSPORT_REQUESTS", "120")
    cmd = [sys.executable, os.path.join(here, "benchmarks", "transport.py"),
           "--flavors", flavors, "--nodes", nodes, "--requests", requests,
           "--cluster-trace"]
    timeout = float(os.environ.get("SMARTBFT_BENCH_TRANSPORT_TIMEOUT", "560"))
    proc = subprocess.run(
        cmd, timeout=timeout, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),  # no device in this bench
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"transport bench failed: "
            f"{proc.stderr.decode(errors='replace')[-400:]}"
        )
    rows = [json.loads(l) for l in proc.stdout.decode().splitlines() if l.strip()]
    _emit(assemble_transport_row(rows, flavor))


def assemble_transport_row(rows: list, flavor: str) -> dict:
    """Fold benchmarks/transport.py's JSON lines into the ONE bench.py
    transport row.  Pure function, importable — the schema drift gate
    pins the ``transport_committed_tx_per_sec`` family through it."""
    by_flavor = {r["flavor"]: r for r in rows if r.get("bench") == "transport"}
    if not by_flavor:
        raise RuntimeError("transport bench produced no rows")
    paired = next((r for r in rows if r.get("metric") == "transport_paired"), {})
    cluster_trace = next(
        (r for r in rows if r.get("metric") == "cluster_timeline"), None
    )
    main_row = by_flavor.get(flavor) or next(iter(by_flavor.values()))
    inproc = by_flavor.get("inproc", {})
    return {
        "metric": "transport_committed_tx_per_sec",
        "value": main_row["tx_per_sec"],
        "unit": "tx/s",
        "vs_baseline": (paired.get("pairs") or [{}])[0].get("vs_inproc", 1.0),
        "flavor": flavor,
        "nodes": main_row["nodes"],
        "requests": main_row["requests"],
        "transport": main_row["transport"],
        "inproc_tx_per_sec": inproc.get("tx_per_sec"),
        "protocol_plane": main_row.get("protocol_plane"),
        "inproc_protocol_plane": inproc.get("protocol_plane"),
        # ISSUE 13: the per-request critical-path decomposition of the
        # measured flavor, and the multi-process merged cluster timeline
        # (clock offsets + per-link network time + merged critical path)
        "critical_path": main_row.get("critical_path"),
        "cluster_trace": cluster_trace,
    }


def rejoin_guard_rows(rows: list) -> list:
    """The ISSUE 17 flat-rejoin pin: ONE scalar row derived from the
    rejoin sweep so ``--check-baseline`` catches an O(1)-rejoin
    regression — the deep-history snapshot rejoin's wall clock over the
    shallow one (unit ``x``, lower is better; the committed baseline
    pins the ideal 1.0 with a 100% allowance, i.e. deep must stay
    within 2x shallow).  The replay control's same ratio rides along
    as context (it is O(depth) by design — hundreds of x).  Pure
    function, importable; returns [] without both snapshot points."""
    snaps, replays = {}, {}
    for r in rows:
        h = r.get("history_decisions")
        if not isinstance(h, (int, float)) \
                or not isinstance(r.get("value"), (int, float)):
            continue
        {"snapshot": snaps, "replay": replays}.get(r.get("mode"), {})[h] = r
    if len(snaps) < 2:
        return []
    small, deep = min(snaps), max(snaps)
    if not snaps[small]["value"]:
        return []
    row = {
        "metric": "rejoin_flatness_vs_depth",
        "value": round(snaps[deep]["value"] / snaps[small]["value"], 4),
        "unit": "x",
        "history_small": int(small),
        "history_deep": int(deep),
        "snapshot_small_s": snaps[small]["value"],
        "snapshot_deep_s": snaps[deep]["value"],
        "interval": snaps[deep].get("interval"),
    }
    if small in replays and deep in replays and replays[small]["value"]:
        row["replay_ratio"] = round(
            replays[deep]["value"] / replays[small]["value"], 4)
    return [row]


def rejoin_bench() -> None:
    """Run benchmarks/rejoin.py (snapshot-install vs full-chain-replay
    rejoin at shallow vs deep history, real LedgerFile/SnapshotStore/
    verification end to end) and emit its ``rejoin_*`` rows plus the
    flat-vs-depth guard row."""
    here = os.path.dirname(os.path.abspath(__file__))
    histories = os.environ.get("SMARTBFT_BENCH_REJOIN_HISTORIES",
                               "100,100000")
    cmd = [sys.executable, os.path.join(here, "benchmarks", "rejoin.py"),
           "--histories", histories]
    timeout = float(os.environ.get("SMARTBFT_BENCH_REJOIN_TIMEOUT", "560"))
    proc = subprocess.run(
        cmd, timeout=timeout, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),  # no device in this bench
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"rejoin bench failed: "
            f"{proc.stderr.decode(errors='replace')[-400:]}"
        )
    rows = [json.loads(l) for l in proc.stdout.decode().splitlines()
            if l.strip()]
    if not rows:
        raise RuntimeError("rejoin bench produced no rows")
    for row in rows:
        _emit(row)
    for guard_row in rejoin_guard_rows(rows):
        _emit(guard_row)


def assemble_byzantine_row(healthy: dict, degraded: dict) -> dict:
    """Fold the paired Byzantine latency probes (no actor vs an active
    vote-forgery flood, SAME cluster + open-loop load) into the ONE
    ``--byzantine`` degraded-mode row.  Pure function, importable — the
    schema drift gate pins the ``byzantine_forge_p99_ms`` family through
    it.  The row's value is the honest-path request p99 WITH the forger
    flooding; ``healthy_p99_ms``/``vs_healthy`` carry the no-actor
    control so the baseline can bound the forger's latency tax."""
    h_lat = healthy.get("latency") or {}
    d_lat = degraded.get("latency") or {}
    h99, d99 = h_lat.get("p99_ms"), d_lat.get("p99_ms")
    if not isinstance(d99, (int, float)) or not isinstance(h99, (int, float)):
        raise RuntimeError(
            f"byzantine probes resolved no p99 (healthy={h99!r}, "
            f"degraded={d99!r}) — no spike request ever committed"
        )
    row = {
        "metric": "byzantine_forge_p99_ms",
        "value": round(float(d99), 3),
        "unit": "ms",
        "healthy_p99_ms": round(float(h99), 3),
        "forged": degraded.get("forged"),
        "shun_events": degraded.get("shun_events"),
        "shed_votes": degraded.get("shed_votes"),
        "spike_acked": degraded.get("spike_acked"),
        "healthy_spike_acked": healthy.get("spike_acked"),
        "latency": d_lat,
        "healthy_latency": h_lat,
    }
    if h99:
        row["vs_healthy"] = round(float(d99) / float(h99), 2)
    return row


def byzantine_bench() -> None:
    """Run the paired Byzantine degraded-mode probes (ISSUE 18): open-
    loop arrivals against the n=4 forgery-rejecting toy-crypto cluster,
    once clean and once with an f=1 actor flooding forged votes at the
    shared verify plane.  The emitted row bounds what the flood costs
    HONEST clients once the per-sender accounting shuns and sheds the
    forger — the longitudinal pin that the defense keeps working."""
    import asyncio

    from smartbft_tpu.testing.chaos import byzantine_latency_probe

    rate = float(os.environ.get("SMARTBFT_BENCH_BYZ_RATE", "30"))

    async def paired():
        healthy = await byzantine_latency_probe(forge=False, rate=rate)
        degraded = await byzantine_latency_probe(forge=True, rate=rate)
        return healthy, degraded

    healthy, degraded = asyncio.run(paired())
    _emit(assemble_byzantine_row(healthy, degraded))


def selfdrive_bench() -> None:
    """Run the self-driving control-plane storm round (ISSUE 20): one
    ``remediation_storm_round`` — load spike, verify-engine hang, muted
    leader — with the verdict→action controller live, emitting the
    ``selfdrive_actions_per_fault`` and ``selfdrive_oscillation_reversals``
    guard rows.  The baseline pins actions-per-fault at the measured 1.0
    (trips past 2, the anti-thrash bound) and reversals at zero (any
    A→B→A flip inside one hysteresis window regresses)."""
    import asyncio

    from smartbft_tpu.obs.benchschema import assemble_selfdrive_rows
    from smartbft_tpu.testing.chaos import remediation_storm_round

    seed = int(os.environ.get("SMARTBFT_BENCH_SELFDRIVE_SEED", "1"))
    stats = asyncio.run(remediation_storm_round(seed=seed, verbose=False))
    for row in assemble_selfdrive_rows(stats):
        _emit(row)


def mixed_read_bench() -> None:
    """Run benchmarks/readplane.py (ISSUE 19): the mixed 95/5 read/write
    sweep against the live socket cluster (quorum-read p99 next to the
    same run's full-path write p99, the read-storm isolation check) plus
    the n=4 vs n=8 read-capacity scaling point, emitting the
    ``read_p99_ms`` and ``read_scaling_vs_n`` rows."""
    here = os.path.dirname(os.path.abspath(__file__))
    scale = os.environ.get("SMARTBFT_BENCH_READ_SCALE", "4,8")
    cmd = [sys.executable, os.path.join(here, "benchmarks", "readplane.py"),
           "--scale-nodes", scale]
    timeout = float(os.environ.get("SMARTBFT_BENCH_READ_TIMEOUT", "560"))
    proc = subprocess.run(
        cmd, timeout=timeout, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),  # no device in this bench
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"read-plane bench failed: "
            f"{proc.stderr.decode(errors='replace')[-400:]}"
        )
    rows = [json.loads(l) for l in proc.stdout.decode().splitlines()
            if l.strip()]
    if not rows:
        raise RuntimeError("read-plane bench produced no rows")
    for row in rows:
        _emit(row)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--shards", default=os.environ.get("SMARTBFT_BENCH_SHARDS", ""),
        help="comma-separated shard counts: additionally run the sharded "
             "sweep (benchmarks/sharded.py) and emit its JSON row with the "
             "per-shard + aggregate `shard` block",
    )
    ap.add_argument(
        "--mesh", nargs="?", const="1,2,4,8",
        default=os.environ.get("SMARTBFT_BENCH_MESH", ""),
        help="additionally run the mesh verify-plane sweep (benchmarks/"
             "mesh.py): fixed S, devices swept (default 1,2,4,8) on the "
             "virtual CPU mesh (real devices when present), emitting a "
             "`mesh` block (per-launch capacity/fill/pad-waste per device "
             "count + bit-for-bit verdict parity) in the JSON row",
    )
    ap.add_argument(
        "--open-loop", action="store_true",
        default=os.environ.get("SMARTBFT_BENCH_OPENLOOP", "") == "1",
        help="additionally run the open-loop service-level bench "
             "(benchmarks/openloop.py): Poisson/Zipf arrivals against the "
             "admission-controlled sharded front door, emitting a "
             "`latency` block (p50/p95/p99, shed counts, saturation knee, "
             "per-degraded-phase percentiles) in the JSON row",
    )
    ap.add_argument(
        "--transport", default=os.environ.get("SMARTBFT_BENCH_TRANSPORT", ""),
        choices=("", "inproc", "tcp", "uds"),
        help="additionally run the paired transport bench (benchmarks/"
             "transport.py): the SAME workload through the in-process "
             "Network and through real sockets on localhost, emitting a "
             "`transport` block (bytes on the wire, frames/flush, "
             "reconnects) in the JSON row",
    )
    ap.add_argument(
        "--rejoin", action="store_true",
        default=os.environ.get("SMARTBFT_BENCH_REJOIN", "") == "1",
        help="additionally run the rejoin bench (benchmarks/rejoin.py): "
             "snapshot-install vs full-chain-replay rejoin wall clock and "
             "bytes at shallow vs deep decision history "
             "(SMARTBFT_BENCH_REJOIN_HISTORIES, default 100,100000), "
             "emitting `rejoin_*` rows plus the flat-vs-depth guard row",
    )
    ap.add_argument(
        "--byzantine", action="store_true",
        default=os.environ.get("SMARTBFT_BENCH_BYZANTINE", "") == "1",
        help="additionally run the Byzantine degraded-mode probe "
             "(testing.chaos.byzantine_latency_probe): honest-path "
             "request p99 under an active vote-forgery flood vs the same "
             "cluster's no-actor control, emitting the "
             "byzantine_forge_p99_ms row the baseline bounds",
    )
    ap.add_argument(
        "--selfdrive", action="store_true",
        default=os.environ.get("SMARTBFT_BENCH_SELFDRIVE", "") == "1",
        help="additionally run the self-driving control-plane storm "
             "round (testing.chaos.remediation_storm_round): spike + "
             "engine hang + muted leader with the verdict→action "
             "controller live, emitting the selfdrive_actions_per_fault "
             "and selfdrive_oscillation_reversals guard rows",
    )
    ap.add_argument(
        "--mixed-read", action="store_true",
        default=os.environ.get("SMARTBFT_BENCH_MIXED_READ", "") == "1",
        help="additionally run the read-plane bench (benchmarks/"
             "readplane.py): mixed 95/5 quorum-read/write wall p99s "
             "against the live socket cluster, the read-storm shed "
             "isolation check, and the n=4 vs n=8 read-capacity scaling "
             "point (SMARTBFT_BENCH_READ_SCALE), emitting the "
             "read_p99_ms and read_scaling_vs_n rows",
    )
    ap.add_argument(
        "--check-baseline", nargs="?", const="BASELINE_OBS.json",
        default=os.environ.get("SMARTBFT_BENCH_CHECK_BASELINE", ""),
        help="after every selected bench ran, diff the emitted rows (plus "
             "the deterministic tiny logical-clock row) against the pinned "
             "baseline file (default BASELINE_OBS.json) and exit non-zero "
             "on regression or schema drift — the longitudinal guard "
             "(smartbft_tpu.obs.baseline)",
    )
    args, _unknown = ap.parse_known_args()

    if os.environ.get("_SMARTBFT_BENCH_CPU") != "1":
        plat = _probe_platform()
        if not plat:
            _log("bench: default JAX platform unavailable (tunnel down?); "
                 "re-exec pinned to CPU")
            env = dict(os.environ, _SMARTBFT_BENCH_CPU="1")
            os.execve(sys.executable, [sys.executable] + sys.argv, env)
        cpu_mode = plat == "cpu"  # healthy init, but no accelerator present
    else:
        cpu_mode = True

    if args.shards:
        try:
            sharded_bench(args.shards, cpu_mode)
        except Exception as exc:  # noqa: BLE001 — sharded row is additive
            _log(f"bench: sharded sweep failed ({type(exc).__name__}: {exc})")

    if args.mesh:
        try:
            mesh_bench(args.mesh, cpu_mode)
        except Exception as exc:  # noqa: BLE001 — mesh row is additive
            _log(f"bench: mesh sweep failed ({type(exc).__name__}: {exc})")

    if args.open_loop:
        try:
            open_loop_bench(cpu_mode)
        except Exception as exc:  # noqa: BLE001 — open-loop row is additive
            _log(f"bench: open-loop bench failed ({type(exc).__name__}: {exc})")

    if args.transport:
        try:
            transport_bench(args.transport)
        except Exception as exc:  # noqa: BLE001 — transport row is additive
            _log(f"bench: transport bench failed ({type(exc).__name__}: {exc})")

    if args.rejoin:
        try:
            rejoin_bench()
        except Exception as exc:  # noqa: BLE001 — rejoin row is additive
            _log(f"bench: rejoin bench failed ({type(exc).__name__}: {exc})")

    if args.byzantine:
        try:
            byzantine_bench()
        except Exception as exc:  # noqa: BLE001 — byzantine row is additive
            _log(f"bench: byzantine probe failed ({type(exc).__name__}: {exc})")

    if args.selfdrive:
        try:
            selfdrive_bench()
        except Exception as exc:  # noqa: BLE001 — selfdrive rows are additive
            _log(f"bench: selfdrive storm failed ({type(exc).__name__}: {exc})")

    if args.mixed_read:
        try:
            mixed_read_bench()
        except Exception as exc:  # noqa: BLE001 — read rows are additive
            _log(f"bench: read-plane bench failed ({type(exc).__name__}: {exc})")

    if os.environ.get("SMARTBFT_BENCH_E2E", "1") == "1":
        try:
            e2e_bench(cpu_mode)
        except Exception as exc:  # noqa: BLE001 — any bench failure
            _log(f"bench: e2e cluster bench failed ({type(exc).__name__}: "
                 f"{exc}); falling back to the kernel micro bench")
            kernel_bench(cpu_mode)
    else:
        kernel_bench(cpu_mode)

    if args.check_baseline:
        raise SystemExit(check_baseline(args.check_baseline))


def check_baseline(path: str) -> int:
    """The longitudinal regression gate: diff this run's emitted rows —
    plus the deterministic tiny logical-clock row, so the gate always
    has at least one comparable metric — against the pinned baseline.
    Returns the process exit code (non-zero on regression/drift)."""
    from smartbft_tpu.obs.baseline import (
        check_rows, load_baseline, render_check, tiny_logical_row,
    )

    rows = list(EMITTED_ROWS)
    tiny_failed = False
    try:
        rows.append(tiny_logical_row())
    except Exception as exc:  # noqa: BLE001 — the gate still checks the
        _log(f"bench: tiny logical row failed ({exc!r})")  # emitted rows
        tiny_failed = True
    result = check_rows(rows, load_baseline(path))
    _log(render_check(result))
    # a gate that compared NOTHING verified nothing: an empty comparison
    # (every bench failed AND the tiny row failed) must read as failure,
    # not as green — that is exactly the most-broken state
    vacuous = not result["checked"]
    ok = result["ok"] and not vacuous and not tiny_failed
    if vacuous:
        _log("bench: baseline check compared ZERO metrics — failing the "
             "gate (a vacuous check is not a passing one)")
    print(json.dumps({
        "metric": "baseline_check",
        "baseline": path,
        "ok": ok,
        "vacuous": vacuous,
        "tiny_row_failed": tiny_failed,
        "checked": result["checked"],
        "regressions": result["regressions"],
        "schema_errors": result["schema_errors"],
    }), flush=True)
    return 0 if ok else 1


def kernel_bench(cpu_mode: bool) -> None:
    BATCH = _resolve_batch(cpu_mode)  # must precede the first p256 import
    if os.environ.get("_SMARTBFT_BENCH_CPU") == "1":
        from smartbft_tpu.utils.jaxenv import force_cpu

        force_cpu()
    import jax

    from smartbft_tpu.utils.jaxenv import enable_compile_cache

    enable_compile_cache()
    import jax.numpy as jnp

    from smartbft_tpu.crypto import p256

    platform = jax.devices()[0].platform
    _log(f"bench: platform={platform} batch={BATCH} reps={REPS}")

    # workload: BATCH commit votes, 64 distinct replica keys, distinct msgs.
    # Signing goes through sign_raw (native OpenSSL when available, ~60 us;
    # the pure-Python RFC 6979 signer would take minutes at this scale).
    keys = [p256.keygen(b"bench-%d" % i) for i in range(64)]
    t0 = time.perf_counter()
    items = []
    for i in range(BATCH):
        d, pub = keys[i % 64]
        msg = b"proposal-%d" % i
        sig = p256.sign_raw(d, msg)
        r, s = int.from_bytes(sig[:32], "big"), int.from_bytes(sig[32:], "big")
        items.append((msg, r, s, pub))
    _log(f"bench: signed {BATCH} items in {time.perf_counter() - t0:.1f}s")

    import numpy as np

    # Kernel ladder: static-key comb kernel (fastest; per-replica
    # precomputed tables) -> generic fused Pallas kernel -> XLA kernel.
    # Every timed call includes the RESULT READBACK (np.asarray): round-3
    # measurement showed block_until_ready does not reliably wait through
    # the tunnel, and readback is what the engine does in production.
    kern = None
    kern_name = "xla"
    if not cpu_mode and os.environ.get("SMARTBFT_BENCH_PALLAS", "1") == "1":
        tile = int(os.environ.get("SMARTBFT_BENCH_TILE", "512"))
        try:
            from smartbft_tpu.crypto import pallas_comb

            reg = pallas_comb.CombKeyRegistry()
            t0 = time.perf_counter()
            e8, r8, s8, kidx = pallas_comb.pack_items(items, reg)
            _log(f"bench: host prep (tables for 64 keys + packing) "
                 f"{time.perf_counter() - t0:.1f}s")
            gtab = jnp.asarray(pallas_comb.g_table(), jnp.bfloat16)
            qtab = jnp.asarray(reg.stacked(), jnp.bfloat16)
            cargs = tuple(jnp.asarray(a) for a in (e8, r8, s8, kidx))

            def comb_kern(*_ignored):
                return pallas_comb.ecdsa_verify_comb(
                    *cargs, gtab, qtab, tile=tile
                )

            t0 = time.perf_counter()
            mask = np.asarray(comb_kern())
            _log(f"bench: comb kernel first call (compile+run) "
                 f"{time.perf_counter() - t0:.1f}s (tile={tile})")
            kern, kern_name = comb_kern, "comb"
        except Exception as exc:  # noqa: BLE001 — any compile failure
            _log(f"bench: comb kernel unavailable ({type(exc).__name__}: "
                 f"{exc}); trying the generic pallas kernel")
    args = None
    if kern is None:
        args = tuple(jnp.asarray(a) for a in p256.verify_inputs(items))
    if kern is None and not cpu_mode \
            and os.environ.get("SMARTBFT_BENCH_PALLAS", "1") == "1":
        import functools

        from smartbft_tpu.crypto import pallas_ecdsa

        tile = int(os.environ.get("SMARTBFT_BENCH_TILE", "128"))
        kern = functools.partial(pallas_ecdsa.ecdsa_verify, tile=tile)
        try:
            t0 = time.perf_counter()
            mask = np.asarray(kern(*args))
            _log(f"bench: pallas first call (compile+run) "
                 f"{time.perf_counter() - t0:.1f}s (tile={tile})")
            kern_name = "pallas"
        except Exception as exc:  # noqa: BLE001 — any compile failure
            _log(f"bench: pallas kernel unavailable ({type(exc).__name__}); "
                 "falling back to the XLA kernel")
            kern = None
    if kern is None:
        kern = jax.jit(p256.ecdsa_verify_kernel)
        t0 = time.perf_counter()
        mask = np.asarray(kern(*args))
        _log(f"bench: first call (compile+run) {time.perf_counter() - t0:.1f}s")

    if not np.asarray(mask).all():
        _log("bench: ERROR device kernel rejected valid signatures")
        raise SystemExit(1)

    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        np.asarray(kern(*args) if args is not None else kern())
        times.append(time.perf_counter() - t0)
    device_us = 1e6 * statistics.median(times) / BATCH
    _log(f"bench: kernel={kern_name}")
    _log(f"bench: device {device_us:.1f} us/sig "
         f"({BATCH / statistics.median(times):.0f} sigs/s)")

    base_n = min(BATCH, 256)
    base_us = _openssl_baseline(items[:base_n])
    _log(f"bench: openssl single-core {base_us:.1f} us/sig")
    mc_us, ncores = _openssl_all_cores_baseline(items[: max(base_n, 64 * ncores_hint())])
    _log(f"bench: openssl all-cores ({ncores}) {mc_us:.1f} us/sig effective")

    from smartbft_tpu.metrics import protocol_plane_snapshot

    _emit({
        "metric": "p256_sig_verify_p50_us",
        "value": round(device_us, 2),
        "unit": "us/sig",
        "vs_baseline": round(base_us / device_us, 3),
        "vs_all_cores": round(mc_us / device_us, 3),
        "cores": ncores,
        # kernel micro bench drives no cluster, so the plane block is the
        # (all-zero) process snapshot — present in EVERY bench row by
        # contract so downstream tooling can rely on the key
        "protocol_plane": protocol_plane_snapshot(),
    })


if __name__ == "__main__":
    main()
