"""Mesh verify-plane sweep: one coalesced wave, N devices (ISSUE 10).

Fixed shard count S, devices swept over ``--devices`` (default 1,2,4,8):
each point runs a full S-shard cluster — routed front door, pipelined
windows, ONE shared coalescer — with the verify plane graduated onto a
D-device mesh through the REAL ``Configuration.verify_mesh_devices``
knob (``Consensus._wire_verify_plane`` → ``CryptoProvider.
configure_verify_mesh``), not a bench-only bypass.  Each engine carries
a fixed per-device lane budget, so aggregate per-launch CAPACITY scales
linearly with the mesh width — the economics that amortize the rig's
fixed ~220 ms launch overhead across all devices (PAPERS.md [7]).

Two stages, each printing JSON lines:

* **parity** — the same randomized mixed wave (several signers, forged
  items, counts that force pad slots) is verified through the
  single-device engine and through a MeshVerifyEngine at every swept
  device count; the row records whether every verdict vector matched
  bit-for-bit.  The tier-1 property test pins the same claim for P-256;
  the bench re-checks it for the crypto it actually runs.
* **sweep** — one ``{"bench": "mesh", "devices": D, ...}`` row per
  point (tx/s, launches, items/launch, capacity, fill, pad waste, mixed
  waves, the coalescer ``mesh`` block) plus a final ``mesh_scaling``
  line comparing the top point against D=1.

Crypto: ``--crypto toy`` (default) is the real CryptoProvider stack over
``testing.toy_scheme`` — an array-math kernel that compiles in
milliseconds at EVERY mesh width, so the sweep runs anywhere (each
device count is a distinct mesh, hence a distinct XLA computation; the
P-256 bignum kernel costs minutes per mesh shape on a cold cache).
``--crypto p256`` runs the production curve for device rigs.

On CPU-only hosts the sweep self-provisions a virtual device mesh
exactly like the MULTICHIP harness (``force_cpu(virtual_devices=N)``);
with real accelerators present it uses them, dropping (and logging)
sweep points wider than the host.

Run:  python benchmarks/mesh.py [--devices 1,2,4,8] [--shards 2] [--cpu]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartbft_tpu.utils.jaxenv import force_cpu  # noqa: E402


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


#: per-sweep-point commit deadline (seconds); bench.py derives its
#: subprocess timeout from this so a stuck point degrades inside this
#: child (which salvages the other rows) instead of the parent killing
#: the whole mesh block (the PR 5/7/8 salvage lesson)
POINT_TIMEOUT = float(os.environ.get("SMARTBFT_BENCH_MESH_POINT_TIMEOUT",
                                     "120"))


def _scheme(crypto: str):
    if crypto == "toy":
        from smartbft_tpu.testing import toy_scheme

        return toy_scheme
    from smartbft_tpu.crypto import p256

    return p256


def _mixed_wave(scheme, n_signers: int = 3, count: int = 23,
                forge_every: int = 5, seed: bytes = b"mesh-parity"):
    """One mixed-tag wave: ``count`` items round-robined over
    ``n_signers`` distinct keys (the shard analog), every
    ``forge_every``-th signature corrupted.  ``count`` deliberately not a
    device multiple, so every mesh width exercises pad slots."""
    keys = [scheme.keygen(seed + b"-%d" % i) for i in range(n_signers)]
    items, expect = [], []
    for i in range(count):
        sk, pub = keys[i % n_signers]
        msg = b"mesh-msg-%d" % i
        sig = scheme.sign_raw(sk, msg)
        ok = i % forge_every != forge_every - 1
        if not ok:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        items.append(scheme.make_item(msg, sig, pub))
        expect.append(ok)
    return items, expect


def run_parity(device_counts: list[int], crypto: str) -> dict:
    """Bit-for-bit verdict parity: mesh engines at every device count
    against the single-device engine over the same mixed wave."""
    from smartbft_tpu.crypto.provider import JaxVerifyEngine
    from smartbft_tpu.parallel import MeshVerifyEngine

    scheme = _scheme(crypto)
    items, expect = _mixed_wave(scheme)
    single = JaxVerifyEngine(pad_sizes=(16, 64), scheme=scheme)
    base = single.verify(items)
    match = base == expect
    checked = []
    for d in device_counts:
        mesh = MeshVerifyEngine(devices=d, pad_sizes=(16, 64), scheme=scheme)
        got = mesh.verify(items)
        checked.append(d)
        if got != base:
            match = False
            _log(f"mesh parity: MISMATCH at devices={d}")
    return {
        "metric": "mesh_parity",
        "crypto": crypto,
        "devices_checked": checked,
        "items": len(items),
        "match": bool(match),
    }


def build_cluster(tmp, devices: int, args, scheme):
    """S-shard cluster whose verify plane graduates onto a
    ``devices``-wide mesh through the Configuration knob."""
    import dataclasses

    from smartbft_tpu.crypto.provider import JaxVerifyEngine
    from smartbft_tpu.testing.sharded import ShardedCluster, sharded_config

    per_dev = tuple(int(x) for x in args.per_device_lanes.split(",")
                    if x.strip())
    pad_sizes = tuple(l * devices for l in per_dev)

    def cfg(s, i):
        return dataclasses.replace(
            sharded_config(i, depth=args.pipeline),
            verify_mesh_devices=devices,
            wal_group_commit=True,
            request_batch_max_count=args.batch,
            request_batch_max_interval=0.02,
            request_pool_size=max(4 * args.decisions * args.batch, 800),
            incoming_message_buffer_size=max(2000, 40 * args.nodes),
            request_forward_timeout=300.0,
            request_complain_timeout=600.0,
            request_auto_remove_timeout=1200.0,
            view_change_resend_interval=300.0,
            view_change_timeout=1200.0,
            leader_heartbeat_timeout=900.0,
        )

    # the initial engine only donates its pad ladder: configure_verify_mesh
    # (wired from the knob at Consensus.start) swaps the coalescer onto the
    # MeshVerifyEngine with the SAME ladder — fixed lanes per device, so
    # capacity scales with the mesh width
    seed_engine = JaxVerifyEngine(pad_sizes=pad_sizes, scheme=scheme)
    return ShardedCluster(
        tmp, shards=args.shards, n=args.nodes, depth=args.pipeline,
        crypto=args.crypto, engine=seed_engine, window=args.window,
        config_fn=cfg, seed=17,
    )


async def run_sweep_point(devices: int, args) -> dict:
    from smartbft_tpu.crypto.provider import VerifyStats
    from smartbft_tpu.utils.clock import WallClockDriver

    scheme = _scheme(args.crypto)
    requests_per_shard = args.decisions * args.batch
    tmp = tempfile.mkdtemp(prefix=f"bench-mesh-{devices}-")
    cluster = build_cluster(tmp, devices, args, scheme)
    driver = WallClockDriver(cluster.scheduler, tick_interval=0.01)
    try:
        driver.start()
        await cluster.start()
        engine = cluster.coalescer.engine
        got_devices = int(getattr(engine, "devices", 0))
        if got_devices != devices:
            raise RuntimeError(
                f"knob wiring failed: wanted a {devices}-device mesh, "
                f"coalescer runs {type(engine).__name__} ({got_devices})"
            )
        # pre-warm every mesh lane shape + probe the warm launch cost
        sk, pub = scheme.keygen(b"mesh-probe")
        item = scheme.make_item(b"p", scheme.sign_raw(sk, b"p"), pub)
        for size in engine.pad_sizes:
            engine.verify([item] * size)
        t0 = time.perf_counter()
        for _ in range(3):
            engine.verify([item])
        launch_probe_ms = 1e3 * (time.perf_counter() - t0) / 3
        engine.stats = type(engine.stats)(
            devices=got_devices, metrics=engine.stats.metrics
        ) if hasattr(engine.stats, "devices") else VerifyStats()

        for s in range(args.shards):
            cluster.client_for_shard(s, 3)
        t0 = time.perf_counter()
        for j in range(args.decisions):
            for s in range(args.shards):
                for k in range(args.batch):
                    cid = cluster.client_for_shard(s, (j + k) % 4)
                    await cluster.submit(cid, f"m-{s}-{j}-{k}")
        deadline = time.perf_counter() + POINT_TIMEOUT
        while time.perf_counter() < deadline:
            if all(sh.committed() >= requests_per_shard
                   for sh in cluster.shard_list):
                break
            await asyncio.sleep(0.02)
        else:
            raise TimeoutError(
                f"devices={devices}: shards committed "
                f"{[sh.committed() for sh in cluster.shard_list]} "
                f"of {requests_per_shard} in time"
            )
        elapsed = time.perf_counter() - t0
        cluster.check_invariants()

        stats = engine.stats
        total = sum(sh.committed() for sh in cluster.shard_list)
        decisions = sum(sh.height() for sh in cluster.shard_list)
        mesh_block = cluster.coalescer.mesh_snapshot()
        return {
            "bench": "mesh",
            "devices": devices,
            "shards": args.shards,
            "crypto": args.crypto,
            "nodes_per_shard": args.nodes,
            "pipeline": args.pipeline,
            "decisions": decisions,
            "tx_per_sec": round(total / elapsed, 1),
            "launches": stats.launches,
            "items_per_launch": round(stats.sigs_verified / stats.launches, 1)
            if stats.launches else 0.0,
            "capacity_items_per_launch": int(engine.pad_sizes[-1]),
            "batch_fill_pct": round(stats.batch_fill_pct, 1),
            "pad_waste_pct": mesh_block.get("pad_waste_pct", 0.0),
            "mixed_waves":
                cluster.coalescer.shard_snapshot()["mixed_waves"],
            "launch_probe_ms": round(launch_probe_ms, 2),
            "elapsed_s": round(elapsed, 2),
            "mesh": mesh_block,
        }
    finally:
        try:
            await cluster.stop()
        except Exception:
            pass
        await driver.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", default="1,2,4,8",
                    help="comma-separated mesh widths to sweep")
    ap.add_argument("--shards", type=int, default=2,
                    help="FIXED shard count S (the sweep varies devices)")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--decisions", type=int, default=12,
                    help="decisions committed per shard per point")
    ap.add_argument("--pipeline", type=int, default=8)
    ap.add_argument("--crypto", choices=("toy", "p256"), default="toy")
    ap.add_argument("--per-device-lanes", default="4,16",
                    help="pad-ladder lanes contributed by EACH device — "
                         "per-launch capacity = lanes x devices")
    ap.add_argument("--window", type=float, default=0.02,
                    help="coalescer fan-in window (seconds)")
    ap.add_argument("--cpu", action="store_true",
                    help="pin JAX to CPU and self-provision a virtual "
                         "device mesh (the MULTICHIP harness idiom)")
    args = ap.parse_args()

    sweep = [int(x) for x in args.devices.split(",") if x.strip()]
    if args.cpu or os.environ.get("SMARTBFT_BENCH_CPU") == "1":
        force_cpu(virtual_devices=max(sweep))
    import jax

    avail = len(jax.devices())
    dropped = [d for d in sweep if d > avail]
    if dropped:
        # no silent caps: the sweep runs what fits and SAYS what it dropped
        _log(f"mesh: host has {avail} device(s); dropping sweep points "
             f"{dropped}")
        sweep = [d for d in sweep if d <= avail]
    if not sweep:
        _log("mesh: no sweep point fits this host")
        return

    try:
        print(json.dumps(run_parity(sweep, args.crypto)), flush=True)
    except Exception as exc:  # noqa: BLE001 — parity row is additive
        _log(f"mesh parity: FAILED — {exc!r}")

    rows = []
    for d in sweep:
        try:
            row = asyncio.run(run_sweep_point(d, args))
        except Exception as exc:  # noqa: BLE001 — a failed point costs
            # ITS slot only; the sweep still prints the other rows
            _log(f"mesh[{d}]: FAILED — {exc!r}")
            continue
        _log(f"mesh[{d}]: {row['tx_per_sec']} tx/s, {row['launches']} "
             f"launches, {row['items_per_launch']} items/launch "
             f"(capacity {row['capacity_items_per_launch']}), fill "
             f"{row['batch_fill_pct']}%")
        print(json.dumps(row), flush=True)
        rows.append(row)

    by_d = {r["devices"]: r for r in rows}
    if len(by_d) >= 2:
        base = by_d[min(by_d)]
        top = by_d[max(by_d)]
        print(json.dumps({
            "metric": "mesh_scaling",
            "value": round(
                top["capacity_items_per_launch"]
                / base["capacity_items_per_launch"], 3
            ) if base["capacity_items_per_launch"] else 0.0,
            "unit": f"x per-launch capacity at D={top['devices']} vs "
                    f"D={base['devices']}",
            "devices": sorted(by_d),
            "tx_ratio": round(top["tx_per_sec"] / base["tx_per_sec"], 3)
            if base["tx_per_sec"] else 0.0,
            "items_per_launch_ratio": round(
                top["items_per_launch"] / base["items_per_launch"], 3
            ) if base["items_per_launch"] else 0.0,
            "launch_ratio": round(top["launches"] / base["launches"], 3)
            if base["launches"] else 0.0,
        }), flush=True)


if __name__ == "__main__":
    main()
