"""Mesh verify-plane sweep: one coalesced wave, N devices (ISSUE 10/11).

Fixed shard count S, devices swept over ``--devices`` (default 1,2,4,8):
each point runs a full S-shard cluster — routed front door, pipelined
windows, ONE shared coalescer — with the verify plane graduated onto a
D-device mesh through the REAL ``Configuration.verify_mesh_devices``
knob (``Consensus._wire_verify_plane`` → ``CryptoProvider.
configure_verify_mesh``), not a bench-only bypass.  Each engine carries
a fixed per-device lane budget, so aggregate per-launch CAPACITY scales
linearly with the mesh width — the economics that amortize the rig's
fixed ~220 ms launch overhead across all devices (PAPERS.md [7]).

ISSUE 11: every sweep point now runs TWICE at the same fixed workload —
an UNGATED control (``verify_flush_hold = 0``, the round-13 eager
contract) and a GATED run (occupancy-aware flush gating through the
real Configuration knob) — and the row carries both, so the
wave-deepening claim (gated fill > 90 % at D=8, strictly fewer
launches than the control) is measured, not asserted.  Client
submission is PACED (``--pace`` between decision rounds) so waves
arrive the way live traffic does — staggered — instead of as one
pre-loaded burst the eager window would accidentally coalesce anyway.

Stages, each printing JSON lines:

* **parity** — the same randomized mixed wave (several signers, forged
  items, counts that force pad slots) is verified through the
  single-device engine and through a MeshVerifyEngine at every swept
  device count; the row records whether every verdict vector matched
  bit-for-bit.  The tier-1 property test pins the same claim for P-256;
  the bench re-checks it for the crypto it actually runs.  A second
  ``mesh_parity_2d`` row makes the same bit-for-bit check through the
  seq×vote ``QuorumMeshVerifyEngine`` (the ``verify_mesh_topology =
  "2d"`` path, whose quorum counts psum across the 'vote' mesh axis).
* **sweep** — one ``{"bench": "mesh", "devices": D, ...}`` row per
  point (gated tx/s, launches, items/launch, capacity, fill, pad
  waste, mixed waves, the coalescer ``mesh`` block with its ``hold``
  decisions, plus the ungated control's launches/fill/tx) and a final
  ``mesh_scaling`` line comparing the top point against D=1.

Crypto: ``--crypto toy`` (default) is the real CryptoProvider stack over
``testing.toy_scheme`` — an array-math kernel that compiles in
milliseconds at EVERY mesh width, so the sweep runs anywhere (each
device count is a distinct mesh, hence a distinct XLA computation; the
P-256 bignum kernel costs minutes per mesh shape on a cold cache).
``--crypto p256`` runs the production curve for device rigs.

On CPU-only hosts the sweep self-provisions a virtual device mesh
exactly like the MULTICHIP harness (``force_cpu(virtual_devices=N)``);
with real accelerators present it uses them, dropping (and logging)
sweep points wider than the host.

Run:  python benchmarks/mesh.py [--devices 1,2,4,8] [--shards 2] [--cpu]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartbft_tpu.utils.jaxenv import force_cpu  # noqa: E402


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


#: per-sweep-point commit deadline (seconds); bench.py derives its
#: subprocess timeout from this so a stuck point degrades inside this
#: child (which salvages the other rows) instead of the parent killing
#: the whole mesh block (the PR 5/7/8 salvage lesson)
POINT_TIMEOUT = float(os.environ.get("SMARTBFT_BENCH_MESH_POINT_TIMEOUT",
                                     "120"))


def _scheme(crypto: str):
    if crypto == "toy":
        from smartbft_tpu.testing import toy_scheme

        return toy_scheme
    from smartbft_tpu.crypto import p256

    return p256


def _mixed_wave(scheme, n_signers: int = 3, count: int = 23,
                forge_every: int = 5, seed: bytes = b"mesh-parity"):
    """One mixed-tag wave: ``count`` items round-robined over
    ``n_signers`` distinct keys (the shard analog), every
    ``forge_every``-th signature corrupted.  ``count`` deliberately not a
    device multiple, so every mesh width exercises pad slots."""
    keys = [scheme.keygen(seed + b"-%d" % i) for i in range(n_signers)]
    items, expect = [], []
    for i in range(count):
        sk, pub = keys[i % n_signers]
        msg = b"mesh-msg-%d" % i
        sig = scheme.sign_raw(sk, msg)
        ok = i % forge_every != forge_every - 1
        if not ok:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        items.append(scheme.make_item(msg, sig, pub))
        expect.append(ok)
    return items, expect


def run_parity(device_counts: list[int], crypto: str) -> dict:
    """Bit-for-bit verdict parity: mesh engines at every device count
    against the single-device engine over the same mixed wave."""
    from smartbft_tpu.crypto.provider import JaxVerifyEngine
    from smartbft_tpu.parallel import MeshVerifyEngine

    scheme = _scheme(crypto)
    items, expect = _mixed_wave(scheme)
    single = JaxVerifyEngine(pad_sizes=(16, 64), scheme=scheme)
    base = single.verify(items)
    match = base == expect
    checked = []
    for d in device_counts:
        mesh = MeshVerifyEngine(devices=d, pad_sizes=(16, 64), scheme=scheme)
        got = mesh.verify(items)
        checked.append(d)
        if got != base:
            match = False
            _log(f"mesh parity: MISMATCH at devices={d}")
    return {
        "metric": "mesh_parity",
        "crypto": crypto,
        "devices_checked": checked,
        "items": len(items),
        "match": bool(match),
    }


def run_parity_2d(device_counts: list[int], crypto: str) -> dict:
    """The 2D (seq×vote) quorum-mesh parity row (ISSUE 11 tentpole b):
    the same mixed wave through ``QuorumMeshVerifyEngine`` at every
    even swept width must match the single-device engine bit for bit,
    and the psum'd per-message vote counts must equal the host tally of
    valid verdicts."""
    from smartbft_tpu.crypto.provider import JaxVerifyEngine
    from smartbft_tpu.parallel import QuorumMeshVerifyEngine, shard_map_available

    scheme = _scheme(crypto)
    if not shard_map_available():
        return {"metric": "mesh_parity_2d", "crypto": crypto,
                "devices_checked": [], "items": 0, "match": None,
                "counts_match": None, "note": "no shard_map in this build"}
    items, expect = _mixed_wave(scheme)
    base = JaxVerifyEngine(pad_sizes=(16, 64), scheme=scheme).verify(items)
    match = base == expect
    counts_match = True
    checked = []
    for d in device_counts:
        eng = QuorumMeshVerifyEngine(devices=d, scheme=scheme, quorum=3)
        got = eng.verify(items)
        checked.append(d)
        if got != base:
            match = False
            _log(f"mesh 2d parity: verdict MISMATCH at devices={d}")
        tally: dict = {}
        for it, ok in zip(items, got):
            tally[it[0]] = tally.get(it[0], 0) + (1 if ok else 0)
        if eng.last_counts != tally:
            counts_match = False
            _log(f"mesh 2d parity: psum count MISMATCH at devices={d}")
    return {
        "metric": "mesh_parity_2d",
        "crypto": crypto,
        "devices_checked": checked,
        "items": len(items),
        "match": bool(match),
        "counts_match": bool(counts_match),
    }


def build_cluster(tmp, devices: int, args, scheme, hold: float):
    """S-shard cluster whose verify plane graduates onto a
    ``devices``-wide mesh through the Configuration knob; ``hold``
    arms occupancy-aware flush gating through the REAL
    ``verify_flush_hold`` knob (0 = the ungated control)."""
    import dataclasses

    from smartbft_tpu.crypto.provider import JaxVerifyEngine
    from smartbft_tpu.testing.sharded import ShardedCluster, sharded_config

    per_dev = tuple(int(x) for x in args.per_device_lanes.split(",")
                    if x.strip())
    pad_sizes = tuple(l * devices for l in per_dev)

    def cfg(s, i):
        return dataclasses.replace(
            sharded_config(i, depth=args.pipeline),
            verify_mesh_devices=devices,
            verify_flush_hold=hold,
            wal_group_commit=True,
            request_batch_max_count=args.batch,
            request_batch_max_interval=0.02,
            request_pool_size=max(4 * args.decisions * args.batch, 800),
            incoming_message_buffer_size=max(2000, 40 * args.nodes),
            request_forward_timeout=300.0,
            request_complain_timeout=600.0,
            request_auto_remove_timeout=1200.0,
            view_change_resend_interval=300.0,
            view_change_timeout=1200.0,
            leader_heartbeat_timeout=900.0,
        )

    # the initial engine only donates its pad ladder: configure_verify_mesh
    # (wired from the knob at Consensus.start) swaps the coalescer onto the
    # MeshVerifyEngine with the SAME ladder — fixed lanes per device, so
    # capacity scales with the mesh width
    seed_engine = JaxVerifyEngine(pad_sizes=pad_sizes, scheme=scheme)
    return ShardedCluster(
        tmp, shards=args.shards, n=args.nodes, depth=args.pipeline,
        crypto=args.crypto, engine=seed_engine, window=args.window,
        config_fn=cfg, seed=17,
    )


async def _run_cluster_point(devices: int, args, hold: float) -> dict:
    """One fixed-workload cluster run at ``devices`` width with the
    given flush-hold knob; returns the raw measurement dict."""
    from smartbft_tpu.crypto.provider import (
        VerifyStats,
        prewarm_verify_engine,
    )
    from smartbft_tpu.utils.clock import WallClockDriver

    scheme = _scheme(args.crypto)
    requests_per_shard = args.decisions * args.batch
    tmp = tempfile.mkdtemp(prefix=f"bench-mesh-{devices}-")
    cluster = build_cluster(tmp, devices, args, scheme, hold)
    driver = WallClockDriver(cluster.scheduler, tick_interval=0.01)
    try:
        driver.start()
        await cluster.start()
        engine = cluster.coalescer.engine
        got_devices = int(getattr(engine, "devices", 0))
        if got_devices != devices:
            raise RuntimeError(
                f"knob wiring failed: wanted a {devices}-device mesh, "
                f"coalescer runs {type(engine).__name__} ({got_devices})"
            )
        if abs(cluster.coalescer.hold - hold) > 1e-9:
            raise RuntimeError(
                f"knob wiring failed: wanted verify_flush_hold={hold}, "
                f"coalescer holds {cluster.coalescer.hold}"
            )
        # pre-warm every mesh lane shape (persists into the compilation
        # cache — see enable_compile_cache) + probe the warm launch cost
        prewarm_verify_engine(engine, scheme)
        sk, pub = scheme.keygen(b"mesh-probe")
        item = scheme.make_item(b"p", scheme.sign_raw(sk, b"p"), pub)
        t0 = time.perf_counter()
        for _ in range(3):
            engine.verify([item])
        launch_probe_ms = 1e3 * (time.perf_counter() - t0) / 3
        engine.stats = type(engine.stats)(
            devices=got_devices, metrics=engine.stats.metrics
        ) if hasattr(engine.stats, "devices") else VerifyStats()

        for s in range(args.shards):
            cluster.client_for_shard(s, 3)
        t0 = time.perf_counter()
        # PACED submission: one decision round per pace interval, so
        # waves arrive staggered like live traffic (the eager window
        # would otherwise coalesce a pre-loaded burst by accident and
        # the gated-vs-ungated comparison would measure nothing)
        for j in range(args.decisions):
            for s in range(args.shards):
                for k in range(args.batch):
                    cid = cluster.client_for_shard(s, (j + k) % 4)
                    await cluster.submit(cid, f"m-{s}-{j}-{k}")
            if args.pace > 0:
                await asyncio.sleep(args.pace)
        deadline = time.perf_counter() + POINT_TIMEOUT
        while time.perf_counter() < deadline:
            if all(sh.committed() >= requests_per_shard
                   for sh in cluster.shard_list):
                break
            await asyncio.sleep(0.02)
        else:
            raise TimeoutError(
                f"devices={devices}: shards committed "
                f"{[sh.committed() for sh in cluster.shard_list]} "
                f"of {requests_per_shard} in time"
            )
        elapsed = time.perf_counter() - t0
        cluster.check_invariants()

        stats = engine.stats
        return {
            "hold_s": hold,
            "launch_probe_ms": round(launch_probe_ms, 2),
            "elapsed_s": round(elapsed, 2),
            "total": sum(sh.committed() for sh in cluster.shard_list),
            "decisions": sum(sh.height() for sh in cluster.shard_list),
            "launches": stats.launches,
            "items": stats.sigs_verified,
            "fill_pct": round(stats.batch_fill_pct, 1),
            "capacity": int(engine.pad_sizes[-1]),
            "mesh": cluster.coalescer.mesh_snapshot(),
            "mixed_waves": cluster.coalescer.shard_snapshot()["mixed_waves"],
        }
    finally:
        try:
            await cluster.stop()
        except Exception:
            pass
        await driver.stop()
        shutil.rmtree(tmp, ignore_errors=True)


async def run_sweep_point(devices: int, args) -> dict:
    """One devices-sweep row: the UNGATED control first (hold 0, the
    round-13 contract), then the GATED run at the same fixed workload.
    Gated numbers are the row's primary values; the control rides along
    as ``*_ungated`` so fill/launch deltas are in every row.  With
    ``--hold 0`` the two runs would be identical, so the control is
    reused instead of paying a second cluster for a no-op comparison."""
    control = await _run_cluster_point(devices, args, 0.0)
    gated = control if args.hold <= 0 \
        else await _run_cluster_point(devices, args, args.hold)
    mesh_block = gated["mesh"]
    return {
        "bench": "mesh",
        "devices": devices,
        "shards": args.shards,
        "crypto": args.crypto,
        "nodes_per_shard": args.nodes,
        "pipeline": args.pipeline,
        "decisions": gated["decisions"],
        "hold_s": args.hold,
        "pace_s": args.pace,
        "tx_per_sec": round(gated["total"] / gated["elapsed_s"], 1)
        if gated["elapsed_s"] else 0.0,
        "launches": gated["launches"],
        "items_per_launch":
            round(gated["items"] / gated["launches"], 1)
            if gated["launches"] else 0.0,
        "capacity_items_per_launch": gated["capacity"],
        "batch_fill_pct": gated["fill_pct"],
        "pad_waste_pct": mesh_block.get("pad_waste_pct", 0.0),
        "mixed_waves": gated["mixed_waves"],
        "launch_probe_ms": gated["launch_probe_ms"],
        "elapsed_s": gated["elapsed_s"],
        # the ungated control at the SAME fixed workload: the
        # wave-deepening deltas (fill up, launches strictly down)
        "launches_ungated": control["launches"],
        "batch_fill_ungated_pct": control["fill_pct"],
        "tx_per_sec_ungated": round(
            control["total"] / control["elapsed_s"], 1)
        if control["elapsed_s"] else 0.0,
        "mesh": mesh_block,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", default="1,2,4,8",
                    help="comma-separated mesh widths to sweep")
    ap.add_argument("--shards", type=int, default=2,
                    help="FIXED shard count S (the sweep varies devices)")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--decisions", type=int, default=12,
                    help="decisions committed per shard per point")
    ap.add_argument("--pipeline", type=int, default=8)
    ap.add_argument("--crypto", choices=("toy", "p256"), default="toy")
    ap.add_argument("--per-device-lanes", default="4,8,12,16",
                    help="pad-ladder lanes contributed by EACH device — "
                         "per-launch capacity = lanes x devices (a denser "
                         "ladder lets deepened waves land near a rung)")
    ap.add_argument("--window", type=float, default=0.02,
                    help="coalescer fan-in window (seconds)")
    ap.add_argument("--hold", type=float, default=0.25,
                    help="verify_flush_hold for the GATED run (seconds; "
                         "the ungated control always runs at 0)")
    ap.add_argument("--pace", type=float, default=0.03,
                    help="sleep between decision submission rounds — "
                         "staggers wave arrivals like live traffic")
    ap.add_argument("--cpu", action="store_true",
                    help="pin JAX to CPU and self-provision a virtual "
                         "device mesh (the MULTICHIP harness idiom)")
    args = ap.parse_args()

    sweep = [int(x) for x in args.devices.split(",") if x.strip()]
    if args.cpu or os.environ.get("SMARTBFT_BENCH_CPU") == "1":
        force_cpu(virtual_devices=max(sweep))
    else:
        # device rigs: persist compiled mesh shapes across bench
        # subprocesses (SMARTBFT_JAX_CACHE_DIR overrides the location) —
        # the 2-3 min per-process compile tax must not poison every row
        from smartbft_tpu.utils.jaxenv import enable_compile_cache

        enable_compile_cache()
    import jax

    avail = len(jax.devices())
    dropped = [d for d in sweep if d > avail]
    if dropped:
        # no silent caps: the sweep runs what fits and SAYS what it dropped
        _log(f"mesh: host has {avail} device(s); dropping sweep points "
             f"{dropped}")
        sweep = [d for d in sweep if d <= avail]
    if not sweep:
        _log("mesh: no sweep point fits this host")
        return

    try:
        print(json.dumps(run_parity(sweep, args.crypto)), flush=True)
    except Exception as exc:  # noqa: BLE001 — parity row is additive
        _log(f"mesh parity: FAILED — {exc!r}")
    try:
        # the 2D engine needs an even width for a real 'vote' axis
        two_d = [d for d in sweep if d % 2 == 0] or sweep
        print(json.dumps(run_parity_2d(two_d, args.crypto)), flush=True)
    except Exception as exc:  # noqa: BLE001 — parity row is additive
        _log(f"mesh 2d parity: FAILED — {exc!r}")

    rows = []
    for d in sweep:
        try:
            row = asyncio.run(run_sweep_point(d, args))
        except Exception as exc:  # noqa: BLE001 — a failed point costs
            # ITS slot only; the sweep still prints the other rows
            _log(f"mesh[{d}]: FAILED — {exc!r}")
            continue
        _log(f"mesh[{d}]: {row['tx_per_sec']} tx/s, {row['launches']} "
             f"launches (ungated {row['launches_ungated']}), "
             f"{row['items_per_launch']} items/launch "
             f"(capacity {row['capacity_items_per_launch']}), fill "
             f"{row['batch_fill_pct']}% (ungated "
             f"{row['batch_fill_ungated_pct']}%)")
        print(json.dumps(row), flush=True)
        rows.append(row)

    by_d = {r["devices"]: r for r in rows}
    if len(by_d) >= 2:
        base = by_d[min(by_d)]
        top = by_d[max(by_d)]
        print(json.dumps({
            "metric": "mesh_scaling",
            "value": round(
                top["capacity_items_per_launch"]
                / base["capacity_items_per_launch"], 3
            ) if base["capacity_items_per_launch"] else 0.0,
            "unit": f"x per-launch capacity at D={top['devices']} vs "
                    f"D={base['devices']}",
            "devices": sorted(by_d),
            "tx_ratio": round(top["tx_per_sec"] / base["tx_per_sec"], 3)
            if base["tx_per_sec"] else 0.0,
            "items_per_launch_ratio": round(
                top["items_per_launch"] / base["items_per_launch"], 3
            ) if base["items_per_launch"] else 0.0,
            "launch_ratio": round(top["launches"] / base["launches"], 3)
            if base["launches"] else 0.0,
        }), flush=True)


if __name__ == "__main__":
    main()
