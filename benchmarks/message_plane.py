"""Message-plane microbench: naive vs vectorized routing+codec cost.

Measures the protocol plane's fan-out terms in isolation — no crypto, no
WAL, no device — by driving synthetic prepare/commit/pre-prepare waves
through the REAL in-process network into vote-registering stub receivers:

* **naive** (``Network(naive=True)``): the pre-vectorization plane — one
  encode per recipient, one decode per delivery, per-message dispatch.
  This is what any transport pays without the encode-once/interned path.
* **vectorized**: encode-once broadcast (1 marshal per broadcast, memoized
  on the message), interned decode (<=1 unmarshal per broadcast, all
  recipients share one frozen object), wave-batched ingest (one dispatch
  call per drained inbox tick), bitmask vote registration.

One simulated decision = one pre-prepare broadcast from the leader (with a
batch-sized payload) + a full prepare wave + a full commit wave (n-1
broadcasts each).  The metric is microseconds of wall time per decision,
plus the PROTOCOL_PLANE counter deltas so the codec-call collapse
((n-1) -> 1 encodes per broadcast) is visible, not inferred.

Run:  python benchmarks/message_plane.py [--nodes 64] [--decisions 20]
      [--payload 25000]
Prints one JSON line per mode plus a comparison line with the ratio —
the "routing+codec cut" number PERF.md records.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartbft_tpu.messages import Commit, PrePrepare, Prepare, Proposal, Signature
from smartbft_tpu.metrics import PROTOCOL_PLANE, ProtocolPlaneTimers
from smartbft_tpu.core.util import SignerIndex, VoteSet
from smartbft_tpu.testing.network import Network


class _WaveSink:
    """Stub consensus: registers every vote into per-seq bitmask vote sets
    (the real registration data structure) and counts deliveries."""

    def __init__(self, node_id: int, index: SignerIndex):
        self.id = node_id
        self.index = index
        self.received = 0
        self.prepares: dict[int, VoteSet] = {}
        self.commits: dict[int, VoteSet] = {}

    def _register(self, sender: int, msg) -> None:
        self.received += 1
        if isinstance(msg, Prepare):
            vs = self.prepares.get(msg.seq)
            if vs is None:
                vs = self.prepares[msg.seq] = VoteSet(
                    lambda _s, m: isinstance(m, Prepare), self.index
                )
            vs.register_vote(sender, msg)
        elif isinstance(msg, Commit):
            vs = self.commits.get(msg.seq)
            if vs is None:
                vs = self.commits[msg.seq] = VoteSet(
                    lambda _s, m: isinstance(m, Commit), self.index
                )
            vs.register_vote(sender, msg)

    # naive / per-message intake
    def handle_message(self, sender: int, msg) -> None:
        self._register(sender, msg)

    # vectorized / wave-batched intake
    def handle_message_batch(self, items) -> None:
        for sender, msg in items:
            self._register(sender, msg)

    async def handle_request(self, sender: int, req: bytes) -> None:
        pass


async def run_mode(naive: bool, n: int, decisions: int,
                   payload_bytes: int) -> dict:
    network = Network(seed=7, naive=naive)
    index = SignerIndex(list(range(1, n + 1)))
    sinks = {}
    for i in range(1, n + 1):
        node = network.add_node(i)
        node.consensus = sinks[i] = _WaveSink(i, index)
    network.start()
    payload = bytes(payload_bytes)
    # expected deliveries per decision: pre-prepare to n-1, plus n prepare
    # and n commit broadcasts of n-1 recipients each
    per_decision = (n - 1) * (1 + 2 * n)
    before = PROTOCOL_PLANE.snapshot()
    t0 = time.perf_counter()
    for d in range(decisions):
        seq = d + 1
        pp = PrePrepare(view=0, seq=seq,
                        proposal=Proposal(payload=payload, metadata=b"m"))
        network.broadcast_consensus(1, pp)
        digest = "d%032d" % seq
        for i in range(1, n + 1):
            network.broadcast_consensus(i, Prepare(view=0, seq=seq, digest=digest))
        for i in range(1, n + 1):
            network.broadcast_consensus(
                i,
                Commit(view=0, seq=seq, digest=digest,
                       signature=Signature(signer=i, value=b"v", msg=b"m")),
            )
        # drain before the next decision so inboxes stay inside their bound
        target = per_decision * (d + 1)  # total deliveries across all sinks
        while sum(s.received for s in sinks.values()) < target:
            await asyncio.sleep(0)
    elapsed = time.perf_counter() - t0
    plane = ProtocolPlaneTimers.delta(before, PROTOCOL_PLANE.snapshot())
    await network.stop()
    # sanity: every wave fully registered
    got = sum(s.received for s in sinks.values())
    assert got == per_decision * decisions, (got, per_decision * decisions)
    return {
        "mode": "naive" if naive else "vectorized",
        "nodes": n,
        "decisions": decisions,
        "payload_bytes": payload_bytes,
        "us_per_decision": round(1e6 * elapsed / decisions, 1),
        "elapsed_s": round(elapsed, 3),
        "protocol_plane": plane,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=64)
    ap.add_argument("--decisions", type=int, default=20)
    ap.add_argument("--payload", type=int, default=25000,
                    help="pre-prepare proposal payload size (bytes); the "
                         "default is a ~500-request batch's worth")
    args = ap.parse_args()

    rows = []
    for naive in (True, False):
        row = asyncio.run(
            run_mode(naive, args.nodes, args.decisions, args.payload)
        )
        print(json.dumps(row), flush=True)
        rows.append(row)
    naive_row, vec_row = rows
    print(json.dumps({
        "metric": f"message_plane_us_per_decision_n{args.nodes}",
        "value": vec_row["us_per_decision"],
        "unit": "us/decision",
        "vs_naive": round(
            naive_row["us_per_decision"] / vec_row["us_per_decision"], 3
        ) if vec_row["us_per_decision"] else 0.0,
        "naive_us_per_decision": naive_row["us_per_decision"],
        "encodes_per_broadcast": {
            "naive": round(naive_row["protocol_plane"]["encodes"]
                           / naive_row["protocol_plane"]["broadcasts"], 2),
            "vectorized": round(vec_row["protocol_plane"]["encodes"]
                                / vec_row["protocol_plane"]["broadcasts"], 2),
        },
    }), flush=True)


if __name__ == "__main__":
    main()
