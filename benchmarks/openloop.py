"""Open-loop service-level load bench: tail latency, not burst throughput.

Round-12 contract (ROADMAP item 4): a service serving millions of users
is judged on p99 under SUSTAINED open-loop arrivals — Poisson gaps at a
configured offered load, Zipf-skewed client keys (hot shards), requests
arriving whether or not the system keeps up.  This bench measures that
directly against the sharded front door:

* **saturation sweep** (``--rates``): one fresh cluster per offered
  load, pumped open-loop for ``--duration`` seconds; each JSON row
  carries offered vs goodput, the submit→commit latency percentiles
  (fixed-bucket log-scale histograms, bounded memory), shed counts from
  the admission gate, and the peak pool occupancy.  A final
  ``open_loop_knee`` line locates the knee: the last offered load that
  still met the SLO (goodput ≥ 90% of offered, shed < 1%) and the first
  that did not.

* **degraded-mode SLOs** (``--degraded``, default on): ONE cluster at a
  fixed offered load rides healthy → verify-engine outage (the breaker
  trips, waves verify on the host fallback) → heal → forced view change
  (leader muted mid-load) → live reshard (S -> S+1 epoch transition
  under the pump) → recovered, with the latency tracker's phase windows
  attributing p50/p95/p99 + shed counts to each degraded mode.  These
  are the numbers PERF.md round 12 reports — measured, not asserted.

Everything runs the REAL stack: routed ShardSet front door, per-shard
consensus groups, shared verify plane (trivial-crypto coalescer — the
system under test here is the front door and protocol plane, not the
signature kernels), WallClockDriver-paced schedulers.

Run:  python benchmarks/openloop.py [--rates 200,400,800,1600]
      [--duration 8] [--shards 2] [--nodes 4] [--degraded-rate 300]
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartbft_tpu.utils.jaxenv import force_cpu  # noqa: E402


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


#: per-phase salvage deadline (seconds) for waits that should be quick
#: (breaker open/close, leader re-election, drain); bench.py derives its
#: subprocess timeout from the sweep/phase counts and THIS constant so a
#: stuck wait degrades one point, not the whole row
PHASE_TIMEOUT = float(os.environ.get("SMARTBFT_BENCH_OPENLOOP_PHASE_TIMEOUT",
                                     "60"))


def openloop_config(pool_size: int, batch: int, admission: float):
    """Per-node configuration for open-loop runs: production-shaped pool
    + admission knobs, view-change machinery tight enough that a forced
    view change completes inside a measured phase."""
    from smartbft_tpu.testing.sharded import sharded_config

    def cfg(s, i):
        return dataclasses.replace(
            sharded_config(i, depth=2),
            wal_group_commit=True,
            request_pool_size=pool_size,
            admission_high_water=admission,
            request_pool_submit_timeout=1.0,
            request_batch_max_count=batch,
            request_batch_max_interval=0.02,
            # a request pooled on a non-leader (mid-view-change intake)
            # must reach the leader well inside the reshard drain
            # deadline, or a moved key-range cannot finish draining
            request_forward_timeout=5.0,
            request_complain_timeout=15.0,
            request_auto_remove_timeout=240.0,
            leader_heartbeat_timeout=3.0,
            leader_heartbeat_count=10,
            # adaptive failover (ISSUE 15): the complain timer derives
            # from the commit inter-arrival EWMA (~10x the measured
            # cadence, the 3 s constant as ceiling), so the forced-VC
            # phase's detection lands sub-second; the flip drain is on
            # by default (flip_drain_windows)
            heartbeat_rtt_multiplier=10.0,
            view_change_timeout=12.0,
            view_change_resend_interval=3.0,
            verify_launch_timeout=0.15,
            verify_launch_retries=2,
            verify_breaker_threshold=3,
            verify_probe_interval=0.05,
        )

    return cfg


def build_cluster(tmp: str, args, *, engine_faults: bool = False,
                  trace: bool = False, trace_capacity: int = 4096):
    from smartbft_tpu.testing.sharded import ShardedCluster

    return ShardedCluster(
        tmp, shards=args.shards, n=args.nodes, depth=2, crypto="trivial",
        engine_faults=engine_faults, window=0.005, seed=17,
        config_fn=openloop_config(args.pool_size, args.batch,
                                  args.admission),
        trace=trace, trace_capacity=trace_capacity,
    )


async def _wait_wall(cond, timeout: float, step: float = 0.02) -> bool:
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            return False
        await asyncio.sleep(step)
    return True


async def run_sweep_point(rate: float, args) -> dict:
    """One offered-load point: fresh cluster, open-loop pump, one row."""
    from smartbft_tpu.testing.load import ZipfClients, run_open_loop
    from smartbft_tpu.utils.clock import WallClockDriver

    tmp = tempfile.mkdtemp(prefix=f"bench-openloop-{int(rate)}-")
    cluster = build_cluster(tmp, args)
    driver = WallClockDriver(cluster.scheduler, tick_interval=0.005)
    zipf = ZipfClients(args.clients, skew=args.zipf)
    try:
        driver.start()
        await cluster.start()
        # the goodput window closes when arrivals stop; commits landing in
        # the drain tail are real but must not pad the in-window rate
        window_committed = {"n": None}
        t_end = cluster.scheduler.now() + args.duration

        def on_tick(now: float) -> None:
            if window_committed["n"] is None and now >= t_end:
                window_committed["n"] = cluster.set.committed_requests()

        stats = await run_open_loop(
            cluster, rate=rate, duration=args.duration, clients=zipf,
            seed=31, wall=True, step=0.005, drain=args.drain,
            on_tick=on_tick,
        )
        committed = cluster.set.committed_requests()
        in_window = window_committed["n"]
        in_window = committed if in_window is None else in_window
        lat = cluster.set.latency.snapshot()
        row = {
            "bench": "openloop",
            "offered_per_sec": rate,
            "duration_s": args.duration,
            "shards": args.shards,
            "nodes_per_shard": args.nodes,
            "clients": args.clients,
            "zipf_skew": args.zipf,
            "hot_client_share": round(zipf.hot_fraction(1), 3),
            "pool_size": args.pool_size,
            "admission_high_water": args.admission,
            "goodput_per_sec": round(in_window / args.duration, 1),
            "committed_total": committed,
            "open_loop": stats.block(),
            "latency": lat,
        }
        _log(f"openloop[{rate:g}/s]: goodput {row['goodput_per_sec']}/s "
             f"shed {stats.shed}/{stats.offered} "
             f"p99 {lat['p99_ms']}ms peak_occ {stats.peak_occupancy}")
        return row
    finally:
        try:
            await cluster.stop()
        except Exception:
            pass
        await driver.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def find_knee(rows: list) -> dict:
    """The saturation knee from sweep rows: the last offered load meeting
    the SLO (goodput >= 90% of offered AND shed < 1%) and the first that
    misses it.  With no overloaded point the knee is beyond the sweep."""
    ok, overloaded = [], []
    for r in rows:
        offered = r["offered_per_sec"]
        meets = (r["goodput_per_sec"] >= 0.9 * offered
                 and r["open_loop"]["shed_rate"] < 0.01)
        (ok if meets else overloaded).append(r)
    knee = {
        "slo": "goodput >= 0.9*offered and shed < 1%",
        "last_ok": None,
        "first_overloaded": None,
        "beyond_sweep": not overloaded,
    }
    if ok:
        best = max(ok, key=lambda r: r["offered_per_sec"])
        knee["last_ok"] = {
            "offered_per_sec": best["offered_per_sec"],
            "goodput_per_sec": best["goodput_per_sec"],
            "p99_ms": best["latency"]["p99_ms"],
        }
    if overloaded:
        first = min(overloaded, key=lambda r: r["offered_per_sec"])
        knee["first_overloaded"] = {
            "offered_per_sec": first["offered_per_sec"],
            "goodput_per_sec": first["goodput_per_sec"],
            "p99_ms": first["latency"]["p99_ms"],
            "shed_rate": first["open_loop"]["shed_rate"],
        }
    return knee


async def run_degraded(args) -> dict:
    """Fixed offered load through every degraded mode, ONE live cluster.

    healthy -> breaker_open (engine hang; host fallback serves) -> heal
    -> view_change (leader muted mid-load; the shard deposes it) ->
    reshard (S -> S+1 live epoch transition) -> recovered.  Returns the
    per-phase p50/p95/p99 + shed table (the PERF.md round-12 numbers)."""
    from smartbft_tpu.testing.load import ZipfClients, run_open_loop
    from smartbft_tpu.utils.clock import WallClockDriver
    from smartbft_tpu.utils.tasks import create_logged_task

    rate = args.degraded_rate
    span = args.phase_duration
    tmp = tempfile.mkdtemp(prefix="bench-openloop-degraded-")
    # tracing ON (the round-15 contract): the flight recorder rides the
    # whole degraded run, and the per-phase VC decomposition comes out in
    # the row's `viewchange` block — the scheduler is wall-driven here,
    # so span durations are real seconds
    # deep rings (16k/recorder): the critical-path decomposition joins a
    # request's submit with its deliver — both must survive the run
    cluster = build_cluster(tmp, args, engine_faults=True, trace=True,
                            trace_capacity=16384)
    # the transition's bounded drain shares the per-phase salvage budget
    # (same convention as benchmarks/sharded.py's live resize)
    cluster.set.drain_deadline = PHASE_TIMEOUT
    driver = WallClockDriver(cluster.scheduler, tick_interval=0.005)
    zipf = ZipfClients(args.clients, skew=args.zipf)
    tracker = cluster.set.latency
    notes: dict = {}
    health_task = None
    try:
        driver.start()
        await cluster.start()

        # continuous SLO evaluation (ISSUE 14): the cluster monitor ticks
        # on the wall-driven scheduler throughout the degraded walk, so
        # the row carries the verdict TRANSITIONS (healthy -> degraded
        # with the breaching SLO named -> healthy) next to the phases
        # that caused them
        async def health_loop() -> None:
            while True:
                try:
                    cluster.health.tick()
                except Exception:  # noqa: BLE001 — judged, never judging
                    pass
                await asyncio.sleep(0.1)

        health_task = create_logged_task(health_loop(),
                                         name="openloop-health")

        async def quiesce_stamps() -> bool:
            """Wait until every stamped request has committed (polling the
            mux) — a fault injected with commits still outstanding would
            attribute ITS latency to the phase that admitted them."""
            return await _wait_wall(
                lambda: (cluster.poll(), tracker.pending() == 0)[-1],
                PHASE_TIMEOUT,
            )

        async def phase(name: str, *, seed: int, drain: float = 0.0):
            tracker.begin_phase(name)
            stats = await run_open_loop(
                cluster, rate=rate, duration=span, clients=zipf,
                seed=seed, wall=True, step=0.005, drain=drain,
                request_prefix=name,
            )
            notes[name] = stats.block()
            _log(f"degraded[{name}]: acked {stats.acked}/{stats.offered} "
                 f"shed {stats.shed}")
            return stats

        await phase("healthy", seed=41)
        await quiesce_stamps()

        # -- breaker open: the verify device hangs; deadline -> retries ->
        # breaker -> host fallback, all under sustained load.  The breaker
        # only trips on LAUNCHES, and launches only happen under traffic —
        # so the hang is armed first and the trip happens inside the
        # pumped phase (verified from the fault snapshot afterwards).
        cluster.engine.hang()
        await phase("breaker_open", seed=42)
        await quiesce_stamps()  # outage-window commits stay in THIS phase
        opened = cluster.coalescer.fault_snapshot()["opens"] >= 1
        cluster.engine.heal()
        closed = await _wait_wall(
            lambda: not cluster.coalescer.breaker_open, PHASE_TIMEOUT
        )
        notes["breaker"] = dict(cluster.coalescer.fault_snapshot(),
                                opened_in_time=opened,
                                closed_in_time=closed)

        # -- forced view change: mute shard 0's leader mid-load; its group
        # deposes it and elects a successor while the pump keeps arriving
        sh = cluster.shard_list[0]
        old_leader = sh.mute_leader()
        tracker.begin_phase("view_change")
        vc_task = create_logged_task(
            run_open_loop(cluster, rate=rate, duration=span, clients=zipf,
                          seed=43, wall=True, step=0.005,
                          request_prefix="view_change"),
            name="openloop-vc-pump",
        )
        deposed = await _wait_wall(
            lambda: sh.leader_id() not in (0, old_leader), PHASE_TIMEOUT
        )
        stats = await vc_task
        notes["view_change"] = dict(stats.block(), old_leader=old_leader,
                                    new_leader=sh.leader_id(),
                                    deposed_in_time=deposed)
        sh.unmute(old_leader)
        _log(f"degraded[view_change]: leader {old_leader} -> "
             f"{sh.leader_id()} shed {stats.shed}")
        # quiesce before the reshard phase: the deposed ex-leader may still
        # believe it leads (its request timers then do nothing — "I am the
        # leader"), and requests it absorbed would wedge the moved-range
        # drain until its sync catches up.  Wait for every live replica to
        # agree on the leader and for the shard's pools to flush.
        agreed = await _wait_wall(
            lambda: len({a.consensus.get_leader_id()
                         for a in sh.live_apps() if a.consensus}) == 1
            and sh.leader_id() not in (0, old_leader),
            PHASE_TIMEOUT,
        )
        flushed = await _wait_wall(
            lambda: (cluster.poll(), not sh.pending_client_ids())[-1],
            PHASE_TIMEOUT,
        )
        notes["view_change"]["quiesced"] = agreed and flushed

        # -- live reshard: S -> S+1 epoch transition inside the phase
        tracker.begin_phase("reshard")
        pump_task = create_logged_task(
            run_open_loop(cluster, rate=rate, duration=span, clients=zipf,
                          seed=44, wall=True, step=0.005,
                          request_prefix="reshard"),
            name="openloop-reshard-pump",
        )
        await asyncio.sleep(span * 0.2)
        try:
            summary = await cluster.reshard(args.shards + 1)
            notes["reshard_transition"] = {
                k: summary[k] for k in ("epoch", "old", "new",
                                        "moved_fraction", "drain_ms",
                                        "paused_submit_ms",
                                        "parked_submits_peak")
            }
        except Exception as exc:  # noqa: BLE001 — a failed transition is
            # itself a measurement; the pump and later phases continue
            notes["reshard_transition"] = {"failed": repr(exc)}
        stats = await pump_task
        notes["reshard"] = stats.block()

        await phase("recovered", seed=45, drain=args.drain)
        tracker.end_phase()

        snap = tracker.snapshot()
        # the ISSUE-12 observability blocks: measured VC sub-phase
        # decomposition (pure assemble over every replica's tracker) and
        # the merged flight-recorder summary
        viewchange = cluster.viewchange_block()
        trace = cluster.trace_block()
        # ISSUE 13: the per-request critical-path decomposition over the
        # merged timeline, grouped by the phase prefix each request key
        # carries — names the dominant segment of the degraded phases
        critical = cluster.critical_path_block(
            phases=["healthy", "breaker_open", "view_change", "reshard",
                    "recovered"],
        )
        return {
            "metric": "open_loop_degraded",
            "offered_per_sec": rate,
            "phase_duration_s": span,
            "shards": args.shards,
            "phases": snap.get("phases", {}),
            "notes": notes,
            "viewchange": viewchange,
            "trace": trace,
            "critical_path": critical,
            # ISSUE 14: the continuous verdict over the whole degraded
            # walk — final state + every transition with its SLO names
            "health": {
                "final": cluster.health.verdict(),
                "transitions": cluster.health.transition_log(),
            },
            "latency": snap,
        }
    finally:
        if health_task is not None:
            health_task.cancel()
        try:
            await cluster.stop()
        except Exception:
            pass
        await driver.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rates", default="200,400,800,1600",
                    help="comma-separated offered loads (req/s) to sweep")
    ap.add_argument("--duration", type=float, default=8.0,
                    help="seconds of offered load per sweep point")
    ap.add_argument("--drain", type=float, default=3.0,
                    help="post-arrival drain window per point")
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--nodes", type=int, default=4, help="replicas per shard")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--pool-size", type=int, default=200)
    ap.add_argument("--admission", type=float, default=0.8,
                    help="admission_high_water fraction (1.0 disables)")
    ap.add_argument("--clients", type=int, default=512,
                    help="Zipf client universe size")
    ap.add_argument("--zipf", type=float, default=1.1, help="Zipf skew s")
    ap.add_argument("--degraded-rate", type=float, default=300.0,
                    help="fixed offered load for the degraded-phase run")
    ap.add_argument("--phase-duration", type=float, default=6.0)
    ap.add_argument("--no-degraded", action="store_true",
                    help="skip the degraded-mode phase run")
    ap.add_argument("--cpu", action="store_true",
                    help="pin JAX to the CPU backend")
    args = ap.parse_args()

    if args.cpu or os.environ.get("SMARTBFT_BENCH_CPU") == "1":
        force_cpu()

    rows = []
    for rate in [float(x) for x in args.rates.split(",") if x.strip()]:
        try:
            row = asyncio.run(run_sweep_point(rate, args))
            print(json.dumps(row), flush=True)
            rows.append(row)
        except Exception as exc:  # noqa: BLE001 — a stuck point costs its
            # slot only; the sweep and the knee degrade to fewer points
            _log(f"openloop[{rate:g}/s]: FAILED — {exc!r}")
    if rows:
        print(json.dumps({"metric": "open_loop_knee", **find_knee(rows)}),
              flush=True)

    if not args.no_degraded:
        try:
            print(json.dumps(asyncio.run(run_degraded(args))), flush=True)
        except Exception as exc:  # noqa: BLE001 — degraded row is additive
            _log(f"openloop degraded run: FAILED — {exc!r}")


if __name__ == "__main__":
    main()
