"""Open-loop service-level load bench: tail latency, not burst throughput.

Round-12 contract (ROADMAP item 4): a service serving millions of users
is judged on p99 under SUSTAINED open-loop arrivals — Poisson gaps at a
configured offered load, Zipf-skewed client keys (hot shards), requests
arriving whether or not the system keeps up.  This bench measures that
directly against the sharded front door:

* **saturation sweep** (``--rates``): one fresh cluster per offered
  load, pumped open-loop for ``--duration`` seconds; each JSON row
  carries offered vs goodput, the submit→commit latency percentiles
  (fixed-bucket log-scale histograms, bounded memory), shed counts from
  the admission gate, and the peak pool occupancy.  A final
  ``open_loop_knee`` line locates the knee: the last offered load that
  still met the SLO (goodput ≥ 90% of offered, shed < 1%) and the first
  that did not.

* **degraded-mode SLOs** (``--degraded``, default on): ONE cluster at a
  fixed offered load rides healthy → verify-engine outage (the breaker
  trips, waves verify on the host fallback) → heal → forced view change
  (leader muted mid-load) → live reshard (S -> S+1 epoch transition
  under the pump) → recovered, with the latency tracker's phase windows
  attributing p50/p95/p99 + shed counts to each degraded mode.  These
  are the numbers PERF.md round 12 reports — measured, not asserted.

Everything runs the REAL stack: routed ShardSet front door, per-shard
consensus groups, shared verify plane (trivial-crypto coalescer — the
system under test here is the front door and protocol plane, not the
signature kernels), WallClockDriver-paced schedulers.

Run:  python benchmarks/openloop.py [--rates 200,400,800,1600]
      [--duration 8] [--shards 2] [--nodes 4] [--degraded-rate 300]
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartbft_tpu.utils.jaxenv import force_cpu  # noqa: E402


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


#: per-phase salvage deadline (seconds) for waits that should be quick
#: (breaker open/close, leader re-election, drain); bench.py derives its
#: subprocess timeout from the sweep/phase counts and THIS constant so a
#: stuck wait degrades one point, not the whole row
PHASE_TIMEOUT = float(os.environ.get("SMARTBFT_BENCH_OPENLOOP_PHASE_TIMEOUT",
                                     "60"))


def openloop_config(pool_size: int, batch: int, admission: float,
                    adaptive: bool = False):
    """Per-node configuration for open-loop runs: production-shaped pool
    + admission knobs, view-change machinery tight enough that a forced
    view change completes inside a measured phase."""
    from smartbft_tpu.testing.sharded import sharded_config

    def cfg(s, i):
        return dataclasses.replace(
            sharded_config(i, depth=2),
            wal_group_commit=True,
            request_pool_size=pool_size,
            admission_high_water=admission,
            request_pool_submit_timeout=1.0,
            request_batch_max_count=batch,
            request_batch_max_interval=0.02,
            # arrival-driven proposing (ISSUE 16): the leader proposes as
            # soon as the arrival EWMA says the wave cannot fill inside
            # the cadence, so `request_batch_max_interval` is the
            # ACCUMULATION CAP under load, not a per-wave latency tax at
            # low load — deep `batch` caps and low-load latency stop
            # being a tradeoff
            request_batch_adaptive=adaptive,
            # a request pooled on a non-leader (mid-view-change intake)
            # must reach the leader well inside the reshard drain
            # deadline, or a moved key-range cannot finish draining
            request_forward_timeout=5.0,
            request_complain_timeout=15.0,
            request_auto_remove_timeout=240.0,
            leader_heartbeat_timeout=3.0,
            leader_heartbeat_count=10,
            # adaptive failover (ISSUE 15): the complain timer derives
            # from the commit inter-arrival EWMA (a multiple of the
            # measured cadence, the 3 s constant as ceiling), so the
            # forced-VC phase's detection lands sub-second; the flip
            # drain is on by default (flip_drain_windows).  20x rather
            # than the product-default 10x: every replica of every shard
            # shares ONE core here, so scheduling jitter near saturation
            # rivals a 10x-the-commit-gap timer and fires spurious view
            # changes mid-measurement (round 18)
            heartbeat_rtt_multiplier=20.0,
            view_change_timeout=12.0,
            view_change_resend_interval=3.0,
            verify_launch_timeout=0.15,
            verify_launch_retries=2,
            verify_breaker_threshold=3,
            verify_probe_interval=0.05,
        )

    return cfg


def build_cluster(tmp: str, args, *, engine_faults: bool = False,
                  trace: bool = False, trace_capacity: int = 4096):
    from smartbft_tpu.testing.sharded import ShardedCluster

    return ShardedCluster(
        tmp, shards=args.shards, n=args.nodes, depth=2, crypto="trivial",
        engine_faults=engine_faults, window=0.005, seed=17,
        config_fn=openloop_config(args.pool_size, args.batch,
                                  args.admission,
                                  adaptive=not args.no_adaptive),
        trace=trace, trace_capacity=trace_capacity,
    )


def cluster_rtt_s_max(cluster) -> float:
    """The worst measured transport RTT across live replicas — 0.0 on the
    in-process loopback Network (no wire, no sampler), the REAL envelope
    once a socket transport rides this bench.  Recorded per row so the
    ROADMAP's WAN-profile work inherits an honest field instead of a
    number that silently meant 'never measured'."""
    worst = 0.0
    for sh in cluster.shard_list:
        for a in sh.live_apps():
            comm = getattr(getattr(a, "consensus", None), "comm", None)
            rtt_fn = getattr(comm, "rtt_seconds", None)
            if rtt_fn is None:
                continue
            try:
                rtt = rtt_fn()
            except Exception:  # noqa: BLE001 — observability, never fatal
                continue
            if rtt is not None and rtt > worst:
                worst = rtt
    return round(worst, 6)


async def _wait_wall(cond, timeout: float, step: float = 0.02) -> bool:
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            return False
        await asyncio.sleep(step)
    return True


async def run_sweep_point(rate: float, args, *, prefix: str = "ol",
                          export_hist: bool = False) -> dict:
    """One offered-load point: fresh cluster, open-loop pump, one row.

    ``prefix`` namespaces request ids (affinity-sweep workers each pump a
    private 1-shard cluster, and the merged row must not alias their
    ids); ``export_hist`` adds the raw latency-histogram state to the row
    so the parent merges EXACT bucket sums, not percentiles."""
    from smartbft_tpu.testing.load import ZipfClients, run_open_loop
    from smartbft_tpu.utils.clock import WallClockDriver

    tmp = tempfile.mkdtemp(prefix=f"bench-openloop-{int(rate)}-")
    cluster = build_cluster(tmp, args)
    driver = WallClockDriver(cluster.scheduler, tick_interval=0.005)
    zipf = ZipfClients(args.clients, skew=args.zipf)
    try:
        driver.start()
        await cluster.start()
        # the goodput window closes when arrivals stop; commits landing in
        # the drain tail are real but must not pad the in-window rate
        window_committed = {"n": None}
        t_end = cluster.scheduler.now() + args.duration

        def on_tick(now: float) -> None:
            if window_committed["n"] is None and now >= t_end:
                window_committed["n"] = cluster.set.committed_requests()

        stats = await run_open_loop(
            cluster, rate=rate, duration=args.duration, clients=zipf,
            seed=31, wall=True, step=0.005, drain=args.drain,
            on_tick=on_tick, request_prefix=prefix,
        )
        committed = cluster.set.committed_requests()
        in_window = window_committed["n"]
        in_window = committed if in_window is None else in_window
        lat = cluster.set.latency.snapshot()
        row = {
            "bench": "openloop",
            "offered_per_sec": rate,
            "duration_s": args.duration,
            "shards": args.shards,
            "nodes_per_shard": args.nodes,
            "clients": args.clients,
            "zipf_skew": args.zipf,
            "hot_client_share": round(zipf.hot_fraction(1), 3),
            "pool_size": args.pool_size,
            "admission_high_water": args.admission,
            "batch_max": args.batch,
            "adaptive_batching": not args.no_adaptive,
            # self-describing rows (ISSUE 16 bench hygiene): which loop
            # topology served this point, and the honest RTT envelope
            "loop_affinity": args.affinity,
            "rtt_s_max": cluster_rtt_s_max(cluster),
            "goodput_per_sec": round(in_window / args.duration, 1),
            "committed_total": committed,
            "open_loop": stats.block(),
            "latency": lat,
        }
        if export_hist:
            row["lat_hist"] = cluster.set.latency.aggregate.export_state()
        _log(f"openloop[{rate:g}/s]: goodput {row['goodput_per_sec']}/s "
             f"shed {stats.shed}/{stats.offered} "
             f"p99 {lat['p99_ms']}ms peak_occ {stats.peak_occupancy}")
        return row
    finally:
        try:
            await cluster.stop()
        except Exception:
            pass
        await driver.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def find_knee(rows: list) -> dict:
    """The saturation knee from sweep rows: the last offered load meeting
    the SLO (goodput >= 90% of offered AND shed < 1%) and the first that
    misses it.  With no overloaded point the knee is beyond the sweep."""
    ok, overloaded = [], []
    for r in rows:
        offered = r["offered_per_sec"]
        meets = (r["goodput_per_sec"] >= 0.9 * offered
                 and r["open_loop"]["shed_rate"] < 0.01)
        (ok if meets else overloaded).append(r)
    knee = {
        "slo": "goodput >= 0.9*offered and shed < 1%",
        "last_ok": None,
        "first_overloaded": None,
        "beyond_sweep": not overloaded,
    }
    if ok:
        best = max(ok, key=lambda r: r["offered_per_sec"])
        knee["last_ok"] = {
            "offered_per_sec": best["offered_per_sec"],
            "goodput_per_sec": best["goodput_per_sec"],
            "p99_ms": best["latency"]["p99_ms"],
        }
    if overloaded:
        first = min(overloaded, key=lambda r: r["offered_per_sec"])
        knee["first_overloaded"] = {
            "offered_per_sec": first["offered_per_sec"],
            "goodput_per_sec": first["goodput_per_sec"],
            "p99_ms": first["latency"]["p99_ms"],
            "shed_rate": first["open_loop"]["shed_rate"],
        }
    return knee


def merge_worker_rows(rows: list, rate: float, shards: int, args) -> dict:
    """Fold S per-process worker rows (one 1-shard cluster each) into the
    ONE merged affinity-sweep row.  Counters sum, peaks take the max, and
    the latency percentiles come from the exact bucket-wise histogram
    merge of the workers' exported raw state — never a
    percentile-of-percentiles."""
    from smartbft_tpu.metrics import LogScaleHistogram

    hist = LogScaleHistogram()
    for r in rows:
        if r.get("lat_hist"):
            hist.merge_from(LogScaleHistogram.from_state(r["lat_hist"]))
    ol = {
        "offered": sum(r["open_loop"]["offered"] for r in rows),
        "acked": sum(r["open_loop"]["acked"] for r in rows),
        "shed_admission": sum(r["open_loop"]["shed_admission"]
                              for r in rows),
        "shed_timeout": sum(r["open_loop"]["shed_timeout"] for r in rows),
        "failed": sum(r["open_loop"]["failed"] for r in rows),
        "peak_occupancy": max(r["open_loop"]["peak_occupancy"]
                              for r in rows),
        "peak_fill": max(r["open_loop"]["peak_fill"] for r in rows),
        "retry_after_p50": None,
    }
    shed = ol["shed_admission"] + ol["shed_timeout"]
    ol["shed_rate"] = round(shed / ol["offered"], 4) if ol["offered"] else 0.0
    lat = hist.snapshot()
    # the latency snapshot a single-cluster row carries also has shed
    # counters riding it; keep the merged row shape-compatible
    lat["shed"] = {"admission": ol["shed_admission"],
                   "timeout": ol["shed_timeout"], "other": ol["failed"]}
    return {
        "bench": "openloop_affinity",
        "offered_per_sec": rate,
        "duration_s": args.duration,
        "shards": shards,
        "nodes_per_shard": args.nodes,
        "clients": sum(r["clients"] for r in rows),
        "zipf_skew": args.zipf,
        "pool_size": args.pool_size,
        "admission_high_water": args.admission,
        "batch_max": args.batch,
        "adaptive_batching": not args.no_adaptive,
        "loop_affinity": "process",
        "rtt_s_max": max(r.get("rtt_s_max", 0.0) for r in rows),
        "goodput_per_sec": round(sum(r["goodput_per_sec"] for r in rows), 1),
        "committed_total": sum(r["committed_total"] for r in rows),
        "open_loop": ol,
        "latency": lat,
        "workers": [
            {"offered_per_sec": r["offered_per_sec"],
             "goodput_per_sec": r["goodput_per_sec"],
             "p99_ms": r["latency"]["p99_ms"],
             "shed_rate": r["open_loop"]["shed_rate"]}
            for r in rows
        ],
    }


def run_affinity_point(rate: float, shards: int, args) -> dict:
    """One affinity-sweep point: S concurrent WORKER PROCESSES, each a
    private 1-shard cluster (own interpreter, own event loop — the
    per-shard loop affinity the shared-scheduler ShardedCluster cannot
    give) serving 1/S of the offered load over a disjoint client slice.
    The parent merges the S rows into one."""
    import subprocess

    here = os.path.abspath(__file__)
    per_rate = rate / shards
    per_clients = max(1, args.clients // shards)
    procs = []
    for k in range(shards):
        cmd = [sys.executable, here, "--worker",
               "--worker-prefix", f"w{k}",
               "--rates", f"{per_rate:g}",
               "--shards", "1", "--nodes", str(args.nodes),
               "--duration", str(args.duration), "--drain", str(args.drain),
               "--batch", str(args.batch),
               "--pool-size", str(args.pool_size),
               "--admission", str(args.admission),
               "--clients", str(per_clients), "--zipf", str(args.zipf),
               "--affinity", "process", "--no-degraded", "--cpu"]
        if args.no_adaptive:
            cmd.append("--no-adaptive")
        procs.append(subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        ))
    deadline = time.monotonic() + args.duration + args.drain + PHASE_TIMEOUT
    rows = []
    for p in procs:
        budget = max(1.0, deadline - time.monotonic())
        try:
            out, _ = p.communicate(timeout=budget)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()
            continue
        if p.returncode != 0:
            continue
        for line in out.decode().splitlines():
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    if not rows:
        raise RuntimeError(
            f"affinity point S={shards} rate={rate:g}: every worker failed")
    if len(rows) < shards:
        _log(f"affinity[S={shards} {rate:g}/s]: only {len(rows)}/{shards} "
             f"workers survived — merged row covers the survivors' load")
    return merge_worker_rows(rows, rate, shards, args)


def run_affinity_sweep(args) -> None:
    """The ISSUE 16 S∈{4,8,16} loop-affinity sweep: for each shard count,
    sweep the offered loads with process-per-shard workers and locate
    the per-S knee.  Emits one merged row per point plus one
    ``open_loop_affinity_knee`` line per S."""
    shard_counts = [int(x) for x in args.sweep_shards.split(",")
                    if x.strip()]
    rates = [float(x) for x in args.rates.split(",") if x.strip()]
    for s in shard_counts:
        rows = []
        for rate in rates:
            try:
                row = run_affinity_point(rate, s, args)
            except Exception as exc:  # noqa: BLE001 — a stuck point costs
                _log(f"affinity[S={s} {rate:g}/s]: FAILED — {exc!r}")
                continue  # its slot; the per-S knee degrades gracefully
            print(json.dumps(row), flush=True)
            rows.append(row)
            _log(f"affinity[S={s} {rate:g}/s]: goodput "
                 f"{row['goodput_per_sec']}/s shed "
                 f"{row['open_loop']['shed_rate']} "
                 f"p99 {row['latency']['p99_ms']}ms")
        if rows:
            print(json.dumps({
                "metric": "open_loop_affinity_knee", "shards": s,
                "loop_affinity": "process", **find_knee(rows),
            }), flush=True)


async def run_degraded(args) -> dict:
    """Fixed offered load through every degraded mode, ONE live cluster.

    healthy -> breaker_open (engine hang; host fallback serves) -> heal
    -> view_change (leader muted mid-load; the shard deposes it) ->
    reshard (S -> S+1 live epoch transition) -> recovered.  Returns the
    per-phase p50/p95/p99 + shed table (the PERF.md round-12 numbers)."""
    from smartbft_tpu.testing.load import ZipfClients, run_open_loop
    from smartbft_tpu.utils.clock import WallClockDriver
    from smartbft_tpu.utils.tasks import create_logged_task

    rate = args.degraded_rate
    span = args.phase_duration
    tmp = tempfile.mkdtemp(prefix="bench-openloop-degraded-")
    # tracing ON (the round-15 contract): the flight recorder rides the
    # whole degraded run, and the per-phase VC decomposition comes out in
    # the row's `viewchange` block — the scheduler is wall-driven here,
    # so span durations are real seconds
    # deep rings (64k/recorder): the critical-path decomposition joins a
    # request's submit with its deliver — both must survive the WHOLE
    # five-phase walk (16k retained only the last ~5k requests, silently
    # dropping the healthy phase from the per-phase critpath block)
    cluster = build_cluster(tmp, args, engine_faults=True, trace=True,
                            trace_capacity=65536)
    # the transition's bounded drain shares the per-phase salvage budget
    # (same convention as benchmarks/sharded.py's live resize)
    cluster.set.drain_deadline = PHASE_TIMEOUT
    driver = WallClockDriver(cluster.scheduler, tick_interval=0.005)
    zipf = ZipfClients(args.clients, skew=args.zipf)
    tracker = cluster.set.latency
    notes: dict = {}
    health_task = None
    try:
        driver.start()
        await cluster.start()

        # continuous SLO evaluation (ISSUE 14): the cluster monitor ticks
        # on the wall-driven scheduler throughout the degraded walk, so
        # the row carries the verdict TRANSITIONS (healthy -> degraded
        # with the breaching SLO named -> healthy) next to the phases
        # that caused them
        async def health_loop() -> None:
            while True:
                try:
                    cluster.health.tick()
                except Exception:  # noqa: BLE001 — judged, never judging
                    pass
                await asyncio.sleep(0.1)

        health_task = create_logged_task(health_loop(),
                                         name="openloop-health")

        async def quiesce_stamps() -> bool:
            """Wait until every stamped request has committed (polling the
            mux) — a fault injected with commits still outstanding would
            attribute ITS latency to the phase that admitted them."""
            return await _wait_wall(
                lambda: (cluster.poll(), tracker.pending() == 0)[-1],
                PHASE_TIMEOUT,
            )

        async def phase(name: str, *, seed: int, drain: float = 0.0):
            tracker.begin_phase(name)
            stats = await run_open_loop(
                cluster, rate=rate, duration=span, clients=zipf,
                seed=seed, wall=True, step=0.005, drain=drain,
                request_prefix=name,
            )
            notes[name] = stats.block()
            _log(f"degraded[{name}]: acked {stats.acked}/{stats.offered} "
                 f"shed {stats.shed}")
            return stats

        await phase("healthy", seed=41)
        await quiesce_stamps()

        # -- breaker open: the verify device hangs; deadline -> retries ->
        # breaker -> host fallback, all under sustained load.  The breaker
        # only trips on LAUNCHES, and launches only happen under traffic —
        # so the hang is armed first and the trip happens inside the
        # pumped phase (verified from the fault snapshot afterwards).
        cluster.engine.hang()
        await phase("breaker_open", seed=42)
        await quiesce_stamps()  # outage-window commits stay in THIS phase
        opened = cluster.coalescer.fault_snapshot()["opens"] >= 1
        cluster.engine.heal()
        closed = await _wait_wall(
            lambda: not cluster.coalescer.breaker_open, PHASE_TIMEOUT
        )
        notes["breaker"] = dict(cluster.coalescer.fault_snapshot(),
                                opened_in_time=opened,
                                closed_in_time=closed)

        # -- forced view change: mute shard 0's leader mid-load; its group
        # deposes it and elects a successor while the pump keeps arriving
        sh = cluster.shard_list[0]
        old_leader = sh.mute_leader()
        tracker.begin_phase("view_change")
        vc_task = create_logged_task(
            run_open_loop(cluster, rate=rate, duration=span, clients=zipf,
                          seed=43, wall=True, step=0.005,
                          request_prefix="view_change"),
            name="openloop-vc-pump",
        )
        deposed = await _wait_wall(
            lambda: sh.leader_id() not in (0, old_leader), PHASE_TIMEOUT
        )
        stats = await vc_task
        notes["view_change"] = dict(stats.block(), old_leader=old_leader,
                                    new_leader=sh.leader_id(),
                                    deposed_in_time=deposed)
        sh.unmute(old_leader)
        _log(f"degraded[view_change]: leader {old_leader} -> "
             f"{sh.leader_id()} shed {stats.shed}")
        # quiesce before the reshard phase: the deposed ex-leader may still
        # believe it leads (its request timers then do nothing — "I am the
        # leader"), and requests it absorbed would wedge the moved-range
        # drain until its sync catches up.  Wait for every live replica to
        # agree on the leader and for the shard's pools to flush.
        agreed = await _wait_wall(
            lambda: len({a.consensus.get_leader_id()
                         for a in sh.live_apps() if a.consensus}) == 1
            and sh.leader_id() not in (0, old_leader),
            PHASE_TIMEOUT,
        )
        flushed = await _wait_wall(
            lambda: (cluster.poll(), not sh.pending_client_ids())[-1],
            PHASE_TIMEOUT,
        )
        notes["view_change"]["quiesced"] = agreed and flushed

        # -- live reshard: S -> S+1 epoch transition inside the phase
        tracker.begin_phase("reshard")
        pump_task = create_logged_task(
            run_open_loop(cluster, rate=rate, duration=span, clients=zipf,
                          seed=44, wall=True, step=0.005,
                          request_prefix="reshard"),
            name="openloop-reshard-pump",
        )
        await asyncio.sleep(span * 0.2)
        try:
            summary = await cluster.reshard(args.shards + 1)
            notes["reshard_transition"] = {
                k: summary[k] for k in ("epoch", "old", "new",
                                        "moved_fraction", "drain_ms",
                                        "paused_submit_ms",
                                        "parked_submits_peak")
            }
        except Exception as exc:  # noqa: BLE001 — a failed transition is
            # itself a measurement; the pump and later phases continue
            notes["reshard_transition"] = {"failed": repr(exc)}
        stats = await pump_task
        notes["reshard"] = stats.block()

        await phase("recovered", seed=45, drain=args.drain)
        tracker.end_phase()

        snap = tracker.snapshot()
        # the ISSUE-12 observability blocks: measured VC sub-phase
        # decomposition (pure assemble over every replica's tracker) and
        # the merged flight-recorder summary
        viewchange = cluster.viewchange_block()
        trace = cluster.trace_block()
        # ISSUE 13: the per-request critical-path decomposition over the
        # merged timeline, grouped by the phase prefix each request key
        # carries — names the dominant segment of the degraded phases
        critical = cluster.critical_path_block(
            phases=["healthy", "breaker_open", "view_change", "reshard",
                    "recovered"],
        )
        return {
            "metric": "open_loop_degraded",
            "offered_per_sec": rate,
            "phase_duration_s": span,
            "shards": args.shards,
            "phases": snap.get("phases", {}),
            "notes": notes,
            "viewchange": viewchange,
            "trace": trace,
            "critical_path": critical,
            # ISSUE 14: the continuous verdict over the whole degraded
            # walk — final state + every transition with its SLO names
            "health": {
                "final": cluster.health.verdict(),
                "transitions": cluster.health.transition_log(),
            },
            "latency": snap,
        }
    finally:
        if health_task is not None:
            health_task.cancel()
        try:
            await cluster.stop()
        except Exception:
            pass
        await driver.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rates", default="200,400,800,1600",
                    help="comma-separated offered loads (req/s) to sweep")
    ap.add_argument("--duration", type=float, default=8.0,
                    help="seconds of offered load per sweep point")
    ap.add_argument("--drain", type=float, default=3.0,
                    help="post-arrival drain window per point")
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--nodes", type=int, default=4, help="replicas per shard")
    # round-18 defaults: deep waves (the adaptive proposer keeps low-load
    # latency flat, so the cap can sit where throughput wants it) and a
    # pool sized so admission, not slot scarcity, is the shed authority
    # at the post-round-18 knee
    ap.add_argument("--batch", type=int, default=128)
    # 2400 (round 18): at the 8-9k/s knee a view-change or GC burst
    # backlogs ~0.3s of arrivals; an 800-slot pool shed those bursts
    # straight through the admission gate and poisoned otherwise-healthy
    # rows, while 2400 rides them out (reported per row as pool_size)
    ap.add_argument("--pool-size", type=int, default=2400)
    ap.add_argument("--admission", type=float, default=0.8,
                    help="admission_high_water fraction (1.0 disables)")
    ap.add_argument("--clients", type=int, default=512,
                    help="Zipf client universe size")
    ap.add_argument("--zipf", type=float, default=1.1, help="Zipf skew s")
    ap.add_argument("--degraded-rate", type=float, default=300.0,
                    help="fixed offered load for the degraded-phase run")
    ap.add_argument("--phase-duration", type=float, default=6.0)
    ap.add_argument("--no-degraded", action="store_true",
                    help="skip the degraded-mode phase run")
    ap.add_argument("--no-adaptive", action="store_true",
                    help="disable arrival-driven proposing (fixed-cadence "
                         "waves, the pre-round-18 behavior)")
    ap.add_argument("--affinity", choices=("shared", "process"),
                    default="shared",
                    help="loop topology label stamped on rows: 'shared' = "
                         "all shards on one scheduler/loop (ShardedCluster)"
                         ", 'process' = one interpreter per shard")
    ap.add_argument("--sweep-shards", default="",
                    help="comma-separated shard counts (e.g. 4,8,16): "
                         "additionally sweep --rates with process-per-"
                         "shard workers and emit merged affinity rows + a "
                         "per-S knee line")
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)  # internal: one sweep point,
    # 1-shard cluster, row + raw histogram on stdout (affinity workers)
    ap.add_argument("--worker-prefix", default="ol",
                    help=argparse.SUPPRESS)
    ap.add_argument("--cpu", action="store_true",
                    help="pin JAX to the CPU backend")
    args = ap.parse_args()

    # measurement hygiene: INFO/DEBUG records cost ~20µs each THROUGH the
    # disabled-handler path (makeRecord + callHandlers), and the replicas
    # emit them per request — at bench rates that is whole CPU-seconds of
    # logging inside the measured window.  WARNING+ (overload, failover)
    # still reaches stderr.
    import logging as _pylogging

    _pylogging.disable(_pylogging.INFO)

    if args.cpu or os.environ.get("SMARTBFT_BENCH_CPU") == "1":
        force_cpu()

    if args.worker:
        rate = float(args.rates.split(",")[0])
        row = asyncio.run(run_sweep_point(
            rate, args, prefix=args.worker_prefix, export_hist=True))
        print(json.dumps(row), flush=True)
        return

    rows = []
    for rate in [float(x) for x in args.rates.split(",") if x.strip()]:
        try:
            row = asyncio.run(run_sweep_point(rate, args))
            print(json.dumps(row), flush=True)
            rows.append(row)
        except Exception as exc:  # noqa: BLE001 — a stuck point costs its
            # slot only; the sweep and the knee degrade to fewer points
            _log(f"openloop[{rate:g}/s]: FAILED — {exc!r}")
    if rows:
        print(json.dumps({"metric": "open_loop_knee", **find_knee(rows)}),
              flush=True)

    if args.sweep_shards:
        run_affinity_sweep(args)

    if not args.no_degraded:
        try:
            print(json.dumps(asyncio.run(run_degraded(args))), flush=True)
        except Exception as exc:  # noqa: BLE001 — degraded row is additive
            _log(f"openloop degraded run: FAILED — {exc!r}")


if __name__ == "__main__":
    main()
