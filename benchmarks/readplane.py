"""Read-plane bench (ISSUE 19): replica-scaled reads that never touch
consensus, measured against the REAL socket cluster.

Three claims under test, each against live ``smartbft_tpu.net.launch``
replica processes over UDS on this host:

* **reads are cheap because they skip consensus** — the mixed 95/5
  phase interleaves quorum reads (``cmd=read mode=quorum``: the control
  edge fans the key to every peer over FT_READ_REQ and applies the f+1
  match rule) with writes (``cmd=submit`` + poll-until-committed, the
  full three-phase protocol) through the SAME cluster under the SAME
  load, and reports both wall-clock p99s side by side.  The pinned
  contrast is the read p99 staying far under the write p99: a read
  costs fan-out RTTs, never a consensus round.

* **read capacity scales with n** — a local read touches ONLY its
  serving replica (no peer frames, no proposer, no verify launch), so
  cluster read capacity is n x the per-replica service rate.  The
  scaling phase measures that per-replica rate on an n=4 and an n=8
  cluster and emits aggregate large/small with the per-replica rates
  alongside: a flat-with-n service rate is the isolation invariant the
  guard actually pins.  On a multi-core host the aggregate is realized
  parallelism; on a 1-core rig (this one) it is capacity aggregation
  under that measured invariant — same honesty rule as the S=16
  affinity knee note in the committed baseline.

* **a read storm degrades reads, never writes** — the storm phase
  blasts local reads at one replica well past its token-bucket gate
  (``read_gate_rate``) while a writer keeps submitting through the full
  path; the row records sheds > 0 on the read side and every storm
  write committed.

Output: one ``read_p99_ms`` row and one ``read_scaling_vs_n`` row as
JSON lines through the pure assemble functions pinned in
``smartbft_tpu.obs.benchschema``.

Run:  python benchmarks/readplane.py [--reads 190] [--writes 10]
      [--scale-nodes 4,8] [--storm-reads 600]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartbft_tpu.net.cluster import SocketCluster  # noqa: E402
from smartbft_tpu.obs.benchschema import (  # noqa: E402
    assemble_read_row,
    assemble_read_scaling_row,
)

#: per-replica sustained read gate for the mixed+storm cluster: far above
#: what the sequential mixed loop offers, far below what the storm's
#: hammering threads reach — so the SAME cluster serves both phases
GATE_RATE = 400.0
GATE_BURST = 64


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _p99(samples_ms: list) -> float:
    if not samples_ms:
        return 0.0
    ordered = sorted(samples_ms)
    return ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]


def _seed_keys(cluster: SocketCluster, keys: int, payload: bytes) -> None:
    """Commit one write per key so every replica's committed KV has the
    keys the read phases will hammer."""
    lead = cluster.wait_leader()
    for k in range(1, keys + 1):
        cluster.submit(lead, f"rd-c{k}", f"seed-{k}", payload)
    cluster.wait_committed(keys, timeout=60.0)


def _timed_write(cluster: SocketCluster, via: int, client: str, rid: str,
                 payload: bytes, *, timeout: float = 30.0) -> float:
    """One full-path write: submit, poll the same replica until its
    committed request count moves past it.  Returns wall ms."""
    before = cluster.committed(via)
    t0 = time.perf_counter()
    cluster.submit(via, client, rid, payload)
    deadline = t0 + timeout
    while cluster.committed(via) <= before:
        if time.perf_counter() > deadline:
            raise TimeoutError(f"write {rid} not committed within {timeout}s")
        time.sleep(0.001)
    return (time.perf_counter() - t0) * 1000.0


def mixed_phase(cluster: SocketCluster, *, reads: int, writes: int,
                keys: int, payload: bytes) -> dict:
    """The 95/5 loop: quorum reads round-robin across entry replicas,
    writes through the leader, every op timed wall-clock.  Also probes
    the local and follower fast paths for their own p99s."""
    lead = cluster.wait_leader()
    ids = cluster.live_ids()
    read_ms: list = []
    write_ms: list = []
    sheds = 0
    per_write = max(1, reads // max(1, writes))
    w = 0
    for i in range(reads):
        via = ids[i % len(ids)]
        key = f"rd-c{1 + i % keys}"
        t0 = time.perf_counter()
        resp = cluster.control(via).call(cmd="read", key=key, mode="quorum",
                                         max_lag=8)
        read_ms.append((time.perf_counter() - t0) * 1000.0)
        if resp.get("shed"):
            sheds += 1
        elif not resp.get("quorum"):
            raise RuntimeError(f"quorum read lost quorum: {resp}")
        if (i + 1) % per_write == 0 and w < writes:
            w += 1
            write_ms.append(_timed_write(cluster, lead, f"rd-c{1 + w % keys}",
                                         f"mix-{w}", payload))
    while w < writes:
        w += 1
        write_ms.append(_timed_write(cluster, lead, f"rd-c{1 + w % keys}",
                                     f"mix-{w}", payload))
    local_ms: list = []
    follower_ms: list = []
    probes = max(32, reads // 4)
    for i in range(probes):
        via = ids[i % len(ids)]
        key = f"rd-c{1 + i % keys}"
        t0 = time.perf_counter()
        cluster.control(via).call(cmd="read", key=key, mode="local")
        local_ms.append((time.perf_counter() - t0) * 1000.0)
        t0 = time.perf_counter()
        cluster.control(via).call(cmd="read", key=key, mode="follower",
                                  max_lag=128)
        follower_ms.append((time.perf_counter() - t0) * 1000.0)
    return {
        "read_p99_ms": _p99(read_ms),
        "write_p99_ms": _p99(write_ms),
        "local_p99_ms": _p99(local_ms),
        "follower_p99_ms": _p99(follower_ms),
        "reads": len(read_ms),
        "writes": len(write_ms),
        "sheds": sheds,
    }


def storm_phase(cluster: SocketCluster, *, storm_reads: int, hammers: int,
                storm_writes: int, payload: bytes) -> dict:
    """Blast local reads at ONE replica past its gate from ``hammers``
    threads while a writer pushes full-path writes: the isolation
    contract is sheds land on reads, every write still commits."""
    target = cluster.live_ids()[0]
    lead = cluster.wait_leader()
    counts = {"served": 0, "shed": 0}
    lock = threading.Lock()
    per_thread = max(1, storm_reads // hammers)

    def hammer(tid: int) -> None:
        served = shed = 0
        for i in range(per_thread):
            resp = cluster.control(target).call(
                cmd="read", key="rd-c1", mode="local")
            if resp.get("shed"):
                shed += 1
            else:
                served += 1
        with lock:
            counts["served"] += served
            counts["shed"] += shed

    committed = {"writes": 0}

    def writer() -> None:
        for k in range(storm_writes):
            _timed_write(cluster, lead, "rd-storm", f"storm-{k}", payload)
            committed["writes"] += 1

    t0 = time.perf_counter()
    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(hammers)]
    threads.append(threading.Thread(target=writer))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    offered = counts["served"] + counts["shed"]
    return {
        "offered": offered,
        "offered_per_sec": round(offered / elapsed, 1) if elapsed else 0.0,
        "sheds": counts["shed"],
        "writes_submitted": storm_writes,
        "writes_committed": committed["writes"],
        "gate_rate": GATE_RATE,
    }


def per_replica_read_rate(cluster: SocketCluster, *, burst: int,
                          keys: int, sample_replicas: int = 2) -> float:
    """Mean local-read service rate (reads/s) over ``sample_replicas``
    replicas, ``burst`` timed reads each — the quantity that must stay
    flat as n grows for the aggregate-capacity claim to hold."""
    rates = []
    for via in cluster.live_ids()[:sample_replicas]:
        t0 = time.perf_counter()
        for i in range(burst):
            cluster.control(via).call(cmd="read", key=f"rd-c{1 + i % keys}",
                                      mode="local")
        elapsed = time.perf_counter() - t0
        rates.append(burst / elapsed)
    return sum(rates) / len(rates)


def scaling_point(n: int, *, burst: int, keys: int, payload: bytes) -> float:
    """One fresh ungated n-replica cluster: seed, measure the
    per-replica local-read service rate, tear down."""
    root = tempfile.mkdtemp(prefix=f"readbench-n{n}-")
    cluster = SocketCluster(root, n=n, config_overrides={
        "read_gate_rate": 0.0,  # scaling measures service rate, not the gate
    })
    try:
        cluster.start(ready_timeout=120.0)
        _seed_keys(cluster, keys, payload)
        return per_replica_read_rate(cluster, burst=burst, keys=keys)
    finally:
        cluster.stop()
        shutil.rmtree(root, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4,
                    help="mixed/storm cluster size (default 4)")
    ap.add_argument("--reads", type=int, default=190,
                    help="mixed-phase quorum reads (default 190 — with "
                         "--writes 10 that is the 95/5 mix)")
    ap.add_argument("--writes", type=int, default=10)
    ap.add_argument("--keys", type=int, default=8,
                    help="distinct seeded client keys the reads hit")
    ap.add_argument("--payload", type=int, default=64)
    ap.add_argument("--scale-nodes", default="4,8",
                    help="small,large cluster sizes for the scaling row "
                         "('' skips the scaling phase)")
    ap.add_argument("--scale-burst", type=int, default=250,
                    help="timed local reads per sampled replica")
    ap.add_argument("--storm-reads", type=int, default=600)
    ap.add_argument("--storm-threads", type=int, default=4)
    ap.add_argument("--storm-writes", type=int, default=5)
    args = ap.parse_args()
    payload = b"r" * args.payload

    root = tempfile.mkdtemp(prefix="readbench-")
    cluster = SocketCluster(root, n=args.nodes, config_overrides={
        "read_gate_rate": GATE_RATE, "read_gate_burst": GATE_BURST,
    })
    try:
        _log(f"readplane: starting n={args.nodes} mixed/storm cluster")
        cluster.start(ready_timeout=120.0)
        _seed_keys(cluster, args.keys, payload)
        mixed = mixed_phase(cluster, reads=args.reads, writes=args.writes,
                            keys=args.keys, payload=payload)
        _log(f"readplane: mixed 95/5 done — read p99 "
             f"{mixed['read_p99_ms']:.1f}ms vs write p99 "
             f"{mixed['write_p99_ms']:.1f}ms")
        storm = storm_phase(cluster, storm_reads=args.storm_reads,
                            hammers=args.storm_threads,
                            storm_writes=args.storm_writes, payload=payload)
        _log(f"readplane: storm done — {storm['sheds']}/{storm['offered']} "
             f"reads shed at {storm['offered_per_sec']}/s offered, "
             f"{storm['writes_committed']}/{storm['writes_submitted']} "
             f"writes committed")
        if storm["sheds"] <= 0:
            raise RuntimeError(
                f"storm never tripped the read gate ({storm}) — the "
                f"isolation claim was not exercised"
            )
        if storm["writes_committed"] != storm["writes_submitted"]:
            raise RuntimeError(f"storm starved writes: {storm}")
        stats = cluster.control(cluster.live_ids()[0]).call(cmd="stats")
        read_block = stats.get("read") or {}
        # pooled control-channel economics (ISSUE 20): every probe above
        # rode the persistent per-replica connection — reuse_fraction
        # near 1.0 is the pin that the bench itself is not paying a
        # connect per call
        chan = cluster.control_stats()
        read_block["control_channel"] = chan
        _log(f"readplane: control channel {chan['calls']} calls over "
             f"{chan['connects']} connects "
             f"(reuse {chan['reuse_fraction']:.3f}, "
             f"{chan['reconnects']} reconnects)")
    finally:
        cluster.stop()
        shutil.rmtree(root, ignore_errors=True)

    print(json.dumps(assemble_read_row(
        read_p99_ms=mixed["read_p99_ms"], write_p99_ms=mixed["write_p99_ms"],
        nodes=args.nodes, reads=mixed["reads"], writes=mixed["writes"],
        mode="quorum", local_p99_ms=mixed["local_p99_ms"],
        follower_p99_ms=mixed["follower_p99_ms"],
        read_sheds=mixed["sheds"], storm=storm, read_stats=read_block,
    )), flush=True)

    if args.scale_nodes:
        small_n, large_n = (int(x) for x in args.scale_nodes.split(","))
        rate_small = scaling_point(small_n, burst=args.scale_burst,
                                   keys=args.keys, payload=payload)
        _log(f"readplane: n={small_n} per-replica rate {rate_small:.0f}/s")
        rate_large = scaling_point(large_n, burst=args.scale_burst,
                                   keys=args.keys, payload=payload)
        _log(f"readplane: n={large_n} per-replica rate {rate_large:.0f}/s")
        print(json.dumps(assemble_read_scaling_row(
            per_replica_rate_small=rate_small,
            per_replica_rate_large=rate_large,
            nodes_small=small_n, nodes_large=large_n,
        )), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
