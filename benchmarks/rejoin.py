"""Rejoin bench (ISSUE 17): O(1) snapshot install vs O(depth) replay.

The claim under test: a replica rejoining a cluster whose history is
10^5 decisions deep should pay roughly what a replica rejoining a 10^2
cluster pays — because it installs a verified snapshot (bounded app
state + anchor certificate) and replays only the post-horizon tail,
instead of re-verifying and re-applying the whole chain.  The control
row is the same rejoin with snapshots disabled: full chain replay,
paged at ``MAX_SYNC_DECISIONS`` like the real sync path, which is
honestly O(depth).

The bench drives the REAL durable components end to end — the framed
:class:`~smartbft_tpu.net.launch.LedgerFile` (append, compact,
recovery), the crash-safe :class:`~smartbft_tpu.snapshot.SnapshotStore`,
``parse_snapshot_blob``/``verify_snapshot`` (the exact install-time
verification the socket replica runs, anchor certificate included) and
``verify_tail`` with the full quorum check per paged batch — but feeds
them a synthesized committed history instead of running live consensus,
so a 10^5-deep donor builds in seconds and the measured section is
purely the JOINER's work:

* snapshot mode: chunked fetch of the donor's snapshot file (the
  FT_SNAP chunk size), structural parse, anchor verification, crash-safe
  install (store save + ledger compact-to-base), then tail verify +
  replay past the horizon;
* replay mode: page the donor's chain in ``MAX_SYNC_DECISIONS`` batches,
  re-encode/decode each frame (the serving + receiving codec work),
  verify continuity AND certificates, append every decision.

Both modes finish by asserting the joiner's chained ledger digest and
chained request-id digest are BIT-IDENTICAL to the donor's — a rejoin
that arrived at a different state would be a wrong answer computed
quickly.

Output: one JSON line per (history, mode) through the pure
``assemble_rejoin_row`` pinned in ``smartbft_tpu.obs.benchschema``.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartbft_tpu.codec import decode, encode  # noqa: E402
from smartbft_tpu.core.util import compute_quorum  # noqa: E402
from smartbft_tpu.messages import Proposal, Signature, ViewMetadata  # noqa: E402
from smartbft_tpu.net.framing import WireDecision, encode_frame  # noqa: E402
from smartbft_tpu.net.framing import FT_SYNC_RESP as _FT_LEDGER  # noqa: E402
from smartbft_tpu.net.launch import LedgerFile  # noqa: E402
from smartbft_tpu.net.transport import MAX_SYNC_DECISIONS  # noqa: E402
from smartbft_tpu.obs.benchschema import assemble_rejoin_row  # noqa: E402
from smartbft_tpu.snapshot import (  # noqa: E402
    CHAIN_SEED,
    RECENT_IDS_CAP,
    AppState,
    SnapshotStore,
    chain_update,
    encode_snapshot_blob,
    fold_ids,
    make_manifest,
    parse_snapshot_blob,
    verify_snapshot,
    verify_tail,
)
from smartbft_tpu.testing.app import BatchPayload, TestRequest  # noqa: E402

#: cluster shape the synthesized certificates model (n=4 -> quorum 3),
#: matching the socket smoke cluster the live rejoin harness drives
NODES = (1, 2, 3, 4)


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


class DonorHistory:
    """A synthesized committed chain of ``depth`` decisions with real
    certificates: one request per decision, quorum signatures, chained
    ledger + request-id digests tracked at every height."""

    def __init__(self, depth: int, payload_bytes: int):
        quorum, _f = compute_quorum(len(NODES))
        filler = b"x" * payload_bytes
        self.depth = depth
        self.wire: list[WireDecision] = []
        self.frames: list[bytes] = []
        self.rids: list[str] = []
        #: chain digest AT each height: chains[h] covers decisions 1..h
        self.chains: list[bytes] = [CHAIN_SEED]
        self.ids_digest = CHAIN_SEED
        chain = CHAIN_SEED
        for seq in range(1, depth + 1):
            rid = f"bench:j-{seq}"
            req = encode(TestRequest(client_id="bench",
                                     request_id=f"j-{seq}", payload=filler))
            proposal = Proposal(
                header=b"",
                payload=encode(BatchPayload(requests=[req])),
                metadata=encode(ViewMetadata(view_id=0, latest_sequence=seq)),
            )
            sigs = [Signature(signer=i, value=b"sig-%d" % i, msg=b"")
                    for i in NODES[:quorum]]
            wd = WireDecision(proposal=proposal, signatures=sigs)
            self.wire.append(wd)
            self.frames.append(encode_frame(_FT_LEDGER, encode(wd)))
            self.rids.append(rid)
            chain = chain_update(chain, proposal.payload, proposal.metadata)
            self.chains.append(chain)
            self.ids_digest = fold_ids(self.ids_digest, [rid])

    def snapshot_blob(self, height: int) -> bytes:
        """The donor's snapshot file image at ``height`` (what the
        FT_SNAP chunk plane would serve), anchor certificate included."""
        state = AppState(
            request_count=height,
            ids_digest=fold_ids(CHAIN_SEED, self.rids[:height]),
            recent_ids=self.rids[max(0, height - RECENT_IDS_CAP):height],
        )
        blob = encode(state)
        anchor = self.wire[height - 1]
        manifest = make_manifest(height, self.chains[height], blob,
                                 anchor.proposal, list(anchor.signatures))
        return encode_snapshot_blob(manifest, blob)


class Joiner:
    """The rejoining replica's durable state: a fresh LedgerFile +
    SnapshotStore in its own directory, plus the in-memory chain/ids
    digests a live replica folds on every deliver."""

    def __init__(self, root: str):
        os.makedirs(root, exist_ok=True)
        self.ledger = LedgerFile(os.path.join(root, "ledger.bin"))
        self.ledger.read_all()
        self.ledger.open_append()
        self.store = SnapshotStore(os.path.join(root, "snapshots"))
        self.height = 0
        self.chain = CHAIN_SEED
        self.ids_digest = CHAIN_SEED

    def apply(self, wd: WireDecision, rid: str) -> None:
        from smartbft_tpu.types import Decision

        self.ledger.append(Decision(proposal=wd.proposal,
                                    signatures=tuple(wd.signatures)))
        self.chain = chain_update(self.chain, wd.proposal.payload,
                                  wd.proposal.metadata)
        self.ids_digest = fold_ids(self.ids_digest, [rid])
        self.height += 1

    def close(self) -> None:
        self.ledger.close()


def _fetch_chunked(path: str, chunk_bytes: int) -> tuple[bytes, int]:
    """Read a snapshot file the way the FT_SNAP plane ships it: bounded
    chunks off the file, reassembled by the receiver.  Returns
    (blob, chunk_count)."""
    parts = []
    chunks = 0
    with open(path, "rb") as fh:
        while True:
            data = fh.read(chunk_bytes)
            if not data:
                break
            parts.append(data)
            chunks += 1
    return b"".join(parts), chunks


def rejoin_snapshot(donor: DonorHistory, snap_path: str, tail_from: int,
                    root: str, chunk_bytes: int) -> dict:
    """One timed snapshot-mode rejoin; returns the measurement dict."""
    quorum, _f = compute_quorum(len(NODES))
    members = frozenset(NODES)
    joiner = Joiner(root)
    t0 = time.perf_counter()
    # 1. chunked fetch + structural parse (torn/tamper detection)
    blob, chunks = _fetch_chunked(snap_path, chunk_bytes)
    parsed = parse_snapshot_blob(blob)
    assert parsed is not None, "donor snapshot failed structural parse"
    manifest, state = parsed
    # 2. anchor-certificate verification (the install gate)
    err = verify_snapshot(manifest, state, quorum, members)
    assert err is None, f"donor snapshot failed verification: {err}"
    # 3. crash-safe install: store save, THEN ledger compact-to-base
    joiner.store.save(manifest, state)
    anchor_wire = encode(donor.wire[manifest.height - 1])
    joiner.ledger.compact(manifest.height, manifest.chain_digest, [],
                          app_state=state, anchor=anchor_wire)
    joiner.height = manifest.height
    joiner.chain = manifest.chain_digest
    joiner.ids_digest = decode(AppState, state).ids_digest
    # 4. tail verify + replay past the horizon (paged like live sync)
    replayed = 0
    tail_bytes = 0
    pos = tail_from
    while pos < donor.depth:
        page = donor.wire[pos:pos + MAX_SYNC_DECISIONS]
        raw = [encode(wd) for wd in page]
        tail_bytes += sum(len(r) for r in raw)
        wds = [decode(WireDecision, r) for r in raw]
        err = verify_tail(wds, pos, quorum=quorum, members=members)
        assert err is None, f"tail verification failed: {err}"
        for i, wd in enumerate(wds):
            joiner.apply(wd, donor.rids[pos + i])
        replayed += len(wds)
        pos += len(wds)
    elapsed = time.perf_counter() - t0
    assert joiner.chain == donor.chains[donor.depth], \
        "snapshot rejoin arrived at a DIFFERENT chain digest"
    assert joiner.ids_digest == donor.ids_digest, \
        "snapshot rejoin arrived at a DIFFERENT ids digest"
    joiner.close()
    snap_bytes = os.path.getsize(snap_path)
    return {
        "rejoin_s": elapsed,
        "bytes": snap_bytes + tail_bytes,
        "snapshot_bytes": snap_bytes,
        "chunks": chunks,
        "replayed": replayed,
    }


def rejoin_replay(donor: DonorHistory, root: str) -> dict:
    """One timed full-chain-replay rejoin (the O(depth) control)."""
    quorum, _f = compute_quorum(len(NODES))
    members = frozenset(NODES)
    joiner = Joiner(root)
    t0 = time.perf_counter()
    total_bytes = 0
    pos = 0
    while pos < donor.depth:
        page = donor.wire[pos:pos + MAX_SYNC_DECISIONS]
        raw = [encode(wd) for wd in page]
        total_bytes += sum(len(r) for r in raw)
        wds = [decode(WireDecision, r) for r in raw]
        err = verify_tail(wds, pos, quorum=quorum, members=members)
        assert err is None, f"tail verification failed: {err}"
        for i, wd in enumerate(wds):
            joiner.apply(wd, donor.rids[pos + i])
        pos += len(wds)
    elapsed = time.perf_counter() - t0
    assert joiner.chain == donor.chains[donor.depth], \
        "replay rejoin arrived at a DIFFERENT chain digest"
    assert joiner.ids_digest == donor.ids_digest, \
        "replay rejoin arrived at a DIFFERENT ids digest"
    joiner.close()
    return {
        "rejoin_s": elapsed,
        "bytes": total_bytes,
        "replayed": donor.depth,
    }


def run_point(depth: int, *, tail: int, payload_bytes: int, reps: int,
              chunk_bytes: int, work_root: str) -> list[dict]:
    """Both modes at one history depth; best-of-``reps`` wall clock
    (rejoin is a latency-shaped metric: the best rep is the machine's
    honest capability, the spread is host weather)."""
    t0 = time.perf_counter()
    donor = DonorHistory(depth, payload_bytes)
    _log(f"rejoin: donor depth={depth} built in "
         f"{time.perf_counter() - t0:.1f}s")
    snap_height = max(1, depth - tail)
    snap_path = os.path.join(work_root, f"donor-{depth}.snap")
    with open(snap_path, "wb") as fh:
        fh.write(donor.snapshot_blob(snap_height))
    results = []
    for mode in ("snapshot", "replay"):
        best = None
        for rep in range(reps):
            root = os.path.join(work_root, f"joiner-{depth}-{mode}-{rep}")
            if mode == "snapshot":
                m = rejoin_snapshot(donor, snap_path, snap_height, root,
                                    chunk_bytes)
            else:
                m = rejoin_replay(donor, root)
            shutil.rmtree(root, ignore_errors=True)
            if best is None or m["rejoin_s"] < best["rejoin_s"]:
                best = m
        _log(f"rejoin: h={depth} mode={mode} best {best['rejoin_s']:.4f}s "
             f"({best['bytes']} bytes, {best['replayed']} replayed)")
        results.append({"depth": depth, "mode": mode, **best})
    return results


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--histories", default="100,100000",
                    help="comma-separated history depths (decisions)")
    ap.add_argument("--tail", type=int, default=16,
                    help="decisions past the snapshot horizon (the tail a "
                         "snapshot-mode joiner still replays)")
    ap.add_argument("--payload-bytes", type=int, default=96)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--chunk-bytes", type=int, default=1 << 20)
    args = ap.parse_args()
    depths = sorted({int(h) for h in args.histories.split(",") if h.strip()})
    small = depths[0]
    small_by_mode: dict[str, float] = {}
    with tempfile.TemporaryDirectory(prefix="sbft-rejoin-") as work_root:
        for depth in depths:
            for m in run_point(depth, tail=args.tail,
                               payload_bytes=args.payload_bytes,
                               reps=args.reps, chunk_bytes=args.chunk_bytes,
                               work_root=work_root):
                vs_small = None
                if m["depth"] == small:
                    small_by_mode[m["mode"]] = m["rejoin_s"]
                elif small_by_mode.get(m["mode"]):
                    vs_small = m["rejoin_s"] / small_by_mode[m["mode"]]
                row = assemble_rejoin_row(
                    history=m["depth"], mode=m["mode"],
                    rejoin_s=m["rejoin_s"], bytes_transferred=m["bytes"],
                    decisions_replayed=m["replayed"],
                    snapshot_bytes=m.get("snapshot_bytes"),
                    snap_chunks=m.get("chunks"),
                    interval=args.tail,
                    vs_small_history=vs_small,
                )
                print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
