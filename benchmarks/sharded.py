"""Sharded scaling sweep: aggregate device tx/s vs shard count S.

The tentpole claim of sharded mode (README "Sharded mode"): S independent
consensus groups sharing ONE verify plane multiply aggregate committed
tx/s with S while device LAUNCH counts grow sublinearly, because launches
carry verify items from many shards at once (cross-shard fill).  This
sweep measures exactly that: for each S in ``--shards`` it runs a full
S-shard cluster (n nodes per shard, pipelined windows, routed front-door
submission) against one shared coalescer/engine and prints one JSON row
with aggregate tx/s, launch counts, mean launch fill, the cross-shard
wave mix, and per-shard attribution blocks; a final ``sharded_scaling``
line compares the top S against S=1.

Engine selection (``--engine``):

* ``launch-cost`` (default) — a fixed-cost launch stand-in: every verify
  launch pays the rig's measured fixed device-launch overhead (PERF.md:
  ~110-1500 ms through the axon tunnel REGARDLESS of batch size; the
  default ``--launch-cost 0.22`` is the round-5 measured-stable value,
  0.11 the historical best-case floor) over a padded lane ladder, while
  verification itself is trivial.  This models precisely the economics
  sharding exploits — fixed launch cost, fill-dependent value — and runs
  anywhere (CI included) in seconds.  Fill %, launch counts, and the
  scaling ratio behave like the device engine's.
* ``jax`` — the real batched device kernels (``--crypto p256`` signs and
  verifies genuine signatures); the configuration for TPU rigs.
* ``host`` — pure-Python arithmetic floor reference.

Run:  python benchmarks/sharded.py [--shards 1,2,4,8] [--nodes 4]
      [--batch 100] [--decisions 8] [--pipeline 16] [--cpu]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartbft_tpu.utils.jaxenv import force_cpu  # noqa: E402


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


#: per-sweep-point commit deadline (seconds); overridable for slow rigs.
#: bench.py's subprocess timeout is derived from this (reps x points x
#: POINT_TIMEOUT + slack) so a stuck point degrades the sweep to fewer
#: reps instead of the parent killing the whole shard block.
POINT_TIMEOUT = float(os.environ.get("SMARTBFT_BENCH_SHARD_POINT_TIMEOUT",
                                     "120"))


class LaunchCostEngine:
    """Fixed-cost launch stand-in for the device verify engine.

    Every ``verify`` call sleeps ``launch_cost`` seconds on its worker
    thread (the coalescer launches off the event loop, exactly like the
    real engine) and records padded-lane stats, so launch counts, fill %,
    and the protocol's overlap behavior match the device engine while the
    verdicts are trivially True.  The cost default is the rig's measured
    fixed per-launch overhead (PERF.md: ~110 ms through the tunnel,
    independent of batch size) — which is the entire economic premise of
    cross-shard coalescing."""

    preferred_coalesce_window = 0.02

    def __init__(self, launch_cost: float = 0.11,
                 pad_sizes=(8, 32, 128, 512, 2048, 8192)):
        from smartbft_tpu.crypto.provider import VerifyStats

        self.launch_cost = launch_cost
        self.pad_sizes = tuple(sorted(pad_sizes))
        self.stats = VerifyStats()
        self.scheme = None
        self._lock = threading.Lock()

    def _pad_to(self, n: int) -> int:
        for s in self.pad_sizes:
            if n <= s:
                return s
        return self.pad_sizes[-1]

    def verify(self, items) -> list:
        t0 = time.perf_counter()
        time.sleep(self.launch_cost)
        n = len(items)
        with self._lock:
            self.stats.record(n, self._pad_to(n), time.perf_counter() - t0)
        return [True] * n


def build_cluster(tmp, *, shards, nodes, depth, batch, requests,
                  engine_kind, crypto, window, launch_cost, pad_sizes):
    import dataclasses

    from smartbft_tpu.testing.sharded import ShardedCluster, sharded_config

    def cfg(s, i):
        return dataclasses.replace(
            sharded_config(i, depth=depth),
            wal_group_commit=True,  # production durability path
            request_batch_max_count=batch,
            request_batch_max_interval=0.02,
            request_pool_size=max(2 * requests, 800),
            incoming_message_buffer_size=max(2000, 40 * nodes),
            request_forward_timeout=300.0,
            request_complain_timeout=600.0,
            request_auto_remove_timeout=1200.0,
            view_change_resend_interval=300.0,
            view_change_timeout=1200.0,
            leader_heartbeat_timeout=900.0,
        )

    if engine_kind == "launch-cost":
        cluster = ShardedCluster(
            tmp, shards=shards, n=nodes, depth=depth, crypto="trivial",
            window=window, config_fn=cfg, seed=13,
        )
        # swap the always-valid host engine for the fixed-cost launcher —
        # same trivial verdicts, device-shaped launch economics
        engine = LaunchCostEngine(launch_cost=launch_cost,
                                  pad_sizes=pad_sizes)
        cluster.engine = engine
        cluster.coalescer.engine = engine
        return cluster
    if engine_kind in ("jax", "host"):
        from smartbft_tpu.crypto import ed25519, p256
        from smartbft_tpu.crypto.provider import HostVerifyEngine, JaxVerifyEngine

        scheme = {"p256": p256, "ed25519": ed25519}[crypto]
        engine = JaxVerifyEngine(pad_sizes=pad_sizes, scheme=scheme) \
            if engine_kind == "jax" else HostVerifyEngine(scheme=scheme)
        return ShardedCluster(
            tmp, shards=shards, n=nodes, depth=depth, crypto=crypto,
            engine=engine, window=window, config_fn=cfg, seed=13,
        )
    raise ValueError(f"unknown engine {engine_kind}")


async def run_sweep_point(S: int, args, pad_sizes) -> dict:
    from smartbft_tpu.utils.clock import WallClockDriver

    requests_per_shard = args.decisions * args.batch
    tmp = tempfile.mkdtemp(prefix=f"bench-sharded-{S}-")
    cluster = build_cluster(
        tmp, shards=S, nodes=args.nodes, depth=args.pipeline,
        batch=args.batch, requests=requests_per_shard,
        engine_kind=args.engine, crypto=args.crypto, window=args.window,
        launch_cost=args.launch_cost, pad_sizes=pad_sizes,
    )
    engine = cluster.engine
    if args.engine == "jax":
        # pre-warm every ring's keys + every lane shape so no XLA compile
        # lands inside the timed window (mirrors benchmarks/throughput.py)
        from smartbft_tpu.crypto.provider import VerifyStats

        scheme = engine.scheme
        sk, pub = scheme.keygen(b"shard-0-1")
        item = scheme.make_item(b"warm", scheme.sign_raw(sk, b"warm"), pub)
        if hasattr(engine, "prewarm_keys"):
            for ring in cluster._rings.values():
                engine.prewarm_keys(ring[1].public_keys.values())
        t0 = time.perf_counter()
        for size in pad_sizes:
            engine.verify([item] * size)
        _log(f"sharded[{S}]: pre-warmed {tuple(pad_sizes)} in "
             f"{time.perf_counter() - t0:.1f}s")
        engine.stats = VerifyStats()
    # warm-launch probe, same contract as throughput.py rows (for the
    # launch-cost engine the probe IS the configured cost, by construction)
    if args.engine == "launch-cost":
        launch_probe_ms = args.launch_cost * 1e3
    else:
        from smartbft_tpu.crypto.provider import VerifyStats

        scheme = engine.scheme
        sk, pub = scheme.keygen(b"probe")
        item = scheme.make_item(b"p", scheme.sign_raw(sk, b"p"), pub)
        engine.verify([item])
        t0 = time.perf_counter()
        for _ in range(3):
            engine.verify([item])
        launch_probe_ms = 1e3 * (time.perf_counter() - t0) / 3
        engine.stats = VerifyStats()

    driver = WallClockDriver(cluster.scheduler, tick_interval=0.01)
    try:
        driver.start()
        await cluster.start()
        plane_bases = {
            sh.shard_id: sh.plane.snapshot() for sh in cluster.shard_list
        }
        target = requests_per_shard
        # resolve the routed client ids once — id-space scanning is load
        # GENERATION, not the system under test
        for s in range(S):
            cluster.client_for_shard(s, 3)
        t0 = time.perf_counter()
        # decision-major interleave: all shards' load arrives together, so
        # their quorum waves are in phase — the deployment shape (many
        # front-door clients, one process), not S sequential bursts
        for j in range(args.decisions):
            for s in range(S):
                for k in range(args.batch):
                    cid = cluster.client_for_shard(s, (j + k) % 4)
                    await cluster.submit(cid, f"r-{s}-{j}-{k}")
        # per-point salvage deadline: generous (healthy points take ~1-2 s
        # on this rig) yet small enough that a stuck rep only costs ITS
        # slot — bench.py sizes its whole-sweep subprocess timeout as
        # reps x points x this + slack, so the sweep degrades to fewer
        # reps instead of the parent killing the whole shard block
        deadline = time.perf_counter() + POINT_TIMEOUT
        while time.perf_counter() < deadline:
            if all(sh.committed() >= target for sh in cluster.shard_list):
                break
            await asyncio.sleep(0.02)
        else:
            raise TimeoutError(
                f"S={S}: shards committed "
                f"{[sh.committed() for sh in cluster.shard_list]} "
                f"of {target} in time"
            )
        elapsed = time.perf_counter() - t0
        cluster.check_invariants()

        stats = engine.stats
        total_committed = sum(sh.committed() for sh in cluster.shard_list)
        decisions = sum(sh.height() for sh in cluster.shard_list)
        shard_block = cluster.stats_block()
        # overwrite the harness's cumulative plane blocks with the timed
        # window's deltas
        from smartbft_tpu.metrics import ProtocolPlaneTimers

        for sh in cluster.shard_list:
            shard_block["per_shard"][sh.shard_id]["plane"] = \
                ProtocolPlaneTimers.delta(
                    plane_bases[sh.shard_id], sh.plane.snapshot()
                )
        shard_block["aggregate"]["plane"] = ProtocolPlaneTimers.sum_snapshots(
            [shard_block["per_shard"][s]["plane"] for s in range(S)]
        )
        return {
            "shards": S,
            "engine": args.engine,
            "crypto": args.crypto if args.engine != "launch-cost" else "trivial",
            "nodes_per_shard": args.nodes,
            "pipeline": args.pipeline,
            "batch": args.batch,
            "decisions_per_shard": args.decisions,
            "tx_per_sec": round(total_committed / elapsed, 1),
            "tx_per_sec_per_shard": round(total_committed / elapsed / S, 1),
            "decisions": decisions,
            "launches": stats.launches,
            "launches_per_decision": round(stats.launches / decisions, 3)
            if decisions else 0.0,
            "batch_fill_pct": round(stats.batch_fill_pct, 1),
            "items_per_launch": round(
                stats.sigs_verified / stats.launches, 1
            ) if stats.launches else 0.0,
            "sigs_verified": stats.sigs_verified,
            "launch_probe_ms": round(launch_probe_ms, 2),
            "elapsed_s": round(elapsed, 2),
            "mixed_waves": shard_block["aggregate"]["coalescer"]["mixed_waves"],
            "mesh": shard_block["aggregate"].get("mesh"),
            "shard": shard_block,
        }
    finally:
        try:
            await cluster.stop()
        except Exception:
            pass
        await driver.stop()
        shutil.rmtree(tmp, ignore_errors=True)


async def run_live_resize(args, pad_sizes) -> dict:
    """Aggregate tx/s tracking S across a LIVE resize (ISSUE 7).

    One cluster walks ``--resize-path`` (default 2 -> 4 -> 3) WITHOUT ever
    stopping: each phase pumps a load burst through the routed front door
    with a small worker pool, and every resize runs the full epoch
    protocol (barrier -> drain -> flip) mid-burst — moved clients park at
    the barrier, unmoved ones never notice.  The row carries per-phase
    tx/s (the resize transition INSIDE the measured window — downtime
    would show up here) and the ``reshard`` block: epochs, moved-key
    fraction, drain ms, and the paused-submit window per transition."""
    import itertools

    from smartbft_tpu.utils.clock import WallClockDriver

    path = [int(x) for x in args.resize_path.split(",")]
    tmp = tempfile.mkdtemp(prefix="bench-live-resize-")
    cluster = build_cluster(
        tmp, shards=path[0], nodes=args.nodes, depth=args.pipeline,
        batch=args.batch, requests=args.decisions * args.batch,
        engine_kind=args.engine, crypto=args.crypto, window=args.window,
        launch_cost=args.launch_cost, pad_sizes=pad_sizes,
    )
    # the transition's bounded drain shares the per-phase salvage budget
    cluster.set.drain_deadline = POINT_TIMEOUT
    driver = WallClockDriver(cluster.scheduler, tick_interval=0.01)
    phases = []
    transitions = []
    try:
        driver.start()
        await cluster.start()
        for phase_no, target in enumerate(path):
            total = args.decisions * args.batch * target
            counter = itertools.count()
            base = cluster.committed_requests()  # polls shards into the mux
            old_s = cluster.set.num_shards

            async def worker():
                while True:
                    k = next(counter)
                    if k >= total:
                        return
                    # route over the ACTIVE epoch's shard count (mid-flip
                    # the set may already hold the new groups)
                    s_active = cluster.set.router.shards_at(cluster.set.epoch)
                    cid = cluster.client_for_shard(k % s_active, k % 4)
                    await cluster.submit(cid, f"lr-{phase_no}-{k}")

            t0 = time.perf_counter()
            pump = asyncio.gather(*(worker() for _ in range(6)))
            summary = None
            try:
                if target != old_s:
                    # the burst is underway: resize NOW
                    await asyncio.sleep(0.2)
                    summary = await cluster.reshard(target)
                    transitions.append(summary)
                await pump
            except BaseException:
                # a failed transition must not leave 6 workers submitting
                # into a cluster the finally block is about to tear down
                pump.cancel()
                try:
                    await pump
                except Exception:
                    pass
                raise
            # barrier commands ride the old shards' streams as ordinary
            # requests — they count toward the committed-id delta
            expect = total + (old_s if summary else 0)
            deadline = time.perf_counter() + POINT_TIMEOUT
            while time.perf_counter() < deadline:
                if cluster.committed_requests() - base >= expect:
                    break
                await asyncio.sleep(0.02)
            else:
                raise TimeoutError(
                    f"live-resize phase S={target}: committed "
                    f"{cluster.committed_requests() - base} of {expect}"
                )
            elapsed = time.perf_counter() - t0
            cluster.check_invariants()
            phase = {
                "shards": target,
                "epoch": cluster.set.epoch,
                "tx_per_sec": round(total / elapsed, 1),
                "requests": total,
                "elapsed_s": round(elapsed, 2),
            }
            if summary is not None:
                phase["resize"] = {
                    "from": summary["old"], "to": summary["new"],
                    "epoch": summary["epoch"],
                    "moved_fraction": summary["moved_fraction"],
                    "drain_ms": summary["drain_ms"],
                    "paused_submit_ms": summary["paused_submit_ms"],
                    "parked_submits_peak": summary["parked_submits_peak"],
                }
            phases.append(phase)
            _log(f"live-resize[{target}]: {phase['tx_per_sec']} tx/s"
                 + (f" (epoch {summary['epoch']}, drain "
                    f"{summary['drain_ms']}ms, paused "
                    f"{summary['paused_submit_ms']}ms)" if summary else ""))
        reshard_block = cluster.set.stats_block()["reshard"]
        return {
            "metric": "live_resize",
            "path": path,
            "engine": args.engine,
            "phases": phases,
            # tx/s tracking S: per-phase throughput ratio vs the first phase
            "tracking_vs_first": [
                round(p["tx_per_sec"] / phases[0]["tx_per_sec"], 3)
                if phases[0]["tx_per_sec"] else 0.0
                for p in phases
            ],
            "reshard": dict(reshard_block, transitions_detail=transitions),
        }
    finally:
        try:
            await cluster.stop()
        except Exception:
            pass
        await driver.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--shards", default="1,2,4,8",
                    help="comma-separated shard counts to sweep")
    ap.add_argument("--resize-path", default="2,4,3",
                    help="shard counts a LIVE resize walks under load "
                         "(one cluster, epoch protocol mid-burst); '' "
                         "skips the live_resize row")
    ap.add_argument("--nodes", type=int, default=4, help="replicas per shard")
    ap.add_argument("--batch", type=int, default=50)
    ap.add_argument("--decisions", type=int, default=12,
                    help="decisions committed per shard per point")
    ap.add_argument("--pipeline", type=int, default=2)
    ap.add_argument("--engine", choices=("launch-cost", "jax", "host"),
                    default="launch-cost")
    ap.add_argument("--crypto", choices=("p256", "ed25519"), default="p256",
                    help="signature scheme for --engine jax/host")
    ap.add_argument("--launch-cost", type=float, default=0.22,
                    help="fixed per-launch seconds for --engine launch-cost "
                         "(default: the rig's round-5 MEASURED-STABLE launch "
                         "overhead, 220 ms — PERF.md; the historical "
                         "best-case floor is 0.11)")
    ap.add_argument("--window", type=float, default=0.05,
                    help="coalescer fan-in window (seconds)")
    ap.add_argument("--pad-sizes", default="auto",
                    help="engine lane ladder; auto = a device-profitable "
                         "ladder (1024..8192) for launch-cost — small waves "
                         "underfill it, which IS the single-chain problem — "
                         "and the production small-rung ladder for jax/host")
    ap.add_argument("--reps", type=int, default=3,
                    help="repetitions per sweep point; the BEST-tx row is "
                         "reported with every rep's tx/s listed alongside "
                         "(host contention on a shared rig swings single "
                         "shots 2-3x — far more than the effect size — so "
                         "the sweep measures capability, not weather; same "
                         "rationale as bench.py's best-of-3 CPU baseline)")
    ap.add_argument("--cpu", action="store_true",
                    help="pin JAX to the CPU backend")
    args = ap.parse_args()

    if args.cpu or os.environ.get("SMARTBFT_BENCH_CPU") == "1":
        force_cpu()
    else:
        # persistent XLA compile cache on the device path (force_cpu
        # enables it for the CPU path): per-process pad-shape compiles
        # must not poison every device bench row
        from smartbft_tpu.utils.jaxenv import enable_compile_cache

        enable_compile_cache()
    if args.pad_sizes == "auto":
        pad_sizes = (1024, 2048, 4096, 8192) \
            if args.engine == "launch-cost" else (8, 32, 128, 512)
    else:
        pad_sizes = tuple(int(x) for x in args.pad_sizes.split(","))
    sweep = [int(x) for x in args.shards.split(",")]

    # reps are INTERLEAVED across sweep points (rep 0 of every S, then rep
    # 1 of every S, ...) so a minutes-long host-contention episode degrades
    # every point roughly equally instead of wiping out one S's whole
    # sample — the cross-S ratios are what the sweep exists to measure
    reps_by_s: dict = {S: [] for S in sweep}
    for rep in range(max(1, args.reps)):
        for S in sweep:
            try:
                reps_by_s[S].append(
                    asyncio.run(run_sweep_point(S, args, pad_sizes))
                )
            except Exception as exc:  # noqa: BLE001 — a failed rep (stuck
                # point, invariant trip, engine error) costs ITS slot only;
                # the sweep degrades to fewer reps and still prints rows
                _log(f"sharded[{S}] rep {rep}: FAILED — {exc!r}")
    rows = []
    for S in sweep:
        reps = reps_by_s[S]
        if not reps:
            continue
        reps.sort(key=lambda r: r["tx_per_sec"])
        row = dict(reps[-1],
                   reps=len(reps),
                   tx_per_sec_reps=[r["tx_per_sec"] for r in reps])
        _log(f"sharded[{S}]: {row['tx_per_sec']} tx/s (best of "
             f"{row['tx_per_sec_reps']}), {row['launches']} launches, "
             f"fill {row['batch_fill_pct']}%, mixed_waves {row['mixed_waves']}")
        print(json.dumps(row), flush=True)
        rows.append(row)

    by_s = {r["shards"]: r for r in rows}
    if 1 in by_s and len(by_s) >= 2:
        top = max(by_s)
        base, peak = by_s[1], by_s[top]
        line = {
            "metric": "sharded_scaling",
            "value": round(peak["tx_per_sec"] / base["tx_per_sec"], 3)
            if base["tx_per_sec"] else 0.0,
            "unit": f"x aggregate tx/s at S={top} vs S=1",
            "s1_tx_per_sec": base["tx_per_sec"],
            f"s{top}_tx_per_sec": peak["tx_per_sec"],
            "launch_growth": round(peak["launches"] / base["launches"], 3)
            if base["launches"] else 0.0,
            "fill_s1_pct": base["batch_fill_pct"],
            f"fill_s{top}_pct": peak["batch_fill_pct"],
            "mixed_waves_at_top": peak["mixed_waves"],
        }
        if 4 in by_s and top != 4:
            # the acceptance bar names S=4 explicitly — always surface it
            line["s4_vs_s1"] = round(
                by_s[4]["tx_per_sec"] / base["tx_per_sec"], 3
            ) if base["tx_per_sec"] else 0.0
        print(json.dumps(line), flush=True)

    if args.resize_path.strip():
        try:
            print(json.dumps(asyncio.run(run_live_resize(args, pad_sizes))),
                  flush=True)
        except Exception as exc:  # noqa: BLE001 — the live-resize row is
            # additive; a stuck phase must not cost the sweep rows above
            _log(f"live-resize: FAILED — {exc!r}")


if __name__ == "__main__":
    main()
