"""Cluster throughput benchmark: committed tx/sec with real crypto.

The BASELINE.md north-star metric.  Spins an n-node cluster in one process
(production wall-clock mode), every commit vote a real signature, and
measures committed transactions per second end-to-end — submit, batch,
three protocol phases, quorum signature verification, two fsync'd WAL
appends per decision, deliver.

Engines (--engines, comma-separated, one cluster run each):
  openssl — OpenSSL via the `cryptography` wheel (the fair stand-in for
            the reference's Go crypto/ecdsa native path).  p256 only.
  jax     — the batched device kernel + async coalescer (cross-sequence
            cross-replica batching).
  host    — pure-Python arithmetic (floor reference).

Schemes (--scheme): p256 (default), ed25519 (BASELINE configs[3]),
bls (configs[4]: aggregate quorum, one pairing equation per check).

--share-engine (default on for jax): all replicas share ONE engine and ONE
async coalescer — the single-chip deployment shape, where concurrent
quorum checks from different replicas merge into shared kernel launches
(the cross-replica half of configs[2]'s batching).

Run:  python benchmarks/throughput.py [--nodes 4] [--requests 600]
      [--batch 100] [--engines openssl,jax] [--scheme p256]
Prints one JSON line per engine plus a final comparison line.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartbft_tpu.utils.jaxenv import force_cpu


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def get_scheme(name: str):
    if name == "p256":
        from smartbft_tpu.crypto import p256

        return p256
    if name == "ed25519":
        from smartbft_tpu.crypto import ed25519

        return ed25519
    if name == "bls":
        from smartbft_tpu.crypto import bls12381

        return bls12381
    raise ValueError(f"unknown scheme {name}")


def get_provider_cls(name: str):
    from smartbft_tpu.crypto.provider import (
        BlsCryptoProvider,
        Ed25519CryptoProvider,
        P256CryptoProvider,
    )

    return {"p256": P256CryptoProvider, "ed25519": Ed25519CryptoProvider,
            "bls": BlsCryptoProvider}[name]


def build_engine(kind: str, pad_sizes, scheme, n_nodes: int = 4):
    from smartbft_tpu.crypto.provider import HostVerifyEngine, JaxVerifyEngine

    if kind == "openssl":
        from smartbft_tpu.crypto import p256
        from smartbft_tpu.crypto.openssl_engine import OpenSSLVerifyEngine

        if scheme is not p256:
            raise ValueError("the openssl engine is p256-only")
        return OpenSSLVerifyEngine(scheme=scheme)
    if kind == "jax":
        return JaxVerifyEngine(pad_sizes=pad_sizes, scheme=scheme)
    if kind == "sharded":
        # quorum waves sharded over ALL visible devices (SURVEY §2.4's
        # multi-chip shape; on CI this is the virtual 8-CPU mesh —
        # run with --cpu or JAX_PLATFORMS=cpu
        # XLA_FLAGS=--xla_force_host_platform_device_count=8)
        from smartbft_tpu.parallel import ShardedVerifyEngine, build_mesh

        return ShardedVerifyEngine(mesh=build_mesh(), pad_sizes=pad_sizes,
                                   scheme=scheme)
    if kind == "sharded2d":
        # the 2D (seq x vote) quorum-block path: waves group by sequence
        # and vote counts psum across the 'vote' mesh axis (quorum_decide
        # under live consensus); multi-chip validation shape, CPU mesh on
        # this rig
        import jax

        from smartbft_tpu.parallel import QuorumMeshVerifyEngine, build_mesh

        ndev = len(jax.devices())
        vote_par = 2 if ndev % 2 == 0 else 1
        mesh = build_mesh((ndev // vote_par, vote_par), ("seq", "vote"))
        # honor --pad-sizes: the engine's block is seq_tile x vote_tile
        # lanes, sized so one block covers the requested top rung
        vote_tile = max(16, n_nodes)
        seq_tile = max(1, -(-max(pad_sizes) // vote_tile))
        quorum = (n_nodes + (n_nodes - 1) // 3 + 1 + 1) // 2
        return QuorumMeshVerifyEngine(mesh=mesh, quorum=quorum,
                                      seq_tile=seq_tile,
                                      vote_tile=vote_tile, scheme=scheme)
    if kind == "host":
        return HostVerifyEngine(scheme=scheme)
    raise ValueError(f"unknown engine {kind}")


async def run_cluster(engine_kind: str, n: int, requests: int, batch: int,
                      pad_sizes, scheme_name: str = "p256",
                      share_engine: bool = False,
                      dedupe: bool = False,
                      pipeline: int = 1,
                      burst_decisions: int = 0) -> dict:
    """``burst_decisions`` > 0 enables the sustained-burst mode: the request
    count is sized to commit that many decisions back to back (decisions x
    batch requests submitted up front), so the FIRST launch's fixed cost is
    amortized over a long window train instead of a single window, and the
    JSON row carries per-window launch counts."""
    import dataclasses

    from smartbft_tpu.crypto.provider import AsyncBatchCoalescer, Keyring
    from smartbft_tpu.testing.app import App, SharedLedgers, fast_config
    from smartbft_tpu.testing.network import Network
    from smartbft_tpu.utils.clock import Scheduler, WallClockDriver

    scheme = get_scheme(scheme_name)
    provider_cls = get_provider_cls(scheme_name)
    if burst_decisions > 0:
        requests = burst_decisions * batch

    def cfg(i):
        pipe = {}
        if pipeline > 1:
            # pipelined window requires rotation off (config.validate)
            pipe = dict(leader_rotation=False, decisions_per_leader=0,
                        pipeline_depth=pipeline)
        return dataclasses.replace(
            fast_config(i),
            **pipe,
            wal_group_commit=True,  # production durability path
            request_batch_max_count=batch,
            request_batch_max_interval=0.02,
            request_pool_size=max(2 * requests, 800),
            incoming_message_buffer_size=max(2000, 40 * n),
            request_forward_timeout=300.0,
            request_complain_timeout=600.0,
            request_auto_remove_timeout=1200.0,
            view_change_resend_interval=300.0,
            view_change_timeout=1200.0,
            leader_heartbeat_timeout=900.0,
        )

    node_ids = list(range(1, n + 1))
    rings = Keyring.generate(node_ids, seed=b"bench-tput", scheme=scheme)
    if share_engine:
        one = build_engine(engine_kind, pad_sizes, scheme, n_nodes=n)
        engines = {i: one for i in node_ids}
        # wider fan-in window when a whole cluster shares one chip: a
        # kernel launch costs ~100ms over the tunnel, so waiting ~20ms to
        # merge every replica's quorum check into ONE launch is cheap
        window = float(os.environ.get("SMARTBFT_BENCH_WINDOW", "0.02"))
        # pipelined mode: up to 2*`pipeline` decisions' quorum waves (base
        # window + launch shadow) coalesce into one flush — max_batch must
        # not force-flush a single wave
        coalescer = AsyncBatchCoalescer(one, window=window,
                                        max_batch=2 * pipeline * max(pad_sizes),
                                        dedupe=dedupe)
        coalescers = {i: coalescer for i in node_ids}
    else:
        engines = {i: build_engine(engine_kind, pad_sizes, scheme, n_nodes=n)
                   for i in node_ids}
        coalescers = {i: None for i in node_ids}

    # warm with a RING key: a foreign key would grow the comb-table
    # registry past the membership (65 keys -> npad 128) and force a
    # recompile of every padded shape mid-run
    sk, pub = scheme.keygen(b"bench-tput-1")
    item = scheme.make_item(
        b"warm-msg", scheme.sign_raw(sk, b"warm-msg"), pub
    )
    # pre-warm every device engine at every lane size so no XLA compile
    # lands inside the timed window
    if engine_kind in ("jax", "sharded", "sharded2d"):
        for eng in set(engines.values()):
            if hasattr(eng, "prewarm_keys"):
                eng.prewarm_keys(
                    rings[node_ids[0]].public_keys.values()
                )
        t0 = time.perf_counter()
        for eng in set(engines.values()):
            for size in pad_sizes:
                eng.verify([item] * size)
        _log(f"bench[{engine_kind}/{scheme_name}]: pre-warmed pad sizes "
             f"{tuple(pad_sizes)} on {len(set(engines.values()))} engine(s) "
             f"in {time.perf_counter() - t0:.1f}s")
    # measure the steady-state per-launch overhead (device: tunnel RTT +
    # pad; host engines: one warm single-item verify) for EVERY engine kind
    # — launch_probe_ms in the JSON row is what lets ratios be
    # weather-normalized across measurement days (VERDICT round-5 item 6)
    probe_eng = engines[node_ids[0]]
    probe_eng.verify([item])  # warm the single-item shape itself
    t0 = time.perf_counter()
    for _ in range(3):
        probe_eng.verify([item])
    launch_probe_ms = 1e3 * (time.perf_counter() - t0) / 3
    _log(f"bench[{engine_kind}/{scheme_name}]: warm launch overhead "
         f"{launch_probe_ms:.1f} ms")
    # drop warm-up/probe traffic from the reported stats
    from smartbft_tpu.crypto.provider import VerifyStats

    for eng in set(engines.values()):
        eng.stats = VerifyStats()

    from smartbft_tpu.metrics import PROTOCOL_PLANE, ProtocolPlaneTimers

    scheduler = Scheduler()
    driver = WallClockDriver(scheduler, tick_interval=0.01)
    network = Network(seed=13)
    shared = SharedLedgers()
    tmp = tempfile.mkdtemp(prefix=f"bench-tput-{engine_kind}-")
    providers = {
        i: provider_cls(rings[i], engine=engines[i], coalescer=coalescers[i])
        for i in node_ids
    }
    apps = [
        App(i, network, shared, scheduler,
            wal_dir=os.path.join(tmp, f"wal-{i}"), config=cfg(i),
            crypto=providers[i])
        for i in node_ids
    ]
    try:
        driver.start()
        for a in apps:
            await a.start()

        # snapshot the protocol-plane timers at the start of the timed
        # window so the row's block covers exactly the measured burst
        plane_before = PROTOCOL_PLANE.snapshot()
        t0 = time.perf_counter()
        for k in range(requests):
            await apps[0].submit("bench", f"req-{k}")

        target = requests
        deadline = time.perf_counter() + 600.0

        def committed(app) -> int:
            return sum(
                len(app.requests_from_proposal(d.proposal)) for d in app.ledger()
            )

        # per-window launch sampling: snapshot the launch counter each time
        # the leader's ledger crosses a k-decision window boundary, so the
        # row shows how the coalescer amortizes launches ACROSS the burst
        # (window_launches[i] = launches during the i-th window of k
        # decisions), not just the end-to-end total
        stats_eng = engines[node_ids[1]]  # follower / shared engine
        window_size = max(1, pipeline)
        marks: list[int] = []
        next_mark = window_size
        while time.perf_counter() < deadline:
            d = len(apps[0].ledger())
            while d >= next_mark:
                marks.append(stats_eng.stats.launches)
                next_mark += window_size
            if all(committed(a) >= target for a in apps):
                break
            await asyncio.sleep(0.02)
        else:
            raise TimeoutError(f"cluster did not commit {target} requests in time")
        elapsed = time.perf_counter() - t0
        # per-phase protocol-plane timers for the timed window (encode-once
        # broadcast + wave-batched ingest accounting; PERF.md decomposition)
        plane = ProtocolPlaneTimers.delta(plane_before, PROTOCOL_PLANE.snapshot())

        decisions = len(apps[0].ledger())
        stats = stats_eng.stats
        if len(marks) * window_size < decisions:
            marks.append(stats.launches)  # tail window (partial)
        window_launches = [
            b - a for a, b in zip([0] + marks[:-1], marks)
        ]
        # verify-plane fault accounting: breaker state + fallback counts in
        # EVERY row, so a degraded (host-fallback) run is never silently
        # reported as a device run.  Shared mode has one coalescer; in
        # per-replica mode ANY node degrading must show, so snapshots are
        # aggregated (counters summed, flags OR-ed) across all nodes.
        coalescers = list({
            id(providers[i].coalescer): providers[i].coalescer
            for i in node_ids
        }.values())
        snaps = [co.fault_snapshot() for co in coalescers]
        breaker_row = {
            k: (any(s[k] for s in snaps) if isinstance(snaps[0][k], bool)
                else sum(s[k] for s in snaps))
            for k in snaps[0]
        }
        # mesh block (ISSUE 10 contract: in EVERY bench row) — shared mode
        # has one coalescer; in per-replica mode the planes are homogeneous
        # in SHAPE (devices/enabled/downgrades) but the launch/fill counts
        # below are ONE plane's, so `planes` makes the scope explicit
        mesh_row = dict(coalescers[0].mesh_snapshot(),
                        planes=len(coalescers))
        return {
            "engine": engine_kind,
            "scheme": scheme_name,
            "nodes": n,
            "shared_engine": share_engine,
            "dedupe": dedupe,
            "pipeline": pipeline,
            "burst_decisions": burst_decisions,
            "tx_per_sec": round(requests / elapsed, 1),
            "decisions": decisions,
            "batch_fill_pct": round(stats.batch_fill_pct, 1),
            "verify_us_per_sig": round(stats.us_per_sig, 1),
            "launches": stats.launches,
            "launches_per_decision": round(stats.launches / decisions, 3)
            if decisions else 0.0,
            "window_launches": window_launches,
            "launch_probe_ms": round(launch_probe_ms, 2),
            "sigs_verified": stats.sigs_verified,
            "elapsed_s": round(elapsed, 2),
            "breaker": breaker_row,
            "mesh": mesh_row,
            "protocol_plane": dict(
                plane,
                # the four timers are disjoint (metrics.ProtocolPlaneTimers),
                # so their sum is the plane's accounted cost per decision
                us_per_decision=round(
                    (plane["ingest_us"] + plane["route_us"]
                     + plane["vote_reg_us"] + plane["codec_us"]) / decisions, 1
                ) if decisions else 0.0,
                encodes_per_broadcast=round(
                    plane["encodes"] / plane["broadcasts"], 3
                ) if plane["broadcasts"] else 0.0,
            ),
        }
    finally:
        for a in apps:
            try:
                await a.stop()
            except Exception:
                pass
        await driver.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--requests", type=int, default=600)
    ap.add_argument("--batch", type=int, default=100)
    ap.add_argument("--engines", default="openssl,jax")
    ap.add_argument("--scheme", default="p256",
                    choices=("p256", "ed25519", "bls"))
    ap.add_argument(
        "--pad-sizes", default="auto",
        help="comma-separated engine pad ladder, or 'auto': derive from the "
             "production JaxVerifyEngine ladder, with the top rung sized to "
             "the cluster's full quorum wave rounded up to a 128-lane Mosaic "
             "block (n x (quorum-1) signatures per decision through the "
             "shared engine) — one decision coalesces into ONE launch with "
             "near-full lanes, and the coalescer's max_batch trigger fires "
             "the moment the wave completes instead of waiting the window "
             "out",
    )
    ap.add_argument("--share-engine", choices=("auto", "yes", "no"),
                    default="auto",
                    help="share one engine+coalescer across replicas "
                         "(auto: yes for the jax engine)")
    ap.add_argument("--dedupe", choices=("auto", "yes", "no"), default="auto",
                    help="deduplicate identical verify items within a "
                         "coalesced flush (auto: on when the engine is "
                         "shared — colocated replicas re-check the same "
                         "commit votes, so a quorum wave holds each "
                         "signature up to n times)")
    ap.add_argument("--cpu", action="store_true",
                    help="pin JAX to the CPU backend")
    ap.add_argument("--pipeline", type=int, default=1,
                    help="pipelined in-flight window depth k (k>=2 runs "
                         "rotation-off mode: the leader keeps k sequences "
                         "outstanding — up to 2k under the launch shadow — "
                         "so consecutive quorum waves coalesce into shared "
                         "device launches)")
    ap.add_argument("--burst-decisions", type=int, default=0,
                    help="sustained-burst mode: size the request load to "
                         "commit this many decisions back to back "
                         "(overrides --requests with N*batch); the JSON row "
                         "then carries per-window launch counts so launch "
                         "amortization over the burst is visible")
    args = ap.parse_args()
    if args.pad_sizes == "auto":
        from smartbft_tpu.crypto.provider import JaxVerifyEngine
        import inspect

        n = args.nodes
        quorum = (n + (n - 1) // 3 + 1 + 1) // 2  # util.go:176-180
        # the shared engine's per-decision wave: every replica checks its
        # quorum; BLS collapses each check to ONE aggregated pairing lane
        wave = n if args.scheme == "bls" else n * (quorum - 1)
        # top rung = the wave rounded up to a 128-lane Mosaic block (n=64:
        # 2688 exactly — the power-of-two ladder padded it to 4096, wasting
        # ~34% of every launch); smaller rungs come from the production
        # engine's default ladder so bench shapes match deployed shapes
        block = 8 if args.scheme == "bls" else 128
        top = min(-(-wave // block) * block, 16384)
        defaults = inspect.signature(JaxVerifyEngine).parameters[
            "pad_sizes"].default
        rungs = {s for s in defaults if s < top} | {top}
        if args.pipeline > 1:
            # deduped steady-state launch for a full window train: one
            # distinct signature per replica per decision, and under the
            # launch shadow up to 2k decisions' waves can sit in one
            # coalesced flush -> k*n and 2k*n lanes
            pipe_rung = min(-(-(args.pipeline * n) // block) * block, 16384)
            shadow_rung = min(
                -(-(2 * args.pipeline * n) // block) * block, 16384
            )
            rungs |= {pipe_rung, shadow_rung}
        pad_sizes = tuple(sorted(rungs))
    else:
        pad_sizes = tuple(int(x) for x in args.pad_sizes.split(","))

    if args.cpu or os.environ.get("SMARTBFT_BENCH_CPU") == "1":
        force_cpu()
    else:
        # persistent XLA compile cache on the device path too (force_cpu
        # enables it for the CPU path): pad-shape prewarms cost full
        # compiles otherwise, every run
        from smartbft_tpu.utils.jaxenv import enable_compile_cache

        enable_compile_cache()

    results = []
    for kind in args.engines.split(","):
        share = (kind in ("jax", "sharded", "sharded2d")) if args.share_engine == "auto" \
            else args.share_engine == "yes"
        # dedupe lives in the shared coalescer: without --share-engine there
        # is no cross-replica batch to deduplicate, so report it as off
        dedupe = share and (args.dedupe != "no")
        if args.dedupe == "yes" and not share:
            _log("bench: --dedupe yes ignored without a shared engine")
        try:
            res = asyncio.run(
                run_cluster(kind, args.nodes, args.requests, args.batch,
                            pad_sizes, scheme_name=args.scheme,
                            share_engine=share, dedupe=dedupe,
                            pipeline=args.pipeline,
                            burst_decisions=args.burst_decisions)
            )
        except TimeoutError as exc:
            _log(f"bench[{kind}]: FAILED — {exc}")
            continue
        _log(f"bench[{kind}]: {res}")
        print(json.dumps(res), flush=True)
        results.append(res)

    if len(results) >= 2:
        base, dev = results[0], results[-1]
        print(json.dumps({
            "metric": f"committed_tx_per_sec_n{args.nodes}",
            "value": dev["tx_per_sec"],
            "unit": "tx/s",
            "vs_baseline": round(dev["tx_per_sec"] / base["tx_per_sec"], 3)
            if base["tx_per_sec"] else 0.0,
        }), flush=True)


if __name__ == "__main__":
    main()
