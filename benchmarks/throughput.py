"""Cluster throughput benchmark: committed tx/sec with real crypto.

The BASELINE.md north-star metric.  Spins an n-node cluster in one process
(production wall-clock mode), every commit vote a real P-256 signature,
and measures committed transactions per second end-to-end — submit,
batch, three protocol phases, quorum signature verification, two fsync'd
WAL appends per decision, deliver.

Engines:
  openssl — OpenSSL via the `cryptography` wheel (the fair stand-in for
            the reference's Go crypto/ecdsa native path).
  jax     — the batched device kernel + async coalescer (cross-sequence
            cross-replica batching).
  host    — pure-Python arithmetic (floor reference).

Run:  python benchmarks/throughput.py [--nodes 4] [--requests 600]
      [--batch 100] [--engines openssl,jax]
Prints one JSON line per engine plus a final comparison line.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartbft_tpu.utils.jaxenv import force_cpu


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_engine(kind: str, pad_sizes):
    from smartbft_tpu.crypto import p256
    from smartbft_tpu.crypto.provider import HostVerifyEngine, JaxVerifyEngine

    if kind == "openssl":
        from smartbft_tpu.crypto.openssl_engine import OpenSSLVerifyEngine

        return OpenSSLVerifyEngine(scheme=p256)
    if kind == "jax":
        return JaxVerifyEngine(pad_sizes=pad_sizes, scheme=p256)
    if kind == "host":
        return HostVerifyEngine(scheme=p256)
    raise ValueError(f"unknown engine {kind}")


async def run_cluster(engine_kind: str, n: int, requests: int, batch: int,
                      pad_sizes) -> dict:
    import dataclasses

    from smartbft_tpu.crypto import p256
    from smartbft_tpu.crypto.provider import Keyring, P256CryptoProvider
    from smartbft_tpu.testing.app import App, SharedLedgers, fast_config
    from smartbft_tpu.testing.network import Network
    from smartbft_tpu.utils.clock import Scheduler, WallClockDriver

    def cfg(i):
        return dataclasses.replace(
            fast_config(i),
            request_batch_max_count=batch,
            request_batch_max_interval=0.02,
            request_pool_size=max(2 * requests, 800),
            request_forward_timeout=300.0,
            request_complain_timeout=600.0,
            request_auto_remove_timeout=1200.0,
            view_change_resend_interval=300.0,
            view_change_timeout=1200.0,
            leader_heartbeat_timeout=900.0,
        )

    node_ids = list(range(1, n + 1))
    rings = Keyring.generate(node_ids, seed=b"bench-tput", scheme=p256)
    engines = {i: build_engine(engine_kind, pad_sizes) for i in node_ids}

    # pre-warm every node's engine at every lane size so no XLA compile
    # lands inside the timed window (each engine has its own jit wrapper)
    if engine_kind == "jax":
        d, pub = p256.keygen(b"warm")
        r, s = p256.sign(d, b"warm-msg")
        for eng in engines.values():
            for size in pad_sizes:
                eng.verify([(b"warm-msg", r, s, pub)] * size)
        _log(f"bench[{engine_kind}]: pre-warmed pad sizes {tuple(pad_sizes)} "
             f"on {len(engines)} engines")

    scheduler = Scheduler()
    driver = WallClockDriver(scheduler, tick_interval=0.01)
    network = Network(seed=13)
    shared = SharedLedgers()
    tmp = tempfile.mkdtemp(prefix=f"bench-tput-{engine_kind}-")
    apps = [
        App(i, network, shared, scheduler,
            wal_dir=os.path.join(tmp, f"wal-{i}"), config=cfg(i),
            crypto=P256CryptoProvider(rings[i], engine=engines[i]))
        for i in node_ids
    ]
    try:
        driver.start()
        for a in apps:
            await a.start()

        t0 = time.perf_counter()
        for k in range(requests):
            await apps[0].submit("bench", f"req-{k}")

        target = requests
        deadline = time.perf_counter() + 600.0

        def committed(app) -> int:
            return sum(
                len(app.requests_from_proposal(d.proposal)) for d in app.ledger()
            )

        while time.perf_counter() < deadline:
            if all(committed(a) >= target for a in apps):
                break
            await asyncio.sleep(0.02)
        else:
            raise TimeoutError(f"cluster did not commit {target} requests in time")
        elapsed = time.perf_counter() - t0

        decisions = len(apps[0].ledger())
        stats = engines[node_ids[1]].stats  # a follower: pure verify duty
        return {
            "engine": engine_kind,
            "nodes": n,
            "tx_per_sec": round(requests / elapsed, 1),
            "decisions": decisions,
            "batch_fill_pct": round(stats.batch_fill_pct, 1),
            "verify_us_per_sig": round(stats.us_per_sig, 1),
            "elapsed_s": round(elapsed, 2),
        }
    finally:
        for a in apps:
            try:
                await a.stop()
            except Exception:
                pass
        await driver.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--requests", type=int, default=600)
    ap.add_argument("--batch", type=int, default=100)
    ap.add_argument("--engines", default="openssl,jax")
    ap.add_argument("--pad-sizes", default="8,32,128")
    ap.add_argument("--cpu", action="store_true",
                    help="pin JAX to the CPU backend")
    args = ap.parse_args()
    pad_sizes = tuple(int(x) for x in args.pad_sizes.split(","))

    if args.cpu or os.environ.get("SMARTBFT_BENCH_CPU") == "1":
        force_cpu()

    results = []
    for kind in args.engines.split(","):
        try:
            res = asyncio.run(
                run_cluster(kind, args.nodes, args.requests, args.batch, pad_sizes)
            )
        except TimeoutError as exc:
            _log(f"bench[{kind}]: FAILED — {exc}")
            continue
        _log(f"bench[{kind}]: {res}")
        print(json.dumps(res), flush=True)
        results.append(res)

    if len(results) >= 2:
        base, dev = results[0], results[-1]
        print(json.dumps({
            "metric": f"committed_tx_per_sec_n{args.nodes}",
            "value": dev["tx_per_sec"],
            "unit": "tx/s",
            "vs_baseline": round(dev["tx_per_sec"] / base["tx_per_sec"], 3)
            if base["tx_per_sec"] else 0.0,
        }), flush=True)


if __name__ == "__main__":
    main()
