"""Transport paired bench: the SAME workload through the in-process
Network and through real sockets on localhost.

Every flavor drives an identical cluster — same ``testing.app.App``
protocol stack, same Scheduler, same crypto (trivial), same request
stream — and only the Comm seam differs:

* ``inproc``: the PR 4 vectorized in-process Network (encode-once wire
  bytes, interned decode, wave-batched ingest) — the A side;
* ``uds`` / ``tcp``: one ``smartbft_tpu.net.SocketComm`` per node, all
  in one asyncio loop, frames crossing REAL kernel sockets on localhost
  (length-prefixed framing, per-wave write coalescing, reconnect
  machinery armed) — the B side.

The socket rows additionally carry the ``transport`` block — bytes on
the wire, frames per flush (the write-coalescing factor), reconnects,
drops — summed across the n nodes' ``TransportMetrics``, next to the
``protocol_plane`` block every bench row already carries.

Run:  python benchmarks/transport.py [--flavors inproc,uds,tcp]
      [--nodes 4] [--requests 120] [--payload 256]
Prints one JSON line per flavor plus a ``transport_paired`` comparison
line (socket vs inproc tx/s) — the PERF.md round-10 numbers.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartbft_tpu.metrics import protocol_plane_snapshot
from smartbft_tpu.net.cluster import _free_port
from smartbft_tpu.net.transport import SocketComm, TransportMetrics
from smartbft_tpu.obs import TraceRecorder, assemble_critical_path_block
from smartbft_tpu.testing.app import App, SharedLedgers, fast_config, wait_for
from smartbft_tpu.testing.network import Network
from smartbft_tpu.utils.clock import Scheduler


def _socket_addrs(n: int, flavor: str, root: str) -> dict[int, str]:
    if flavor == "uds":
        # sockets live under the run's own tempdir (short /tmp path, well
        # inside the ~107-byte UDS limit) so run_flavor's cleanup takes
        # them along instead of leaking a dir per bench invocation
        return {i: f"uds://{root}/n{i}.sock" for i in range(1, n + 1)}
    return {i: f"tcp://127.0.0.1:{_free_port()}" for i in range(1, n + 1)}


def _build_apps(flavor: str, n: int, wal_root: str, *, trace: bool = False):
    """``trace=True`` arms one flight recorder per node (ONE process, so
    time.monotonic is one shared clock — no offset estimation needed) and,
    on socket flavors, the FT_TRACE wire sidecar + request-key hook; the
    recorders come back for the critical-path assemble."""
    scheduler = Scheduler()
    shared = SharedLedgers()
    apps: list[App] = []
    recorders: list[TraceRecorder] = []

    def recorder_for(i: int):
        if not trace:
            return None
        rec = TraceRecorder(clock=time.monotonic, node=f"n{i}",
                            capacity=16384)
        recorders.append(rec)
        return rec

    if flavor == "inproc":
        network = Network(scheduler)
        for i in range(1, n + 1):
            apps.append(App(i, network, shared, scheduler,
                            wal_dir=os.path.join(wal_root, f"wal-{i}"),
                            config=fast_config(i),
                            recorder=recorder_for(i)))
    else:
        addrs = _socket_addrs(n, flavor, wal_root)
        for i in range(1, n + 1):
            comm = SocketComm(
                i, addrs[i], {j: a for j, a in addrs.items() if j != i},
                cluster_key=b"bench", backoff_base=0.01, backoff_max=0.2,
            )
            rec = recorder_for(i)
            app = App(i, None, shared, scheduler,
                      wal_dir=os.path.join(wal_root, f"wal-{i}"),
                      config=fast_config(i), comm=comm, recorder=rec)
            if rec is not None:
                comm.recorder = rec
                comm.request_key_fn = \
                    lambda raw, a=app: str(a.request_id(raw))
            apps.append(app)
    return apps, scheduler, recorders


def _aggregate_transport(apps: list[App], flavor: str) -> dict:
    agg = TransportMetrics()
    connected = backlog = 0
    for app in apps:
        if app.comm is None:
            continue
        snap = app.comm.transport_snapshot()
        for name in TransportMetrics.__slots__:
            setattr(agg, name, getattr(agg, name) + snap[name])
        connected += snap["peers_connected"]
        backlog += snap["outbox_backlog"]
    out = agg.snapshot()
    out["flavor"] = flavor
    out["peers_connected"] = connected
    out["outbox_backlog"] = backlog
    return out


async def _drive(apps: list[App], scheduler: Scheduler, requests: int,
                 payload: int, timeout: float) -> tuple[float, int]:
    for app in apps:
        await app.start()
    n = len(apps)

    def all_committed(total: int) -> bool:
        return all(
            sum(len(a.requests_from_proposal(d.proposal)) for d in a.ledger())
            >= total
            for a in apps
        )

    # settle: every node sees an elected leader before the clock starts —
    # heartbeats only flow once the socket links are up, so this also
    # absorbs the dial/handshake phase the inproc flavor never pays
    await wait_for(
        lambda: all(
            a.consensus is not None and a.consensus.get_leader_id() != 0
            for a in apps
        ),
        scheduler, 30.0,
    )
    blob = b"x" * payload
    t0 = time.perf_counter()
    for k in range(requests):
        await apps[0].submit("bench", f"req-{k}", blob)
        if (k + 1) % 50 == 0:  # let the pipeline drain; pool stays bounded
            await wait_for(lambda t=k + 1 - 40: all_committed(max(t, 0)),
                           scheduler, timeout)
    await wait_for(lambda: all_committed(requests), scheduler, timeout)
    elapsed = time.perf_counter() - t0
    decisions = apps[0].height()
    return elapsed, decisions


def run_flavor(flavor: str, n: int, requests: int, payload: int,
               timeout: float, *, trace: bool = True) -> dict:
    with tempfile.TemporaryDirectory(prefix=f"sbft-tb-{flavor}-") as root:
        apps, scheduler, recorders = _build_apps(flavor, n, root,
                                                 trace=trace)
        plane0 = protocol_plane_snapshot()

        async def run():
            try:
                return await _drive(apps, scheduler, requests, payload, timeout)
            finally:
                for a in apps:
                    await a.stop()

        elapsed, decisions = asyncio.run(run())
        plane1 = protocol_plane_snapshot()
        row = {
            "bench": "transport",
            "flavor": flavor,
            "nodes": n,
            "requests": requests,
            "payload_bytes": payload,
            "decisions": decisions,
            "elapsed_s": round(elapsed, 3),
            "tx_per_sec": round(requests / elapsed, 1) if elapsed else 0.0,
            "transport": _aggregate_transport(apps, flavor),
            "protocol_plane": {
                k: round(plane1[k] - plane0[k], 1)
                for k in plane1 if isinstance(plane1[k], (int, float))
            },
        }
        if recorders:
            # every recorder shares one process clock: merge directly and
            # decompose (the ISSUE 13 per-request critical-path block —
            # in EVERY --transport row, the same pure fn the tests pin)
            events = [e for r in recorders for e in r.snapshot()]
            events.sort(key=lambda e: e.get("t", 0.0))
            row["critical_path"] = assemble_critical_path_block(events)
        return row


def run_cluster_trace(n: int = 4, requests: int = 24,
                      transport: str = "uds",
                      timeout: float = 120.0) -> dict:
    """The ISSUE 13 socket-cluster timeline row: a REAL process-per-
    replica cluster with wire tracing armed commits a small workload,
    then the parent pulls every replica's flight recorder plus control-
    channel clock offsets and merges ONE causally-ordered cluster
    timeline — skew-adjusted timestamps, per-directed-link network
    times, and the merged per-request critical path."""
    from smartbft_tpu.net.cluster import SocketCluster

    with tempfile.TemporaryDirectory(prefix="sbft-ct-") as root:
        cluster = SocketCluster(root, n=n, transport=transport, trace=True,
                                trace_capacity=16384)
        try:
            cluster.start()
            cluster.wait_leader()
            live = cluster.live_ids()
            for k in range(requests):
                cluster.submit(live[k % len(live)], "ct", f"ct-{k}")
            cluster.wait_committed(requests, timeout=timeout)
            timeline = cluster.cluster_timeline()
        finally:
            cluster.stop()
    # residual tolerance = the merge's stated error bound: 2x the worst
    # per-replica midpoint error (two clocks touch every cross-node delta)
    err = max((o["err_bound_s"] for o in timeline["offsets"].values()),
              default=0.0)
    critical = assemble_critical_path_block(
        timeline["merged"],
        residual_tolerance_ms=max(1.0, 2e3 * err),
    )
    return {
        "metric": "cluster_timeline",
        "nodes": n,
        "transport": transport,
        "requests": requests,
        "merged_events": timeline["events"],
        "offsets": timeline["offsets"],
        "hops": timeline["hops"],
        "critical_path": critical,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--flavors", default="inproc,uds",
                    help="comma list of inproc/uds/tcp")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--requests", type=int, default=120)
    ap.add_argument("--payload", type=int, default=256)
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--untraced", action="store_true",
                    help="disable the flight recorders + FT_TRACE sidecar "
                         "(drops the critical_path block from the rows)")
    ap.add_argument("--cluster-trace", action="store_true",
                    help="additionally run a process-per-replica socket "
                         "cluster with wire tracing and emit the merged "
                         "cluster_timeline row (clock offsets, per-link "
                         "network time, merged critical path)")
    args = ap.parse_args(argv)

    flavors = [f.strip() for f in args.flavors.split(",") if f.strip()]
    for f in flavors:
        if f not in ("inproc", "uds", "tcp"):
            ap.error(f"unknown flavor {f!r}")
    rows = {}
    for flavor in flavors:
        row = run_flavor(flavor, args.nodes, args.requests, args.payload,
                         args.timeout, trace=not args.untraced)
        rows[flavor] = row
        print(json.dumps(row), flush=True)
    if args.cluster_trace:
        try:
            print(json.dumps(run_cluster_trace(n=args.nodes)), flush=True)
        except Exception as exc:  # noqa: BLE001 — timeline row is additive
            print(f"cluster-trace run failed: {exc!r}", file=sys.stderr)
    socket_rows = [rows[f] for f in flavors if f != "inproc"]
    if "inproc" in rows and socket_rows:
        base = rows["inproc"]["tx_per_sec"]
        print(json.dumps({
            "metric": "transport_paired",
            "inproc_tx_per_sec": base,
            "pairs": [
                {
                    "flavor": r["flavor"],
                    "tx_per_sec": r["tx_per_sec"],
                    "vs_inproc": round(r["tx_per_sec"] / base, 3)
                    if base else 0.0,
                    "frames_per_flush": r["transport"]["frames_per_flush"],
                    "bytes_sent": r["transport"]["bytes_sent"],
                }
                for r in socket_rows
            ],
        }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
