"""naive_chain — a minimal hash-chained blockchain embedding smartbft_tpu.

Re-design of /root/reference/examples/naive_chain/ (chain.go:92-99,
node.go:90-273): four in-process nodes order client transactions into
blocks chained by the previous block's digest.  Like the reference
example, every node implements the WHOLE plugin SPI itself — Application,
Comm, Assembler, Signer, Verifier, MembershipNotifier, RequestInspector,
Synchronizer — over its own asyncio channel mesh, with zero imports from
the ``smartbft_tpu.testing`` harness.  Unlike the reference's no-op crypto
(node.go:90-110), commit votes here carry REAL P-256 signatures via the
library's ``P256CryptoProvider``, so this is also a working template for a
production embedding.

Run:  python examples/naive_chain.py
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartbft_tpu import wal as walmod
from smartbft_tpu.api import (
    Application,
    Assembler,
    Comm,
    MembershipNotifier,
    RequestInspector,
    Signer,
    Synchronizer,
    Verifier,
)
from smartbft_tpu.codec import decode, encode, wiremsg
from smartbft_tpu.config import Configuration
from smartbft_tpu.consensus import Consensus
from smartbft_tpu.crypto.provider import Keyring, P256CryptoProvider
from smartbft_tpu.messages import Message, Proposal, Signature, ViewMetadata
from smartbft_tpu.types import Decision, Reconfig, RequestInfo, SyncResponse
from smartbft_tpu.utils.clock import Scheduler, WallClockDriver
from smartbft_tpu.utils.logging import StdLogger
from smartbft_tpu.utils.memo import BoundedMemo


# --------------------------------------------------------------------------
# wire types owned by the application (the library never sees their schema)
# --------------------------------------------------------------------------

@wiremsg
class Transaction:
    """A client transaction (chain.go's Transaction equivalent)."""

    client_id: str = ""
    tx_id: str = ""
    payload: bytes = b""


@wiremsg
class BlockData:
    transactions: list[bytes] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.transactions is None:
            object.__setattr__(self, "transactions", [])


@wiremsg
class BlockHeader:
    sequence: int = 0
    prev_hash: bytes = b""
    data_hash: bytes = b""


# --------------------------------------------------------------------------
# the embedder's own transport: an asyncio channel mesh (chain_test.go's
# channel network, re-built here because the library owns no transport)
# --------------------------------------------------------------------------

class ChannelMesh:
    """node-id -> inbox queue; each node drains its own inbox task."""

    def __init__(self) -> None:
        self.inboxes: dict[int, asyncio.Queue] = {}

    def register(self, node_id: int) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue(maxsize=1000)
        self.inboxes[node_id] = q
        return q

    def node_ids(self) -> list[int]:
        return sorted(self.inboxes.keys())

    def post(self, target: int, item) -> None:
        q = self.inboxes.get(target)
        if q is None:
            return
        try:
            q.put_nowait(item)
        except asyncio.QueueFull:
            pass  # drop on overflow, like any real bounded transport


class NodeComm(Comm):
    """The Comm SPI for one node over the mesh."""

    def __init__(self, self_id: int, mesh: ChannelMesh):
        self.self_id = self_id
        self.mesh = mesh

    def send_consensus(self, target_id: int, msg: Message) -> None:
        self.mesh.post(target_id, ("consensus", self.self_id, msg))

    def send_transaction(self, target_id: int, request: bytes) -> None:
        self.mesh.post(target_id, ("request", self.self_id, request))

    def nodes(self) -> list[int]:
        return self.mesh.node_ids()


# --------------------------------------------------------------------------
# the chain node: implements every remaining SPI interface itself
# --------------------------------------------------------------------------

class ChainNode(Application, Assembler, Signer, Verifier, RequestInspector,
                Synchronizer, MembershipNotifier):
    """One replica of the blockchain (node.go:90-273 equivalent)."""

    def __init__(self, node_id: int, mesh: ChannelMesh, scheduler: Scheduler,
                 keyring: Keyring, wal_dir: str, pipeline: int = 1):
        self.id = node_id
        self.pipeline = pipeline
        self.mesh = mesh
        self.scheduler = scheduler
        self.comm = NodeComm(node_id, mesh)
        self.crypto = P256CryptoProvider(keyring)
        # the View's batched-verify seam goes through the provider too
        self.verify_consenter_sigs_batch = self.crypto.verify_consenter_sigs_batch
        self.verify_consenter_sigs_batch_async = (
            self.crypto.verify_consenter_sigs_batch_async
        )
        self.wal_dir = wal_dir
        self.logger = StdLogger(f"chain-{node_id}")
        self._request_id_cache: BoundedMemo = BoundedMemo()
        self.blocks: list[tuple[BlockHeader, list[bytes], tuple[Signature, ...]]] = []
        self.decisions: list[Decision] = []  # full committed decisions
        self.block_listeners: list[asyncio.Queue] = []
        self.consensus: Consensus | None = None
        # register in the mesh at construction: every node must see the full
        # membership via Comm.nodes() before any consensus instance starts
        self._inbox: asyncio.Queue = mesh.register(node_id)
        self._inbox_task: asyncio.Task | None = None
        self._wal = None
        # Pipelined-embedder pattern: with pipeline_depth > 1 the leader
        # assembles block s+1 BEFORE block s delivers, so a hash-chained
        # application must chain on its PENDING ladder (assembled/verified
        # headers above the delivered tip), not on the delivered tip alone.
        # seq -> BlockHeader; pruned at delivery, branches above a
        # re-verified sequence dropped (a view change may replace an
        # uncommitted block, invalidating everything chained on it).
        self._pending_headers: dict[int, BlockHeader] = {}

    # -- Application -------------------------------------------------------

    #: in-memory ledger append — lets the controller deliver inline
    blocking_deliver = False

    def deliver(self, proposal: Proposal, signatures) -> Reconfig:
        header = decode(BlockHeader, proposal.header)
        data = decode(BlockData, proposal.payload)
        self.blocks.append((header, list(data.transactions), tuple(signatures)))
        self.decisions.append(
            Decision(proposal=proposal, signatures=tuple(signatures))
        )
        for s in [s for s in self._pending_headers if s <= len(self.blocks)]:
            del self._pending_headers[s]
        for q in self.block_listeners:
            q.put_nowait((header, list(data.transactions)))
        return Reconfig(in_latest_decision=False)

    # -- Assembler ---------------------------------------------------------

    def _tip_hash_at(self, seq: int) -> bytes | None:
        """Hash of the chain header AT ``seq`` — delivered or pending —
        or None when this node doesn't know it (catch-up handles that)."""
        if seq == 0:
            return b"genesis"
        if seq <= len(self.blocks):
            return hashlib.sha256(encode(self.blocks[seq - 1][0])).digest()
        pending = self._pending_headers.get(seq)
        if pending is not None:
            return hashlib.sha256(encode(pending)).digest()
        return None

    def _remember_header(self, header: BlockHeader) -> None:
        """Record a pending (assembled/verified) header — bounded to the
        window above the delivered tip so a bogus far-future sequence can
        never poison the ladder or grow it without bound."""
        if not (len(self.blocks) < header.sequence
                <= len(self.blocks) + max(self.pipeline, 1)):
            return
        existing = self._pending_headers.get(header.sequence)
        if existing is not None and existing != header:
            # a superseded branch: everything chained above it is invalid
            for s in [s for s in self._pending_headers if s > header.sequence]:
                del self._pending_headers[s]
        self._pending_headers[header.sequence] = header

    def assemble_proposal(self, metadata: bytes, requests) -> Proposal:
        payload = encode(BlockData(transactions=list(requests)))
        # the consensus core tells us which sequence this proposal will
        # occupy (ViewMetadata.latest_sequence) — deriving it from the
        # pending ladder instead would let a stale entry from an abandoned
        # proposal (view change before commit) skip a height
        md = decode(ViewMetadata, metadata)
        next_seq = md.latest_sequence
        # re-proposing at a height supersedes anything remembered at or
        # above it (only possible after a view change abandoned it)
        for s in [s for s in self._pending_headers if s >= next_seq]:
            del self._pending_headers[s]
        prev_hash = self._tip_hash_at(next_seq - 1)
        if prev_hash is None:  # a leader always has its own frontier's context
            raise ValueError(f"assembling at {next_seq} without chain context")
        header = BlockHeader(
            sequence=next_seq,
            prev_hash=prev_hash,
            data_hash=hashlib.sha256(payload).digest(),
        )
        self._remember_header(header)
        return Proposal(
            header=encode(header),
            payload=payload,
            metadata=metadata,
            verification_sequence=self.verification_sequence(),
        )

    # -- Signer / Verifier: crypto via the library provider, semantics ours --

    def sign(self, data: bytes) -> bytes:
        return self.crypto.sign(data)

    def sign_proposal(self, proposal: Proposal, auxiliary_input: bytes) -> Signature:
        return self.crypto.sign_proposal(proposal, auxiliary_input)

    def verify_proposal(self, proposal: Proposal) -> list[RequestInfo]:
        header = decode(BlockHeader, proposal.header)
        data = decode(BlockData, proposal.payload)
        if header.data_hash != hashlib.sha256(proposal.payload).digest():
            raise ValueError("block data hash mismatch")
        if proposal.verification_sequence != self.verification_sequence():
            raise ValueError("wrong verification sequence")
        # chain linkage: the proposal must extend the chain at its height —
        # the delivered tip, or (pipelined mode) a pending verified header
        # above it.  Unknown heights pass here and are handled by catch-up.
        expected_prev = self._tip_hash_at(header.sequence - 1)
        if expected_prev is not None:
            if header.prev_hash != expected_prev:
                raise ValueError("block does not extend the chain tip")
            # remember only VERIFIED linkage: an unknown height must stay
            # transient (catch-up handles it), or a bogus far sequence
            # could sit in the ladder forever
            self._remember_header(header)
        return [self.request_id(r) for r in data.transactions]

    def verify_request(self, raw_request: bytes) -> RequestInfo:
        return self.request_id(raw_request)

    def verify_consenter_sig(self, signature: Signature, proposal: Proposal) -> bytes:
        return self.crypto.verify_consenter_sig(signature, proposal)

    def verify_signature(self, signature: Signature) -> None:
        self.crypto.verify_signature(signature)

    def verification_sequence(self) -> int:
        return 0  # static membership: the config epoch never advances

    def requests_from_proposal(self, proposal: Proposal) -> list[RequestInfo]:
        data = decode(BlockData, proposal.payload)
        return [self.request_id(r) for r in data.transactions]

    def auxiliary_data(self, msg: bytes) -> bytes:
        return self.crypto.auxiliary_data(msg)

    # -- RequestInspector --------------------------------------------------

    def request_id(self, raw_request: bytes) -> RequestInfo:
        # bounded memo: the inspector sees the same bytes at submit,
        # proposal verification, and removal
        def compute() -> RequestInfo:
            tx = decode(Transaction, raw_request)
            return RequestInfo(client_id=tx.client_id, request_id=tx.tx_id)

        return self._request_id_cache.get_or(raw_request, compute)

    # -- MembershipNotifier ------------------------------------------------

    def membership_change(self) -> bool:
        return False

    # -- Synchronizer ------------------------------------------------------

    def sync(self) -> SyncResponse:
        """Naive, like the reference example: report the local tip with its
        original metadata and signatures (a real embedder fetches missing
        blocks from peers here)."""
        if not self.decisions:
            return SyncResponse(latest=Decision(proposal=Proposal()),
                                reconfig=Reconfig(in_latest_decision=False))
        return SyncResponse(latest=self.decisions[-1],
                            reconfig=Reconfig(in_latest_decision=False))

    # -- lifecycle ---------------------------------------------------------

    async def _serve_inbox(self) -> None:
        while True:
            item = await self._inbox.get()
            if item is None:
                return
            kind, sender, payload = item
            if self.consensus is None:
                continue
            if kind == "consensus":
                self.consensus.handle_message(sender, payload)
            else:
                await self.consensus.handle_request(sender, payload)

    def _latest_metadata(self) -> tuple[ViewMetadata, Proposal, list[Signature]]:
        if not self.decisions:
            return ViewMetadata(), Proposal(), []
        latest = self.decisions[-1]
        md = (decode(ViewMetadata, latest.proposal.metadata)
              if latest.proposal.metadata else ViewMetadata())
        return md, latest.proposal, list(latest.signatures)

    async def start(self) -> None:
        self._inbox_task = asyncio.get_running_loop().create_task(
            self._serve_inbox(), name=f"chain-inbox-{self.id}"
        )
        self._wal, entries = walmod.initialize_and_read_all(self.wal_dir, self.logger)
        md, last_proposal, last_sigs = self._latest_metadata()
        self.consensus = Consensus(
            config=self._config(),
            application=self,
            assembler=self,
            wal=self._wal,
            wal_initial_content=entries,
            comm=self.comm,
            signer=self,
            verifier=self,
            membership_notifier=self,
            request_inspector=self,
            synchronizer=self,
            logger=self.logger,
            metadata=md,
            last_proposal=last_proposal,
            last_signatures=last_sigs,
            scheduler=self.scheduler,
            viewchanger_tick_interval=0.2,
            heartbeat_tick_interval=0.2,
        )
        await self.consensus.start()

    async def stop(self) -> None:
        if self.consensus is not None:
            await self.consensus.stop()
        if self._inbox_task is not None:
            # await (not put_nowait): a flooded bounded inbox would raise
            # QueueFull and leave the task unjoined / the WAL open
            await self._inbox.put(None)
            await self._inbox_task
            self._inbox_task = None
        if self._wal is not None:
            self._wal.close()

    def _config(self) -> Configuration:
        # pipeline >= 2 runs the pipelined in-flight window (rotation-off
        # mode): the leader keeps k blocks outstanding so consecutive
        # blocks' quorum waves coalesce into shared verify launches
        pipe = (
            dict(leader_rotation=False, decisions_per_leader=0,
                 pipeline_depth=self.pipeline)
            if self.pipeline > 1 else {}
        )
        return Configuration(
            self_id=self.id,
            request_batch_max_count=10,
            request_batch_max_interval=0.05,
            request_forward_timeout=2.0,
            request_complain_timeout=4.0,
            request_auto_remove_timeout=30.0,
            view_change_resend_interval=1.0,
            view_change_timeout=10.0,
            leader_heartbeat_timeout=15.0,
            leader_heartbeat_count=10,
            collect_timeout=1.0,
            sync_on_start=False,
            **pipe,
        )

    async def submit(self, client_id: str, tx_id: str, payload: bytes) -> None:
        tx = encode(Transaction(client_id=client_id, tx_id=tx_id, payload=payload))
        await self.consensus.submit_request(tx)


def verify_chain(node: "ChainNode") -> None:
    """Assert every block's prev_hash links to its predecessor's header —
    the chain-integrity check shared by the demo and the tests."""
    for i in range(1, len(node.blocks)):
        prev_hdr = node.blocks[i - 1][0]
        want = hashlib.sha256(encode(prev_hdr)).digest()
        assert node.blocks[i][0].prev_hash == want, f"chain broken at {i}!"


# --------------------------------------------------------------------------
# demo main: 4 nodes, 10 blocks, chain-link verification
# --------------------------------------------------------------------------

async def main(num_blocks: int = 10) -> None:
    scheduler = Scheduler()
    driver = WallClockDriver(scheduler, tick_interval=0.01)
    mesh = ChannelMesh()
    keyrings = Keyring.generate([1, 2, 3, 4], seed=b"naive-chain")
    tmp = tempfile.mkdtemp(prefix="naive_chain_wal_")

    nodes = [
        ChainNode(i, mesh, scheduler, keyrings[i], os.path.join(tmp, f"wal-{i}"))
        for i in range(1, 5)
    ]
    driver.start()
    for n in nodes:
        await n.start()

    listener: asyncio.Queue = asyncio.Queue()
    nodes[0].block_listeners.append(listener)

    print(f"chain started: 4 nodes, real P-256 votes, "
          f"leader={nodes[0].consensus.get_leader_id()}")
    for k in range(num_blocks):
        await nodes[0].submit("alice", f"txn-{k}", payload=f"transfer #{k}".encode())
        header, txns = await asyncio.wait_for(listener.get(), timeout=30)
        tx = decode(Transaction, txns[0])
        print(
            f"block {header.sequence}: prev={header.prev_hash.hex()[:12]} "
            f"txns={len(txns)} first={tx.client_id}:{tx.tx_id}"
        )

    # verify chain links + re-verify every commit signature offline
    verifier = P256CryptoProvider(keyrings[2])
    for node in nodes:
        verify_chain(node)
    n_sigs = 0
    for decision in nodes[0].decisions:
        assert len(decision.signatures) >= 3  # quorum for n=4
        for sig in decision.signatures:
            verifier.verify_consenter_sig(sig, decision.proposal)
            n_sigs += 1
    heights = [len(n.blocks) for n in nodes]
    print(f"chain verified: heights={heights}, "
          f"{n_sigs} commit signatures re-verified offline")

    for n in nodes:
        await n.stop()
    await driver.stop()


if __name__ == "__main__":
    asyncio.run(main())
