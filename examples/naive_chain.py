"""naive_chain — a minimal hash-chained blockchain over smartbft_tpu.

Re-design of /root/reference/examples/naive_chain/ (chain.go:92-99,
node.go:90-273): four in-process nodes order client transactions into
blocks chained by the previous block's digest, with no-op crypto.  Runs in
production mode (wall-clock scheduler), unlike the logical-clock test
harness.

Run:  python examples/naive_chain.py
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from smartbft_tpu.codec import decode, encode, wiremsg
from smartbft_tpu.messages import Proposal
from smartbft_tpu.testing.app import App, BatchPayload, SharedLedgers, TestRequest, fast_config
from smartbft_tpu.testing.network import Network
from smartbft_tpu.types import Decision, Reconfig
from smartbft_tpu.utils.clock import Scheduler, WallClockDriver


@wiremsg
class BlockHeader:
    sequence: int = 0
    prev_hash: bytes = b""
    data_hash: bytes = b""


class ChainNode(App):
    """An App whose assembled proposals are hash-chained blocks
    (node.go:112-158)."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.blocks: list[tuple[BlockHeader, list[bytes]]] = []
        self.block_listeners: list[asyncio.Queue] = []

    def _prev_hash(self) -> bytes:
        if not self.blocks:
            return b"genesis"
        hdr = self.blocks[-1][0]
        return hashlib.sha256(encode(hdr)).digest()

    def assemble_proposal(self, metadata: bytes, requests) -> Proposal:
        payload = encode(BatchPayload(requests=list(requests)))
        header = BlockHeader(
            sequence=len(self.blocks) + 1,
            prev_hash=self._prev_hash(),
            data_hash=hashlib.sha256(payload).digest(),
        )
        return Proposal(
            header=encode(header),
            payload=payload,
            metadata=metadata,
            verification_sequence=self.verification_seq,
        )

    def deliver(self, proposal: Proposal, signatures) -> Reconfig:
        header = decode(BlockHeader, proposal.header)
        batch = decode(BatchPayload, proposal.payload)
        self.blocks.append((header, list(batch.requests)))
        self.shared.append(self.id, Decision(proposal=proposal, signatures=tuple(signatures)))
        for q in self.block_listeners:
            q.put_nowait((header, list(batch.requests)))
        return Reconfig(in_latest_decision=False)


async def main(num_blocks: int = 10) -> None:
    scheduler = Scheduler()
    driver = WallClockDriver(scheduler, tick_interval=0.01)
    network = Network(seed=7)
    shared = SharedLedgers()
    tmp = tempfile.mkdtemp(prefix="naive_chain_wal_")

    nodes = [
        ChainNode(i, network, shared, scheduler, wal_dir=os.path.join(tmp, f"wal-{i}"))
        for i in range(1, 5)
    ]
    driver.start()
    for n in nodes:
        await n.start()

    listener: asyncio.Queue = asyncio.Queue()
    nodes[0].block_listeners.append(listener)

    print(f"chain started: 4 nodes, leader={nodes[0].consensus.get_leader_id()}")
    for k in range(num_blocks):
        await nodes[0].submit("alice", f"txn-{k}", payload=f"transfer #{k}".encode())
        header, txns = await asyncio.wait_for(listener.get(), timeout=30)
        txt = decode(TestRequest, txns[0])
        print(
            f"block {header.sequence}: prev={header.prev_hash.hex()[:12]} "
            f"txns={len(txns)} first={txt.client_id}:{txt.request_id}"
        )

    # verify the chain links
    for i in range(1, len(nodes[0].blocks)):
        prev_hdr = nodes[0].blocks[i - 1][0]
        want = hashlib.sha256(encode(prev_hdr)).digest()
        assert nodes[0].blocks[i][0].prev_hash == want, "chain broken!"
    heights = [len(n.blocks) for n in nodes]
    print(f"chain verified: heights={heights}")

    for n in nodes:
        await n.stop()
    await driver.stop()


if __name__ == "__main__":
    asyncio.run(main())
