"""smartbft_tpu — a TPU-native Byzantine fault-tolerant SMR framework.

A from-scratch re-design of the capabilities of pkucode/SmartBFT (surveyed in
/root/repo/SURVEY.md): a PBFT-style three-phase consensus core with leader
rotation, deterministic blacklisting, a full view-change sub-protocol,
heartbeat failure detection, state transfer, dynamic reconfiguration, and a
crash-tolerant segmented WAL — with the signature-verification hot path
(ECDSA P-256 / Ed25519 quorum checks) batched and executed on TPU via JAX.

Layering (top-down, mirrors SURVEY.md §1):
  consensus.Consensus  — composition root / public facade
  api                  — the 10-interface plugin SPI the embedder implements
  core                 — Controller, View, ViewChanger, Pool, Batcher,
                         HeartbeatMonitor, StateCollector, PersistedState
  messages / codec     — canonical wire & persistence schema
  wal                  — durable segmented log
  crypto + ops         — TPU batch Signer/Verifier (the point of the project)
  parallel             — device-mesh sharding for the verify kernels
  shard                — S consensus groups over one shared verify plane
                         (router / delivery mux / ShardSet front door)
  testing              — in-process fault-injection network harness
"""

__version__ = "0.1.0"

from .config import DEFAULT_CONFIG, Configuration
from .messages import (
    Commit,
    HeartBeat,
    HeartBeatResponse,
    NewView,
    PrePrepare,
    Prepare,
    Proposal,
    Signature,
    SignedViewData,
    StateTransferRequest,
    StateTransferResponse,
    ViewChange,
    ViewData,
    ViewMetadata,
)
from .types import Checkpoint, Decision, Reconfig, RequestInfo, SyncResponse

__all__ = [
    "Configuration",
    "DEFAULT_CONFIG",
    "Commit",
    "HeartBeat",
    "HeartBeatResponse",
    "NewView",
    "PrePrepare",
    "Prepare",
    "Proposal",
    "Signature",
    "SignedViewData",
    "StateTransferRequest",
    "StateTransferResponse",
    "ViewChange",
    "ViewData",
    "ViewMetadata",
    "Checkpoint",
    "Decision",
    "Reconfig",
    "RequestInfo",
    "SyncResponse",
]
