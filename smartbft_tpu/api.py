"""Plugin SPI: the boundary the embedding application implements.

Re-design of /root/reference/pkg/api/dependencies.go:14-99.  Ten abstract
interfaces plus one deliberate extension: :class:`Verifier` gains a *batch*
method, ``verify_consenter_sigs_batch``, so the protocol core is
batching-native from day one — the reference fans out one goroutine per
commit signature (/root/reference/internal/bft/view.go:537-541); here the
View accumulates votes and flushes them as one call, which the TPU verifier
executes as a single vmap'd kernel launch.

All methods are synchronous; implementations that need concurrency (the TPU
bridge) do their own batching/queueing internally.  The consensus core runs
on a single asyncio loop and calls potentially-blocking SPI methods through
``asyncio.to_thread`` where latency matters (sync, batch verify).
"""

from __future__ import annotations

import abc
from typing import Optional, Sequence

from .messages import Message, Proposal, Signature
from .types import Decision, Reconfig, RequestInfo, SyncResponse


class Application(abc.ABC):
    """Receives consented proposals (dependencies.go:14-19)."""

    @abc.abstractmethod
    def deliver(self, proposal: Proposal, signatures: Sequence[Signature]) -> Reconfig:
        """Persist the decided proposal; returns reconfiguration info."""


class Comm(abc.ABC):
    """Node-to-node transport, supplied by the embedder (dependencies.go:22-30).

    ``broadcast_consensus`` is an OPTIONAL vectorization seam: transports
    that can encode a message once and fan the same wire bytes out to
    every peer (the in-process network; a real transport's scatter path)
    override it — the default loops ``send_consensus``, which pays the
    per-recipient cost."""

    @abc.abstractmethod
    def send_consensus(self, target_id: int, msg: Message) -> None: ...

    @abc.abstractmethod
    def send_transaction(self, target_id: int, request: bytes) -> None: ...

    @abc.abstractmethod
    def nodes(self) -> list[int]:
        """Participating node ids (return a fresh copy)."""


class Assembler(abc.ABC):
    """Creates proposals from batched requests (dependencies.go:33-37)."""

    @abc.abstractmethod
    def assemble_proposal(self, metadata: bytes, requests: Sequence[bytes]) -> Proposal: ...


class WriteAheadLog(abc.ABC):
    """Durable log (dependencies.go:40-44)."""

    @abc.abstractmethod
    def append(self, entry: bytes, truncate_to: bool) -> None: ...


class Signer(abc.ABC):
    """Signs data / proposals (dependencies.go:47-52)."""

    @abc.abstractmethod
    def sign(self, data: bytes) -> bytes: ...

    @abc.abstractmethod
    def sign_proposal(self, proposal: Proposal, auxiliary_input: bytes) -> Signature: ...


class Verifier(abc.ABC):
    """Validates requests, proposals and signatures (dependencies.go:55-71).

    ``verify_consenter_sigs_batch`` is the TPU seam: the default
    implementation loops over :meth:`verify_consenter_sig`, while the TPU
    verifier overrides it with one batched kernel launch.
    """

    @abc.abstractmethod
    def verify_proposal(self, proposal: Proposal) -> list[RequestInfo]:
        """Raises on invalid proposal; returns the included requests' info."""

    @abc.abstractmethod
    def verify_request(self, raw_request: bytes) -> RequestInfo:
        """Raises on invalid request; returns its info."""

    @abc.abstractmethod
    def verify_consenter_sig(self, signature: Signature, proposal: Proposal) -> bytes:
        """Raises on invalid signature; returns the signature's auxiliary data."""

    @abc.abstractmethod
    def verify_signature(self, signature: Signature) -> None:
        """Raises on invalid signature."""

    @abc.abstractmethod
    def verification_sequence(self) -> int:
        """Current config-epoch for request re-validation."""

    @abc.abstractmethod
    def requests_from_proposal(self, proposal: Proposal) -> list[RequestInfo]: ...

    @abc.abstractmethod
    def auxiliary_data(self, msg: bytes) -> bytes:
        """Extracts auxiliary data from a signature's message."""

    # --- batching extension (not in the reference SPI) ---

    def verify_consenter_sigs_batch(
        self, signatures: Sequence[Signature], proposal: Proposal
    ) -> list[Optional[bytes]]:
        """Verify many consenter signatures over one proposal.

        Returns, per signature, its auxiliary data on success or ``None`` on
        failure — never raises for individual bad signatures.  Override in
        batched (TPU) verifiers; the default is the sequential fallback.
        """
        out: list[Optional[bytes]] = []
        for sig in signatures:
            try:
                out.append(self.verify_consenter_sig(sig, proposal))
            except Exception:
                out.append(None)
        return out


class MembershipNotifier(abc.ABC):
    """Signals membership change in the last proposal (dependencies.go:74-78)."""

    @abc.abstractmethod
    def membership_change(self) -> bool: ...


class RequestInspector(abc.ABC):
    """Extracts (client id, request id) from a raw request (dependencies.go:81-85)."""

    @abc.abstractmethod
    def request_id(self, raw_request: bytes) -> RequestInfo: ...


class Synchronizer(abc.ABC):
    """Fetches remote decisions to catch this replica up (dependencies.go:88-93)."""

    @abc.abstractmethod
    def sync(self) -> SyncResponse: ...


class Logger(abc.ABC):
    """Structured-logging contract (dependencies.go:96-99)."""

    @abc.abstractmethod
    def debugf(self, template: str, *args) -> None: ...

    @abc.abstractmethod
    def infof(self, template: str, *args) -> None: ...

    @abc.abstractmethod
    def warnf(self, template: str, *args) -> None: ...

    @abc.abstractmethod
    def errorf(self, template: str, *args) -> None: ...

    @abc.abstractmethod
    def panicf(self, template: str, *args) -> None:
        """Log and raise."""
