"""Canonical, deterministic binary codec for wire messages and digests.

The reference serializes wire messages with protobuf and computes digests over
ASN.1-marshaled structures (/root/reference/pkg/types/types.go:50-69,
/root/reference/internal/bft/util.go:557-579).  Protobuf encoding is not
byte-deterministic across implementations, and the blacklist/digest logic of
the protocol requires *byte-exact* agreement between replicas.  This codec is
therefore a from-scratch, reflection-driven, fully canonical encoding:

- ``int``   -> 8-byte big-endian unsigned (all protocol ints are uint64)
- ``bool``  -> 1 byte (0/1)
- ``bytes`` -> u32 length + payload
- ``str``   -> u32 length + UTF-8 payload
- ``list[X]``      -> u32 count + each element
- ``Optional[Msg]``-> 1-byte presence flag + body
- nested dataclass -> fields in declaration order, inline

Every encodable message is a frozen dataclass registered via ``@wiremsg``.
Oneof-style unions (the top-level consensus ``Message``) are encoded as a
1-byte type tag + body; tags are assigned at registration time and are part
of the wire format, so registration order is stable and append-only.
"""

from __future__ import annotations

import dataclasses
import struct
import typing
from typing import Any, Optional, Type, TypeVar, get_args, get_origin

T = TypeVar("T")

_U64 = struct.Struct(">Q")
_U32 = struct.Struct(">I")

# registry: class -> tag, tag -> class (for union-tagged encoding)
_TAG_BY_CLS: dict[type, int] = {}
_CLS_BY_TAG: dict[int, type] = {}
_NEXT_TAG = [1]



class CodecError(Exception):
    pass


def wiremsg(cls: Type[T]) -> Type[T]:
    """Class decorator: freeze as dataclass and register a wire tag."""
    cls = dataclasses.dataclass(frozen=True)(cls)
    tag = _NEXT_TAG[0]
    _NEXT_TAG[0] += 1
    _TAG_BY_CLS[cls] = tag
    _CLS_BY_TAG[tag] = cls
    return cls


def _enc_int(out: bytearray, v: int) -> None:
    if v < 0 or v > 0xFFFFFFFFFFFFFFFF:
        raise CodecError(f"int out of uint64 range: {v}")
    out += _U64.pack(v)


def _dec_int(buf: memoryview, off: int) -> tuple[int, int]:
    return _U64.unpack_from(buf, off)[0], off + 8


def _enc_bool(out: bytearray, v: bool) -> None:
    out.append(1 if v else 0)


def _dec_bool(buf: memoryview, off: int) -> tuple[bool, int]:
    return buf[off] != 0, off + 1


def _enc_bytes(out: bytearray, v: bytes) -> None:
    out += _U32.pack(len(v))
    out += v


def _dec_bytes(buf: memoryview, off: int) -> tuple[bytes, int]:
    n = _U32.unpack_from(buf, off)[0]
    off += 4
    return bytes(buf[off : off + n]), off + n


def _enc_str(out: bytearray, v: str) -> None:
    _enc_bytes(out, v.encode("utf-8"))


def _dec_str(buf: memoryview, off: int) -> tuple[str, int]:
    b, off = _dec_bytes(buf, off)
    return b.decode("utf-8"), off


def _make_list_codec(elem_enc, elem_dec):
    def enc(out: bytearray, v: list) -> None:
        out += _U32.pack(len(v))
        for e in v:
            elem_enc(out, e)

    def dec(buf: memoryview, off: int) -> tuple[list, int]:
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        res = []
        for _ in range(n):
            e, off = elem_dec(buf, off)
            res.append(e)
        return res, off

    return enc, dec


def _make_optional_codec(elem_enc, elem_dec):
    def enc(out: bytearray, v) -> None:
        if v is None:
            out.append(0)
        else:
            out.append(1)
            elem_enc(out, v)

    def dec(buf: memoryview, off: int):
        flag = buf[off]
        off += 1
        if flag == 0:
            return None, off
        return elem_dec(buf, off)

    return enc, dec


def _make_msg_codec(cls):
    def enc(out: bytearray, v) -> None:
        if type(v) is not cls:
            raise CodecError(f"expected {cls.__name__}, got {type(v).__name__}")
        _encode_into(out, v)

    def dec(buf: memoryview, off: int):
        return _decode_from(cls, buf, off)

    return enc, dec


def _codec_for(tp):
    origin = get_origin(tp)
    if tp is int:
        return _enc_int, _dec_int
    if tp is bool:
        return _enc_bool, _dec_bool
    if tp is bytes:
        return _enc_bytes, _dec_bytes
    if tp is str:
        return _enc_str, _dec_str
    if origin in (list, tuple):
        (elem,) = get_args(tp)[:1]
        return _make_list_codec(*_codec_for(elem))
    if origin is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1 and len(get_args(tp)) == 2:
            return _make_optional_codec(*_codec_for(args[0]))
        raise CodecError(f"only Optional unions supported, got {tp}")
    if dataclasses.is_dataclass(tp):
        return _make_msg_codec(tp)
    raise CodecError(f"unsupported field type {tp!r}")


# ---------------------------------------------------------------------------
# compiled per-class codecs.  The plan above dispatches through one closure
# call per field; on the protocol hot path (every request id, every
# signature binding, every metadata read) that indirection is the dominant
# Python cost (measured: decode() was ~half the n=64 cluster profile).
# Each class instead gets ONE generated function that inlines the scalar
# field handling and falls back to the plan closures only for nested /
# container fields.  The wire format is bit-identical to the plan codecs.
# ---------------------------------------------------------------------------

_ENC_FN: dict[type, Any] = {}
_DEC_FN: dict[type, Any] = {}

_INLINE_ENC = {
    int: ("    x = v.{name}\n"
          "    if x < 0 or x > 18446744073709551615:\n"
          "        raise CodecError('int out of uint64 range: %r' % (x,))\n"
          "    out += _u64(x)\n"),
    bool: "    out.append(1 if v.{name} else 0)\n",
    bytes: ("    x = v.{name}\n"
            "    out += _u32(len(x))\n"
            "    out += x\n"),
    str: ("    x = v.{name}.encode('utf-8')\n"
          "    out += _u32(len(x))\n"
          "    out += x\n"),
}

_INLINE_DEC = {
    int: ("    {name} = _u64u(buf, off)[0]\n"
          "    off += 8\n"),
    bool: ("    {name} = buf[off] != 0\n"
           "    off += 1\n"),
    bytes: ("    n = _u32u(buf, off)[0]\n"
            "    off += 4\n"
            "    {name} = bytes(buf[off:off + n])\n"
            "    off += n\n"),
    str: ("    n = _u32u(buf, off)[0]\n"
          "    off += 4\n"
          "    {name} = str(buf[off:off + n], 'utf-8')\n"
          "    off += n\n"),
}


#: identifiers used by the generated codec bodies — a dataclass field with
#: one of these names would silently miscompile, so registration rejects it
_RESERVED_FIELD_NAMES = frozenset(
    {"out", "v", "buf", "off", "n", "x", "_cls", "CodecError"}
    | {f"_e{i}" for i in range(64)} | {f"_d{i}" for i in range(64)}
    | {"_u64", "_u32", "_u64u", "_u32u", "_enc", "_dec"}
)


def _compile_codecs(cls) -> None:
    hints = typing.get_type_hints(cls)
    fields = dataclasses.fields(cls)
    for f in fields:
        if f.name in _RESERVED_FIELD_NAMES:
            raise CodecError(
                f"{cls.__name__}.{f.name}: field name is reserved by the "
                "compiled codec generator"
            )
    ns: dict[str, Any] = {
        "CodecError": CodecError,
        "_u64": _U64.pack, "_u32": _U32.pack,
        "_u64u": _U64.unpack_from, "_u32u": _U32.unpack_from,
        "_cls": cls,
    }
    enc_src = ["def _enc(out, v):\n"]
    dec_src = ["def _dec(buf, off):\n"]
    names = []
    for i, f in enumerate(fields):
        tp = hints[f.name]
        names.append(f.name)
        if tp in _INLINE_ENC:
            enc_src.append(_INLINE_ENC[tp].format(name=f.name))
            dec_src.append(_INLINE_DEC[tp].format(name=f.name))
        else:
            e, d = _codec_for(tp)
            ns[f"_e{i}"], ns[f"_d{i}"] = e, d
            enc_src.append(f"    _e{i}(out, v.{f.name})\n")
            dec_src.append(f"    {f.name}, off = _d{i}(buf, off)\n")
    if not fields:
        enc_src.append("    pass\n")
    dec_src.append(f"    return _cls({', '.join(names)}), off\n")
    exec("".join(enc_src), ns)
    exec("".join(dec_src), ns)
    _ENC_FN[cls] = ns["_enc"]
    _DEC_FN[cls] = ns["_dec"]


def _enc_fn(cls):
    fn = _ENC_FN.get(cls)
    if fn is None:
        _compile_codecs(cls)
        fn = _ENC_FN[cls]
    return fn


def _dec_fn(cls):
    fn = _DEC_FN.get(cls)
    if fn is None:
        _compile_codecs(cls)
        fn = _DEC_FN[cls]
    return fn


def _encode_into(out: bytearray, msg) -> None:
    _enc_fn(type(msg))(out, msg)


def _decode_from(cls: Type[T], buf: memoryview, off: int) -> tuple[T, int]:
    return _dec_fn(cls)(buf, off)


def encode(msg) -> bytes:
    """Canonical encoding of a registered message (no type tag)."""
    out = bytearray()
    _encode_into(out, msg)
    return bytes(out)


def decode(cls: Type[T], data: bytes) -> T:
    try:
        msg, off = _decode_from(cls, memoryview(data), 0)
    except (struct.error, IndexError, UnicodeDecodeError) as e:
        raise CodecError(f"malformed {cls.__name__}: {e}") from e
    if off != len(data):
        raise CodecError(f"{len(data) - off} trailing bytes decoding {cls.__name__}")
    return msg


def encode_tagged(msg) -> bytes:
    """Encoding prefixed with the registered 1-byte type tag (for oneofs)."""
    cls = type(msg)
    tag = _TAG_BY_CLS.get(cls)
    if tag is None:
        raise CodecError(f"{cls.__name__} is not a registered wire message")
    out = bytearray([tag])
    _encode_into(out, msg)
    return bytes(out)


def decode_tagged(data: bytes):
    if not data:
        raise CodecError("empty buffer")
    cls = _CLS_BY_TAG.get(data[0])
    if cls is None:
        raise CodecError(f"unknown wire tag {data[0]}")
    try:
        msg, off = _decode_from(cls, memoryview(data), 1)
    except (struct.error, IndexError, UnicodeDecodeError) as e:
        raise CodecError(f"malformed {cls.__name__}: {e}") from e
    if off != len(data):
        raise CodecError(f"{len(data) - off} trailing bytes decoding {cls.__name__}")
    return msg


def tag_of(cls: type) -> int:
    return _TAG_BY_CLS[cls]
