"""Consensus configuration.

Python re-design of the reference's 21-field configuration struct
(/root/reference/pkg/types/config.go:14-187).  Durations are float seconds
(the reference uses ``time.Duration``); all timeouts are consumed by the
tick-driven time source in :mod:`smartbft_tpu.utils.clock`, so sub-tick
precision is not meaningful.
"""

from __future__ import annotations

from dataclasses import dataclass, replace


class ConfigError(ValueError):
    pass


@dataclass(frozen=True)
class Configuration:
    # Identity
    self_id: int = 0

    # Batching (config.go:18-28)
    request_batch_max_count: int = 100
    request_batch_max_bytes: int = 10 * 1024 * 1024
    request_batch_max_interval: float = 0.05
    # Arrival-driven batch formation (README "Arrival-driven proposing").
    # Off (default): the BatchBuilder waits the full
    # request_batch_max_interval for a partial wave — the fixed cadence tax
    # the round-17 critical path showed as a 31-37% propose_wait share at
    # every offered rate.  On: the builder consults the pool's arrival-rate
    # EWMA and proposes the moment the in-formation wave provably cannot
    # fill within the remaining interval (deficit / arrival_rate >
    # fill_slack * time_left), while a wave the rate predicts WILL fill is
    # still allowed to form to full depth.  Low offered rates thus propose
    # immediately (propose_wait ~ 0) and saturation still forms deep
    # amortizing waves; the max interval stays the hard deadline either way.
    # fill_slack > 1 keeps waiting past the strict prediction (deeper waves,
    # more residual wait); < 1 gives up earlier (lower latency, shallower
    # waves).
    request_batch_adaptive: bool = False
    request_batch_fill_slack: float = 1.0

    # Buffers / pool (config.go:30-35).
    # When a View/ViewChanger inbox reaches incoming_message_buffer_size:
    # - inbox_backpressure=False (default): further messages are DROPPED
    #   (with a rate-limited warning).  Dropping bounds a Byzantine
    #   flooder's memory without letting it stall the shared event loop;
    #   the cost is that an honest burst near the bound (e.g. a view-change
    #   storm at large n) can shed prepares/commits/view-data and pay an
    #   extra view change.  Size the bound generously for large clusters —
    #   the throughput harness uses max(2000, 40*n).
    # - inbox_backpressure=True: the SENDING task blocks until space frees,
    #   matching the reference's full-channel semantics (view.go:190,
    #   viewchanger.go:206).  Requires the transport to deliver through the
    #   async intake (Consensus.handle_message_async); transports calling
    #   the sync intake still get drop semantics.
    # Pipelined views (pipeline_depth > 1) use direct ingest with no inbox:
    # vote-set dedup and the slot window bound memory, so neither policy
    # applies there.
    incoming_message_buffer_size: int = 200
    inbox_backpressure: bool = False
    request_pool_size: int = 400

    # Group-commit WAL durability (no reference counterpart — the reference
    # fsyncs inline on every append, writeaheadlog.go:469-472).  ON: protocol
    # saves append immediately and await a shared batched fsync wave, so the
    # disk never blocks the event loop.  Deterministic logical-clock tests
    # turn it OFF (see testing.app.fast_config): awaiting a real executor
    # round-trip lets the test clock race ahead of the protocol.
    wal_group_commit: bool = True

    # Request timeout chain (config.go:37-45)
    request_forward_timeout: float = 2.0
    request_complain_timeout: float = 20.0
    request_auto_remove_timeout: float = 180.0

    # RTT-derived forward timing (no reference counterpart — the
    # reference's forward timeout is a constant; round 16's cluster
    # timeline measured follower-submitted requests spending 97.6% of
    # their latency waiting out that constant).  When > 0 and the
    # transport measures RTT (smartbft_tpu.net.SocketComm does, from
    # dial and sync round trips), the EFFECTIVE forward timeout becomes
    # clamp(multiplier * measured_rtt, 10 ms, request_forward_timeout):
    # the configured constant stays the ceiling and the fallback (no
    # transport measurement, in-process Comm, cold links).  0 (default)
    # keeps the constant — reference-faithful.
    request_forward_rtt_multiplier: float = 0.0

    # View change (config.go:47-51)
    view_change_resend_interval: float = 5.0
    view_change_timeout: float = 20.0

    # Heartbeats (config.go:53-62)
    leader_heartbeat_timeout: float = 60.0
    leader_heartbeat_count: int = 10
    num_of_ticks_behind_before_syncing: int = 10

    # Adaptive failover detection (no reference counterpart — the
    # reference's complain timer is the constant above; round 16 measured
    # detection arm-to-fire up to 21.8 s under a muted leader while the
    # VC protocol itself runs in 35-52 ms, making DETECTION ~99% of the
    # failover cliff).  When heartbeat_rtt_multiplier > 0 the EFFECTIVE
    # complain timer becomes
    #   clamp(multiplier * max(rtt_ewma, commit_interval_ewma,
    #         observed_heartbeat_gap_ewma) * backoff,
    #         DETECTION_FLOOR, leader_heartbeat_timeout)
    # where rtt_ewma is the transport's measured per-peer RTT envelope
    # (SocketComm, PR 14) and commit_interval_ewma is the Controller's
    # commit inter-arrival EWMA (the Pool._drain_rate idiom) — both
    # CLUSTER-VISIBLE signals, so the leader's heartbeat emission cadence
    # (effective timeout / leader_heartbeat_count) shrinks in step with
    # the followers' complain timers; the observed-gap term (sampled
    # with the receipt-time clock — tick-quantized samples would feed
    # the tick cadence back into the derivation and run it up to the
    # ceiling) additionally guarantees a follower never complains faster
    # than a multiple of the emission cadence its leader actually
    # demonstrates.  The derived timer only applies to a leader this
    # follower has OBSERVED in the current view (first-observation
    # grace): until the new leader's first sign of life the constant
    # governs, so warm followers carrying hair-trigger signals from the
    # previous view cannot spuriously depose a cold-signal leader whose
    # own derivation paces its first emission at ceiling/count.
    # The configured constant stays the ceiling AND the
    # fallback (no measurement yet, in-process Comm with no RTT, cold
    # cluster).  The monitor's tick cadence is derived from the effective
    # timeout too, so arm-to-fire can never overshoot the timer by
    # multiples (the round-16 granularity gap).  ``backoff`` widens the
    # timer by detection_backoff_base per consecutive complain against
    # the SAME view (capped at detection_backoff_max, and always at the
    # ceiling), so a flaky network that keeps killing view changes backs
    # detection off instead of thrashing leadership; installing a higher
    # view resets it.  0 (default) keeps the constant — reference-
    # faithful.
    heartbeat_rtt_multiplier: float = 0.0
    detection_backoff_base: float = 2.0
    detection_backoff_max: float = 8.0

    # Flip-time backlog drain (ISSUE 15 — round 16's critical path put
    # 98% of forced-VC request time in `propose_wait`: followers' pooled
    # requests wait out a full request_forward_timeout before reaching
    # the NEW leader after the flip).  When > 0, a view-flip timer
    # restart fast-forwards the oldest
    #   flip_drain_windows * pipeline_depth * request_batch_max_count
    # pooled requests (their forward timers arm at the floor instead of
    # the full timeout), so the new view's first proposals batch the
    # stalled backlog into deep windows immediately; the rest of the
    # pool keeps the ordinary timeout chain.  Leader-side pool dedup
    # absorbs the duplicates this may forward.  0 disables (every timer
    # restarts at the full forward timeout — reference-faithful).
    flip_drain_windows: int = 4

    # State collection (config.go:64-66)
    collect_timeout: float = 1.0

    # Flags (config.go:68-75)
    sync_on_start: bool = False
    speed_up_view_change: bool = False

    # Leader rotation (config.go:77-80).
    # rotation_granularity selects the unit decisions_per_leader counts:
    # - "decision" (reference-faithful): a leader term spans
    #   decisions_per_leader decisions, and every pre-prepare chains to the
    #   PREVIOUS decision's commit certificate (view.go:606-647).  Requires
    #   pipeline_depth == 1 — a pipelined leader proposes s+1 before s's
    #   certificate exists.
    # - "window": a leader term spans decisions_per_leader WINDOWS of
    #   pipeline_depth decisions each, and only the FIRST pre-prepare of
    #   each window chains (to the last decision of the previous window —
    #   the window anchor).  Within a window the full k-deep pipeline runs;
    #   at window boundaries the pipeline drains so the anchor certificate
    #   exists before the next window opens.  This is how rotation +
    #   blacklisting co-host with pipeline_depth > 1.
    leader_rotation: bool = True
    decisions_per_leader: int = 3
    rotation_granularity: str = "decision"

    # Request limits (config.go:82-87)
    request_max_bytes: int = 10 * 1024
    request_pool_submit_timeout: float = 5.0

    # Admission control at the front door (no reference counterpart — the
    # reference's pool blocks submitters on a weighted semaphore forever;
    # a service past its saturation knee must SHED, not queue unboundedly:
    # PBFT's own overload story assumes excess load is dropped, and queue
    # growth past the knee buys only latency, never goodput).  Consumed by
    # core.pool.Pool via PoolOptions; rides ConfigMirror/reconfig.
    # - admission_high_water: fraction of request_pool_size at which
    #   submit stops queueing and fails fast with AdmissionRejected
    #   (retry-after hint derived from the measured drain rate).  The
    #   gate input counts pooled requests PLUS parked submitters.  1.0
    #   (default) disables shedding — pure bounded-wait semantics.
    # request_pool_submit_timeout above doubles as the TOTAL bound a
    # submitter may spend parked on pool space (one deadline across every
    # re-park), so even with the gate off callers shed instead of wedging.
    admission_high_water: float = 1.0

    # Pipelined in-flight window (no reference counterpart — the reference
    # keeps exactly one sequence in flight: the leader re-acquires the
    # propose token only after the current decision delivers,
    # controller.go:555-557, and only pipelines vote COLLECTION one ahead,
    # view.go:107-113).  pipeline_depth k >= 2 lets the leader keep up to k
    # consecutive sequences outstanding (propose s+1 before s delivers);
    # replicas run a per-sequence slot machine with in-order commit
    # broadcast and in-order delivery.  The payoff is batched quorum
    # verification ACROSS decisions: k commit waves coalesce into one
    # device launch instead of k.  Under the launch shadow the leader may
    # keep up to 2k sequences outstanding (it fills window w+1's protocol
    # plane while window w's verify wave is on device), and replicas hold
    # at most 3k slots (one extra window of frontier-skew tolerance on
    # intake) — so the per-view memory bound is 3k slots x one proposal
    # each.  Deep windows (k=16/32) are the launch-amortization lever; the
    # validation cap below keeps the slot ladder, the view-change ladder
    # message, and crash-restore replay bounded.  Requires leader_rotation
    # off — the rotation protocol chains each pre-prepare to the PREVIOUS
    # decision's commit certificate (view.go:606-647), which a pipelined
    # leader does not yet hold.  k = 1 is the reference-faithful default.
    pipeline_depth: int = 1

    # Verify-plane fault tolerance (no reference counterpart — the
    # reference verifies each signature on its own goroutine, view.go:537-
    # 541, which cannot hang or fail as a unit; routing the quorum-verify
    # hot path through one shared device engine makes the device a single
    # point of failure).  Consumed when the Consensus facade wires a
    # CryptoProvider's coalescer (crypto/provider.VerifyFaultPolicy.
    # from_config).  These three durations are WALL-CLOCK seconds even
    # under the logical test clock: the engine runs on worker threads the
    # tick scheduler cannot observe.
    # - verify_launch_timeout: deadline per coalescer flush; on expiry the
    #   in-flight launch is abandoned (its late result discarded) and the
    #   wave enters the retry path.  Default is generous against the
    #   measured 0.11-1.5 s launch-weather range (PERF.md).
    # - verify_launch_retries: re-submissions (exponential backoff with
    #   jitter) of a failed/timed-out wave before it falls back to host.
    # - verify_breaker_threshold: consecutive launch failures that trip
    #   the host-fallback circuit breaker open (a permanent kernel error
    #   trips it immediately).
    # - verify_probe_interval: cadence of the background canary probe that
    #   re-tries the device while the breaker is open.
    # - verify_mesh_devices: device-mesh width of the verify plane.  0
    #   (default) keeps the single-device engine.  N >= 1 graduates the
    #   coalescer's engine onto an N-device mesh at start/reconfig
    #   (CryptoProvider.configure_verify_mesh): every coalesced wave is
    #   padded to a device-count multiple, partitioned along the batch
    #   axis (NamedSharding(mesh, P('batch'))), and verified in ONE
    #   logical launch spanning the mesh.  The fault knobs above apply
    #   per MESH launch unchanged (deadline abandons the whole mesh
    #   launch, the breaker degrades every shard to host together).
    #   DEGRADED MODE: a host with fewer visible devices than configured
    #   keeps the single-device engine LOUDLY, with a counted downgrade
    #   (consensus.tpu.count_mesh_downgrades) — it never dies at start.
    # - verify_mesh_topology: the mesh SHAPE when verify_mesh_devices > 0.
    #   "1d" (default) partitions the batch axis (MeshVerifyEngine);
    #   "2d" graduates onto the seq x vote QuorumMeshVerifyEngine, whose
    #   per-sequence quorum counts psum across the 'vote' mesh axis —
    #   quorum counting itself rides the device collective — while
    #   per-item verdicts stay bit-identical to the 1D engine.  A build
    #   with no usable shard_map downgrades loudly like an unbuildable
    #   mesh.
    # - verify_flush_hold: occupancy-aware flush gating (wall-clock
    #   seconds; 0 disables).  A coalescer flush whose wave sits below a
    #   pad-ladder rung may HOLD up to this hard deadline while per-tag
    #   submit-rate tracking predicts more shards' waves inbound, so one
    #   deeper launch replaces several shallow ones (fixed-launch-
    #   overhead amortization).  The hold is bypassed outright while the
    #   breaker is open (host fallback must not wait), past max_batch,
    #   and for rung-exact waves; hold decisions are exported in the
    #   bench `mesh` block (waves_held, held_ms, depth_gain_items).
    verify_launch_timeout: float = 30.0
    verify_launch_retries: int = 2
    verify_breaker_threshold: int = 3
    verify_probe_interval: float = 2.0
    verify_mesh_devices: int = 0
    verify_mesh_topology: str = "1d"
    verify_flush_hold: float = 0.0

    # Per-sender misbehavior accounting (ISSUE 18 — no reference
    # counterpart: the reference drops an invalid vote and forgets who
    # sent it).  Every cryptographically provable invalid verdict
    # (bad signature value, digest-binding forgery, unknown signer) is
    # attributed to its signer in a node-LOCAL MisbehaviorTable; a sender
    # whose decayed score crosses the threshold is shunned — its
    # Prepare/Commit votes are dropped at intake BEFORE reaching the
    # verify plane (a vote-forgery flood stops costing device launches)
    # and its forwarded client requests lose the admission-gate bypass.
    # Local-only by design: the shared window-boundary blacklist stays a
    # pure function of replicated view-change evidence.
    # - misbehavior_shun_threshold: provable-invalid score at which a
    #   sender is shunned (honest senders score ~0; an honest replica's
    #   votes simply verify).
    # - misbehavior_decay_interval: seconds between score-halving ticks —
    #   the redemption path: a sender that stops forging drains below
    #   half the threshold and is released.
    misbehavior_shun_threshold: int = 8
    misbehavior_decay_interval: float = 30.0

    # Real-socket transport (smartbft_tpu/net/ — no reference counterpart:
    # the reference is a library whose embedder supplies Comm; these knobs
    # configure the transport we ship).  Consumed by SocketComm.from_config
    # and round-tripped by testing.reconfig.ConfigMirror like every other
    # knob, so a reconfiguration cannot silently reset the transport —
    # EXCEPT transport_listen, which is per-node like self_id (each
    # replica binds its OWN address) and is therefore restored from the
    # local config on receipt (with_node_locals), never mirrored.
    # - transport_listen: this node's own listen address ("tcp://host:port",
    #   port 0 for ephemeral, or "uds:///path"); empty = in-process Comm,
    #   no socket transport.
    # - transport_outbox_cap: max frames buffered per peer while its link
    #   is down/slow; beyond it the OLDEST frame is dropped and counted
    #   (loud-but-bounded — a dead peer must never grow a live replica's
    #   memory without bound).
    # - transport_reconnect_backoff_base/_max: exponential redial backoff
    #   bounds (seconds, wall-clock; each sleep gets ±25% jitter so n
    #   replicas redialing a restarted peer do not thundering-herd it).
    # - transport_max_frame_bytes: frame-length sanity cap; a length
    #   prefix above it poisons the connection (dropped, counted) before
    #   any allocation happens.
    transport_listen: str = ""
    transport_outbox_cap: int = 4096
    transport_reconnect_backoff_base: float = 0.05
    transport_reconnect_backoff_max: float = 2.0
    transport_max_frame_bytes: int = 16 * 1024 * 1024

    # Elastic shards (smartbft_tpu/shard/ — no reference counterpart: the
    # reference is one consensus instance; sharding and live resharding are
    # this codebase's scale story).  Consumed by ShardSet.reshard and
    # shard.autoscale.OccupancyAutoscaler.from_config; round-tripped by
    # testing.reconfig.ConfigMirror so a reconfiguration cannot silently
    # reset the elasticity envelope.
    # - reshard_drain_deadline: wall-clock seconds a live reshard may
    #   spend waiting for barrier commits + moved-key-range drain before
    #   the transition aborts and parked moved-client submits raise
    #   ShardEpochError (unmoved clients are never delayed).
    # - autoscale_high_occupancy / autoscale_low_occupancy: combined pool
    #   fill fractions (ShardSet.occupancy()['fill']) above which the
    #   autoscaler scales OUT / below which it scales IN.
    # - autoscale_cooldown: seconds after any reshard (executed or failed)
    #   before the autoscaler decides again — the anti-flap gate.
    # - autoscale_min_shards / autoscale_max_shards: the elasticity bounds.
    reshard_drain_deadline: float = 30.0
    autoscale_high_occupancy: float = 0.85
    autoscale_low_occupancy: float = 0.15
    autoscale_cooldown: float = 60.0
    autoscale_min_shards: int = 1
    autoscale_max_shards: int = 8

    # Snapshots + log compaction (smartbft_tpu/snapshot/ — the PBFT
    # stable-checkpoint discipline, ISSUE 17).  Consumed by the socket
    # ReplicaApp and the in-process testing App; round-tripped by
    # testing.reconfig.ConfigMirror so a reconfiguration cannot silently
    # turn compaction off (or on) for part of the cluster.
    # - snapshot_interval_decisions: capture a snapshot (and truncate the
    #   ledger/WAL prefix behind it) every N committed decisions.  0
    #   (default) disables snapshots entirely — full-chain catch-up and
    #   unbounded ledger growth, the pre-ISSUE-17 behavior, which several
    #   existing harness oracles (committed_ids over the whole history)
    #   rely on.
    # - snapshot_chunk_bytes: FT_SNAP_RESP chunk payload size for state
    #   transfer; must leave frame-envelope headroom under
    #   transport_max_frame_bytes (validated below).
    snapshot_interval_decisions: int = 0
    snapshot_chunk_bytes: int = 1024 * 1024

    # The read/serving plane (smartbft_tpu/core/readplane.py — ISSUE 19,
    # Castro–Liskov's read-only optimization).  Reads execute at replicas
    # against committed state with NO ordering and bypass the write
    # path's pool/admission gate entirely; they get their own
    # token-bucket gate so a read storm degrades reads, never writes.
    # Consumed by the socket ReplicaApp and the in-process testing App;
    # round-tripped by ConfigMirror like every other knob.
    # - read_gate_rate: sustained reads/second one replica serves before
    #   shedding (0 = gate off, every read answered — the default, since
    #   committed-state reads are one dict lookup under the lock).
    # - read_gate_burst: bucket depth — the burst a replica absorbs
    #   before the rate limit bites.
    # - read_watch_buffer: per-subscriber committed-stream notification
    #   cap; past it the OLDEST notification is dropped and counted
    #   (the transport outbox-cap discipline — a slow subscriber must
    #   never grow replica memory without bound).
    # - read_max_watches: concurrent subscriptions one replica carries;
    #   registration past it is refused loudly.
    read_gate_rate: float = 0.0
    read_gate_burst: int = 256
    read_watch_buffer: int = 256
    read_max_watches: int = 64

    # The self-driving control plane (smartbft_tpu/control/ — ISSUE 20,
    # the verdict→action reflex arc).  Consumed by ControlPolicy /
    # ControlLoop; round-tripped by ConfigMirror so a reconfiguration
    # retunes the controller itself along with everything else.
    # - control_interval: seconds between controller ticks.
    # - control_cooldown: per-ACTION cooldown (scale_out, scale_in and
    #   retune each have their own clock); re-armed on failure too.
    # - control_hysteresis: window within which an action that UNDOES a
    #   recent one (scale-in after scale-out, a knob flipped back to its
    #   previous value) is vetoed — the anti-oscillation guard.
    # - control_idle_hold: sustained-idle seconds before scale-in fires.
    # - control_budget_actions / control_budget_window: global anti-thrash
    #   budget — at most N actions of ANY kind per window.
    # - control_knob_deadband: relative change a derived knob must exceed
    #   before a retune commits it (EWMA jitter must not reconfigure the
    #   cluster).
    # - control_forward_rtt_multiplier: derived request_forward_timeout =
    #   multiplier x measured transport RTT EWMA (clamped to the
    #   boot-time value; PR 15's request_forward_rtt_multiplier pattern,
    #   but COMMITTED through reconfig rather than applied locally).
    # - control_hold_commit_multiplier: derived verify_flush_hold =
    #   multiplier x commit inter-arrival EWMA.
    # - control_outbox_drain_window: derived transport_outbox_cap =
    #   measured pool drain rate x this window (seconds of backlog the
    #   outbox may hold).
    control_interval: float = 1.0
    control_cooldown: float = 30.0
    control_hysteresis: float = 120.0
    control_idle_hold: float = 60.0
    control_budget_actions: int = 4
    control_budget_window: float = 300.0
    control_knob_deadband: float = 0.25
    control_forward_rtt_multiplier: float = 8.0
    control_hold_commit_multiplier: float = 0.5
    control_outbox_drain_window: float = 2.0

    def validate(self) -> None:
        def positive(name: str) -> None:
            v = getattr(self, name)
            if v <= 0:
                raise ConfigError(f"{name} should be greater than zero")

        if self.self_id == 0:
            raise ConfigError("self_id should be greater than zero")
        for field in (
            "request_batch_max_count",
            "request_batch_max_bytes",
            "request_batch_max_interval",
            "incoming_message_buffer_size",
            "request_pool_size",
            "request_forward_timeout",
            "request_complain_timeout",
            "request_auto_remove_timeout",
            "view_change_resend_interval",
            "view_change_timeout",
            "leader_heartbeat_timeout",
            "leader_heartbeat_count",
            "num_of_ticks_behind_before_syncing",
            "collect_timeout",
            "request_max_bytes",
            "request_pool_submit_timeout",
            "verify_launch_timeout",
            "verify_breaker_threshold",
            "verify_probe_interval",
            "transport_outbox_cap",
            "transport_reconnect_backoff_base",
            "transport_reconnect_backoff_max",
            "transport_max_frame_bytes",
            "reshard_drain_deadline",
            "autoscale_cooldown",
            "request_batch_fill_slack",
            "control_interval",
            "control_cooldown",
            "control_hysteresis",
            "control_budget_window",
            "control_outbox_drain_window",
        ):
            positive(field)
        if self.control_idle_hold < 0:
            raise ConfigError("control_idle_hold should not be negative")
        if self.control_budget_actions < 1:
            raise ConfigError("control_budget_actions should be at least 1")
        if not (0.0 <= self.control_knob_deadband < 1.0):
            raise ConfigError(
                "control_knob_deadband should be in [0, 1), got "
                f"{self.control_knob_deadband}"
            )
        if self.control_forward_rtt_multiplier < 0:
            raise ConfigError(
                "control_forward_rtt_multiplier should not be negative"
            )
        if self.control_hold_commit_multiplier < 0:
            raise ConfigError(
                "control_hold_commit_multiplier should not be negative"
            )
        if not (0.0 < self.autoscale_low_occupancy
                < self.autoscale_high_occupancy <= 1.0):
            raise ConfigError(
                "autoscale occupancy thresholds must satisfy "
                "0 < low < high <= 1, got "
                f"low={self.autoscale_low_occupancy} "
                f"high={self.autoscale_high_occupancy}"
            )
        if not (1 <= self.autoscale_min_shards <= self.autoscale_max_shards):
            raise ConfigError(
                "autoscale shard bounds must satisfy 1 <= min <= max, got "
                f"{self.autoscale_min_shards}..{self.autoscale_max_shards}"
            )
        if self.verify_launch_retries < 0:
            raise ConfigError("verify_launch_retries should not be negative")
        if self.request_forward_rtt_multiplier < 0:
            raise ConfigError(
                "request_forward_rtt_multiplier should not be negative "
                "(0 keeps the constant request_forward_timeout)"
            )
        if self.heartbeat_rtt_multiplier < 0:
            raise ConfigError(
                "heartbeat_rtt_multiplier should not be negative "
                "(0 keeps the constant leader_heartbeat_timeout)"
            )
        if self.detection_backoff_base < 1.0:
            raise ConfigError(
                "detection_backoff_base must be at least 1 (the per-round "
                "complain-timer widening factor; 1 disables backoff)"
            )
        if self.detection_backoff_max < self.detection_backoff_base:
            raise ConfigError(
                "detection_backoff_max must be at least "
                "detection_backoff_base (it caps the cumulative backoff "
                "multiplier)"
            )
        if self.flip_drain_windows < 0:
            raise ConfigError(
                "flip_drain_windows should not be negative "
                "(0 disables the flip-time backlog fast-forward)"
            )
        if self.verify_mesh_devices < 0:
            raise ConfigError(
                "verify_mesh_devices should not be negative "
                "(0 = single-device verify plane)"
            )
        if self.verify_mesh_topology not in ("1d", "2d"):
            raise ConfigError(
                "verify_mesh_topology should be '1d' (batch-axis mesh) or "
                "'2d' (seq x vote quorum mesh), got "
                f"{self.verify_mesh_topology!r}"
            )
        if self.verify_flush_hold < 0:
            raise ConfigError(
                "verify_flush_hold should not be negative "
                "(0 disables occupancy-aware flush gating)"
            )
        if self.misbehavior_shun_threshold < 1:
            raise ConfigError(
                "misbehavior_shun_threshold should be at least 1, got "
                f"{self.misbehavior_shun_threshold}"
            )
        if self.misbehavior_decay_interval <= 0:
            raise ConfigError(
                "misbehavior_decay_interval should be positive (the decay "
                "tick is also the shun-release/redemption path), got "
                f"{self.misbehavior_decay_interval}"
            )
        if self.snapshot_interval_decisions < 0:
            raise ConfigError(
                "snapshot_interval_decisions should not be negative "
                "(0 disables snapshots and log compaction)"
            )
        if self.snapshot_chunk_bytes <= 0:
            raise ConfigError(
                "snapshot_chunk_bytes should be greater than zero"
            )
        if self.snapshot_chunk_bytes > self.transport_max_frame_bytes - 65536:
            raise ConfigError(
                "snapshot_chunk_bytes must sit at least 64 KiB under "
                "transport_max_frame_bytes (chunk + envelope must fit one "
                "frame, or every state transfer poisons its connection)"
            )
        if self.read_gate_rate < 0:
            raise ConfigError(
                "read_gate_rate should not be negative "
                "(0 disables the read gate)"
            )
        for name in ("read_gate_burst", "read_watch_buffer",
                     "read_max_watches"):
            if getattr(self, name) < 1:
                raise ConfigError(f"{name} should be at least 1")
        if not (0.0 < self.admission_high_water <= 1.0):
            raise ConfigError(
                "admission_high_water must be in (0, 1] (a fraction of "
                f"request_pool_size; 1.0 disables shedding), got "
                f"{self.admission_high_water}"
            )
        if self.transport_reconnect_backoff_base > self.transport_reconnect_backoff_max:
            raise ConfigError(
                "transport_reconnect_backoff_base is bigger than "
                "transport_reconnect_backoff_max"
            )
        # a frame must be able to carry a maximum-size proposal plus its
        # metadata/signature envelope, or every send of a full batch
        # poisons the receiving connection and the cluster loops on
        # reconnect without ever committing
        if self.transport_max_frame_bytes < self.request_batch_max_bytes + 65536:
            raise ConfigError(
                "transport_max_frame_bytes must exceed request_batch_max_bytes "
                "by at least 64 KiB of proposal envelope headroom"
            )
        if self.request_batch_max_count > self.request_batch_max_bytes:
            raise ConfigError("request_batch_max_count is bigger than request_batch_max_bytes")
        if self.request_forward_timeout > self.request_complain_timeout:
            raise ConfigError("request_forward_timeout is bigger than request_complain_timeout")
        if self.request_complain_timeout > self.request_auto_remove_timeout:
            raise ConfigError("request_complain_timeout is bigger than request_auto_remove_timeout")
        if self.view_change_resend_interval > self.view_change_timeout:
            raise ConfigError("view_change_resend_interval is bigger than view_change_timeout")
        if self.leader_rotation and self.decisions_per_leader == 0:
            raise ConfigError("decisions_per_leader should be greater than zero when leader rotation is active")
        if not self.leader_rotation and self.decisions_per_leader != 0:
            raise ConfigError("decisions_per_leader should be zero when leader rotation is off")
        if self.pipeline_depth < 1:
            raise ConfigError("pipeline_depth should be at least 1")
        if self.pipeline_depth > 256:
            raise ConfigError(
                "pipeline_depth is capped at 256: replicas hold up to "
                "3*pipeline_depth proposal slots per view (base window + "
                "launch shadow + intake skew) and the view-change ViewData "
                "carries one in-flight rung per undelivered sequence"
            )
        if self.rotation_granularity not in ("decision", "window"):
            raise ConfigError(
                "rotation_granularity should be 'decision' or 'window', "
                f"got {self.rotation_granularity!r}"
            )
        if (
            self.pipeline_depth > 1
            and self.leader_rotation
            and self.rotation_granularity != "window"
        ):
            raise ConfigError(
                "pipeline_depth > 1 with leader_rotation requires "
                "rotation_granularity='window' (per-decision rotation chains "
                "every pre-prepare to the previous decision's commit "
                "certificate, which a pipelined leader does not yet hold; "
                "window granularity chains only at window boundaries)"
            )

    @property
    def effective_decisions_per_leader(self) -> int:
        """decisions_per_leader expressed in DECISIONS regardless of
        granularity: window granularity multiplies by the window depth so a
        term spans decisions_per_leader whole windows.  This is the value
        every get_leader_id / blacklist computation consumes — it must be
        derived identically on every replica (it is pure config)."""
        if (
            self.leader_rotation
            and self.rotation_granularity == "window"
            and self.pipeline_depth > 1
        ):
            return self.decisions_per_leader * self.pipeline_depth
        return self.decisions_per_leader

    def with_self_id(self, self_id: int) -> "Configuration":
        return replace(self, self_id=self_id)

    def with_node_locals(self, prev: "Configuration") -> "Configuration":
        """Restore the per-node fields a cluster-wide reconfiguration must
        never overwrite: ``self_id`` and this node's own listen address
        (each replica binds its OWN ``transport_listen``; a committed
        config carries the proposer's)."""
        return replace(
            self,
            self_id=prev.self_id,
            transport_listen=prev.transport_listen,
        )


#: Reasonable defaults for a ~10ms-RTT cluster (config.go:92-113).
DEFAULT_CONFIG = Configuration()
