"""The Consensus facade: composition root and public API.

Re-design of /root/reference/pkg/consensus/consensus.go:28-523.  Validates
configuration, wires ViewChanger / StateCollector / Controller / Pool /
Batcher / HeartbeatMonitor, computes the start view/seq from the checkpoint
metadata plus WAL-restored ViewChange/NewView records, and runs the reconfig
loop: when a delivered decision or a sync carries a reconfiguration, stop
all components, swap config and node set, rebuild, restart.

All timing flows through one tick-driven Scheduler; production attaches a
WallClockDriver, tests advance it manually.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Sequence

from . import api as bft_api
from .codec import decode
from .config import Configuration
from .core.batcher import BatchBuilder
from .core.controller import Controller
from .core.heartbeat import FOLLOWER, LEADER, HeartbeatMonitor
from .core.misbehavior import MisbehaviorTable
from .core.pool import Pool, PoolOptions
from .core.proposer import ProposalMaker
from .core.state import PersistedState
from .core.statecollector import StateCollector
from .core.util import InFlightData
from .core.view import ViewSequencesHolder
from .core.viewchanger import ViewChanger
from .messages import Message, ViewMetadata
from .metrics import MetricsBundle
from .types import Checkpoint, Proposal, Reconfig, Signature, SyncResponse
from .utils.clock import Scheduler, Ticker, WallClockDriver
from .utils.tasks import create_logged_task


def _scaled_rtt_fn(mult: float, comm):
    """An ``mult * comm.rtt_seconds()`` provider when ``mult`` is armed
    and the transport measures RTT (SocketComm does); None otherwise —
    consumers keep their configured constants, and each clamps the
    derived value into its own [floor, constant]."""
    rtt_fn = getattr(comm, "rtt_seconds", None)
    if mult <= 0 or rtt_fn is None:
        return None

    def derive():
        rtt = rtt_fn()
        return None if rtt is None else mult * rtt

    return derive


class Consensus:
    """Public entry points: start / stop / submit_request / handle_message /
    handle_request / get_leader_id (consensus.go:28-68,108,283-317)."""

    def __init__(
        self,
        *,
        config: Configuration,
        application: bft_api.Application,
        assembler: bft_api.Assembler,
        wal: bft_api.WriteAheadLog,
        wal_initial_content: Sequence[bytes],
        comm: bft_api.Comm,
        signer: bft_api.Signer,
        verifier: bft_api.Verifier,
        membership_notifier: Optional[bft_api.MembershipNotifier],
        request_inspector: bft_api.RequestInspector,
        synchronizer: bft_api.Synchronizer,
        logger: bft_api.Logger,
        metadata: ViewMetadata,
        last_proposal: Proposal,
        last_signatures: Sequence[Signature],
        scheduler: Optional[Scheduler] = None,
        metrics: Optional[MetricsBundle] = None,
        viewchanger_tick_interval: float = 1.0,
        heartbeat_tick_interval: float = 1.0,
        recorder=None,
    ):
        self.config = config
        self.application = application
        self.assembler = assembler
        self.wal = wal
        self.wal_initial_content = list(wal_initial_content)
        self.comm = comm
        self.signer = signer
        self.verifier = verifier
        self.membership_notifier = membership_notifier
        self.request_inspector = request_inspector
        self.synchronizer = synchronizer
        self.logger = logger
        self.metadata = metadata
        self.last_proposal = last_proposal
        self.last_signatures = list(last_signatures)
        self.metrics = metrics or MetricsBundle()
        self.scheduler = scheduler if scheduler is not None else Scheduler()
        # flight recorder (ISSUE 12): the embedder passes an
        # obs.TraceRecorder to trace this replica; the default nop
        # recorder keeps every instrumentation site at one attribute
        # read.  The VC phase tracker rides the SAME injectable clock as
        # every other timer and outlives reconfig-rebuilt components.
        from .obs import NOP_RECORDER, ViewChangePhaseTracker

        self.recorder = recorder if recorder is not None else NOP_RECORDER
        self.vc_phases = ViewChangePhaseTracker(
            clock=self.scheduler.now, node=f"n{config.self_id}",
            recorder=self.recorder, metrics=self.metrics.view_change,
        )
        # per-sender misbehavior accounting (ISSUE 18): node-LOCAL — fed
        # by the verifier's per-signer invalid-verdict attribution
        # (configure_misbehavior seam), read by the Controller to shed
        # shunned senders' votes at intake and revoke their forwarded-
        # request admission bypass; decayed on a ticker (redemption).
        self.misbehavior = MisbehaviorTable(
            self_id=config.self_id,
            shun_threshold=config.misbehavior_shun_threshold,
            logger=logger,
            recorder=self.recorder,
        )
        # committed-state read hook (ISSUE 19): the embedder registers a
        # callable (key: str) -> Optional[tuple[bytes, int, bytes, int]]
        # = (value, height, state_digest, anchor_height) answered from
        # COMMITTED state only.  The facade exposes it (read_committed)
        # so read-plane callers hold one handle per replica; consensus
        # itself never calls it — reads bypass the pool/proposer/verify
        # plane entirely, that is the whole point.
        self.read_hook = None
        self._own_scheduler = scheduler is None
        self._clock_driver: Optional[WallClockDriver] = None
        self.viewchanger_tick_interval = viewchanger_tick_interval
        self.heartbeat_tick_interval = heartbeat_tick_interval

        self.nodes: list[int] = []
        self.num_nodes = 0
        self._node_set: set[int] = set()

        self.pool: Optional[Pool] = None
        self.controller: Optional[Controller] = None
        self.view_changer: Optional[ViewChanger] = None
        self.collector: Optional[StateCollector] = None
        self.state: Optional[PersistedState] = None
        self.in_flight: Optional[InFlightData] = None
        self.checkpoint = Checkpoint()

        self._running = False
        self._stopping = False
        self._reconfig_queue: asyncio.Queue = asyncio.Queue()
        self._run_task: Optional[asyncio.Task] = None
        self._tickers: list[Ticker] = []
        self._restore_view_change = False

    # ------------------------------------------------------------------ SPI glue

    def complain(self, view_num: int, stop_view: bool) -> None:
        """FailureDetector for the Controller/View (consensus.go:70-74)."""
        if self.view_changer is not None:
            self.view_changer.start_view_change(view_num, stop_view)

    @property
    def blocking_deliver(self) -> bool:
        """Forward the embedder app's deliver-blocking capability so the
        controller can skip the executor offload for in-memory delivers."""
        return getattr(self.application, "blocking_deliver", True)

    def deliver(self, proposal: Proposal, signatures) -> Reconfig:
        """Application wrapper that detects reconfig (consensus.go:76-84).
        Runs on an executor thread — route reconfigs back thread-safely."""
        reconfig = self.application.deliver(proposal, signatures)
        if reconfig.in_latest_decision:
            self.logger.debugf("Detected a reconfig in deliver")
            self._loop.call_soon_threadsafe(self._reconfig_queue.put_nowait, reconfig)
        return reconfig

    def sync(self) -> SyncResponse:
        """Synchronizer wrapper that detects reconfig (consensus.go:86-100).
        Runs on an executor thread."""
        sync_response = self.synchronizer.sync()
        if sync_response.reconfig.in_latest_decision:
            self.logger.debugf("Detected a reconfig in sync")
            self._loop.call_soon_threadsafe(
                self._reconfig_queue.put_nowait,
                Reconfig(
                    in_latest_decision=True,
                    current_nodes=sync_response.reconfig.current_nodes,
                    current_config=sync_response.reconfig.current_config,
                ),
            )
        return sync_response

    # ------------------------------------------------------------------ public

    def get_leader_id(self) -> int:
        """consensus.go:103-107 — zero when not running."""
        if not self._running or self.controller is None:
            return 0
        return self.controller.get_leader_id()

    def _wire_verify_plane(self) -> None:
        """Arm the verifier's verify-plane fault machinery from this node's
        Configuration (launch deadline, retry budget, breaker threshold,
        probe cadence) and attach the TPU metrics bundle, so breaker
        transitions are counted where the embedder can see them.  The
        coalescer fills only unset pieces (a shared cross-replica coalescer
        keeps its explicit settings); verifiers without the seam no-op."""
        configure = getattr(self.verifier, "configure_fault_policy", None)
        if configure is not None:
            from .crypto.provider import VerifyFaultPolicy

            try:
                configure(
                    policy=VerifyFaultPolicy.from_config(self.config),
                    metrics=self.metrics.tpu,
                )
            except Exception as e:  # noqa: BLE001 — wiring must not kill start
                self.logger.warnf("verify-plane fault wiring failed: %r", e)
        # per-sender misbehavior accounting (ISSUE 18): verifiers with the
        # seam feed every per-signer invalid verdict into this node's
        # MisbehaviorTable; verifiers without it stay attribution-only.
        configure_misbehavior = getattr(
            self.verifier, "configure_misbehavior", None)
        if configure_misbehavior is not None:
            try:
                configure_misbehavior(self.misbehavior)
            except Exception as e:  # noqa: BLE001 — wiring must not kill start
                self.logger.warnf("misbehavior-table wiring failed: %r", e)
        # occupancy-aware flush gating (verify_flush_hold): wired before
        # the mesh so a graduated engine's first waves already gate.
        # configure_hold keeps explicit constructor holds (the shared-
        # coalescer contract, like the fault policy).
        configure_hold = getattr(self.verifier, "configure_flush_hold", None)
        if configure_hold is not None:
            try:
                configure_hold(self.config.verify_flush_hold)
            except Exception as e:  # noqa: BLE001 — wiring must not kill start
                self.logger.warnf("verify flush-hold wiring failed: %r", e)
        # mesh graduation (verify_mesh_devices > 0): swap the coalescer's
        # engine onto an N-device mesh — 1D batch-axis or (topology "2d")
        # the seq x vote quorum mesh — idempotent across colocated
        # replicas sharing one coalescer and across reconfigs; an
        # unbuildable mesh downgrades loudly inside the provider (counted)
        # instead of raising, so only unexpected wiring errors land here.
        if self.config.verify_mesh_devices > 0:
            configure_mesh = getattr(self.verifier, "configure_verify_mesh",
                                     None)
            if configure_mesh is not None:
                # a pre-topology provider implementation gets the width
                # alone — probed by SIGNATURE, never by catching
                # TypeError (a TypeError raised inside mesh construction
                # must surface in the log, not silently downgrade a "2d"
                # config to the 1D mesh)
                kwargs = {"metrics": self.metrics.tpu}
                try:
                    import inspect

                    params = inspect.signature(configure_mesh).parameters
                    if "topology" in params:
                        kwargs["topology"] = self.config.verify_mesh_topology
                except (TypeError, ValueError):
                    # unsignaturable callable (C extension, mock): assume
                    # the current provider surface
                    kwargs["topology"] = self.config.verify_mesh_topology
                try:
                    configure_mesh(self.config.verify_mesh_devices, **kwargs)
                except Exception as e:  # noqa: BLE001 — ditto
                    self.logger.warnf("verify-mesh wiring failed: %r", e)

    async def start(self) -> None:
        """consensus.go:108-165."""
        self._loop = asyncio.get_running_loop()
        self.validate_configuration(self.comm.nodes())
        self._wire_verify_plane()
        # WAL persistence spans (ISSUE 13): the log records wal.append /
        # wal.fsync durations into this replica's recorder (and its own
        # bounded histograms either way); WALs without the seam no-op
        attach_wal_recorder = getattr(self.wal, "attach_recorder", None)
        if attach_wal_recorder is not None:
            attach_wal_recorder(self.recorder)

        self._set_nodes(self.comm.nodes())
        self.in_flight = InFlightData()
        self.state = PersistedState(
            self.in_flight, self.wal_initial_content, self.logger, self.wal,
            group_commit=self.config.wal_group_commit,
        )
        self.checkpoint.set(self.last_proposal, self.last_signatures)

        self._create_components()
        self._create_pool()
        self._continue_create_components()

        view, seq, dec = self._set_view_and_seq(
            self.metadata.view_id,
            self.metadata.latest_sequence,
            self.metadata.decisions_in_view,
        )

        self._run_task = create_logged_task(
            self._run(), name=f"consensus-{self.config.self_id}",
            logger=self.logger,
        )

        if self._own_scheduler:
            self._clock_driver = WallClockDriver(self.scheduler)
            self._clock_driver.start()

        await self._start_components(view, seq, dec, config_sync=True)
        self._running = True

    async def _run(self) -> None:
        """Reconfig/stop loop (consensus.go:167-184)."""
        try:
            while True:
                reconfig = await self._reconfig_queue.get()
                if reconfig is None:
                    return
                await self._reconfig(reconfig)
                if self._stopping:
                    return
        finally:
            self.logger.infof("Exiting")
            self._running = False

    async def _reconfig(self, reconfig: Reconfig) -> None:
        """consensus.go:186-253."""
        self.logger.debugf("Starting reconfig")
        await self.view_changer.stop()
        await self.controller.stop(pool_pause=True)
        self.collector.stop()
        self._stop_tickers()

        if self.config.self_id not in reconfig.current_nodes:
            self.logger.infof("Evicted in reconfiguration, shutting down")
            self._stopping = True
            return

        if reconfig.current_config is not None:
            self.config = reconfig.current_config.with_node_locals(self.config)
        try:
            self.validate_configuration(list(reconfig.current_nodes))
        except ValueError as e:
            if "does not contain the SelfID" in str(e):
                self._stopping = True
                return
            raise

        self._set_nodes(list(reconfig.current_nodes))
        self._wire_verify_plane()  # the reconfig may carry new verify knobs
        self._create_components()
        self.pool.change_options(
            self.controller,
            PoolOptions(
                queue_size=self.pool._opts.queue_size,
                forward_timeout=self.config.request_forward_timeout,
                complain_timeout=self.config.request_complain_timeout,
                auto_remove_timeout=self.config.request_auto_remove_timeout,
                request_max_bytes=self.config.request_max_bytes,
                submit_timeout=self.config.request_pool_submit_timeout,
                admission_high_water=self.config.admission_high_water,
                forward_timeout_fn=self._forward_timeout_fn(),
                flip_drain_limit=self._flip_drain_limit(),
            ),
        )
        self._continue_create_components()

        proposal, _ = self.checkpoint.get()
        md = decode(ViewMetadata, proposal.metadata) if proposal.metadata else ViewMetadata()
        view, seq, dec = self._set_view_and_seq(
            md.view_id, md.latest_sequence, md.decisions_in_view
        )
        await self._start_components(view, seq, dec, config_sync=False)
        self.pool.restart_timers()
        self.metrics.consensus.count_consensus_reconfig.add(1)
        self.logger.debugf("Reconfig is done")

    async def stop(self) -> None:
        """consensus.go:283-291."""
        self._stopping = True
        if self.view_changer is not None:
            await self.view_changer.stop()
        if self.controller is not None:
            await self.controller.stop()
        if self.collector is not None:
            self.collector.stop()
        self._stop_tickers()
        if self._clock_driver is not None:
            await self._clock_driver.stop()
            self._clock_driver = None
        self._reconfig_queue.put_nowait(None)
        if self._run_task is not None:
            await self._run_task
            self._run_task = None
        self._running = False

    def handle_message(self, sender: int, m: Message) -> None:
        """consensus.go:293-300 — membership filter then dispatch."""
        if sender not in self._node_set:
            self.logger.warnf("Received message from unexpected node %d", sender)
            return
        if self.controller is not None:
            self.controller.process_messages(sender, m)

    async def handle_message_async(self, sender: int, m: Message) -> None:
        """Async intake: lets a backpressure-configured cluster block the
        delivering transport task on full component inboxes (the
        reference's full-channel sender semantics, view.go:190)."""
        if sender not in self._node_set:
            self.logger.warnf("Received message from unexpected node %d", sender)
            return
        if self.controller is not None:
            await self.controller.process_messages_async(sender, m)

    def handle_message_batch(self, items) -> None:
        """Wave-batched intake: one transport tick's (sender, msg) pairs
        dispatched in a single call — consecutive view-bound runs register
        into the view as one wave (see Controller.process_messages_batch)."""
        filtered = self._filter_members(items)
        if filtered and self.controller is not None:
            self.controller.process_messages_batch(filtered)

    async def handle_message_batch_async(self, items) -> None:
        """Backpressure-capable mirror of :meth:`handle_message_batch`."""
        filtered = self._filter_members(items)
        if filtered and self.controller is not None:
            await self.controller.process_messages_batch_async(filtered)

    def _filter_members(self, items) -> list:
        filtered = []
        for sender, m in items:
            if sender not in self._node_set:
                self.logger.warnf("Received message from unexpected node %d", sender)
                continue
            filtered.append((sender, m))
        return filtered

    async def handle_request(self, sender: int, req: bytes):
        """Returns the pool-shed exception (admission / submit-timeout)
        when the forwarded request was refused by the overload machinery —
        the socket transport turns it into a structured REJECT frame for
        the forwarder — and None otherwise."""
        if self.controller is not None:
            return await self.controller.handle_request(sender, req)
        return None

    async def submit_request(self, req: bytes, *, internal: bool = False) -> None:
        """consensus.go:309-317.  ``internal`` marks a control-plane
        submission (reshard barrier, operator command): it bypasses the
        client-facing admission gate — under sustained overload the gate
        would otherwise shed the very commands that remediate the
        overload (a scale-out's barrier, a pool-resizing reconfig) —
        while still riding the pool's hard capacity bound and submit
        deadline."""
        if self.get_leader_id() == 0:
            raise RuntimeError("no leader")
        await self.controller.submit_request(req, forwarded=internal)

    def misbehavior_snapshot(self) -> dict:
        """This node's per-sender misbehavior accounting (ISSUE 18):
        lifetime cause counts, decayed shun scores, the current shun set,
        intake sheds, and shared-blacklist corroborations — read by the
        chaos oracles and the bench `byzantine` row."""
        return self.misbehavior.snapshot()

    def read_committed(self, key: str):
        """Read-plane entry (ISSUE 19): the embedder-registered committed-
        state read, or None when no hook is installed / nothing committed
        for ``key``.  Returns (value, height, state_digest, anchor_height)
        — the stamp a quorum-read client matches ``f+1`` ways and a
        follower-read client checks against its staleness bound.  Never
        touches the pool, the proposer, or the verify plane."""
        if self.read_hook is None:
            return None
        return self.read_hook(key)

    def delivery_frontier(self) -> dict:
        """The committed delivery frontier this replica has reached: the
        latest delivered sequence (checkpoint metadata), the view it
        belongs to, and the commit inter-arrival EWMA — the freshness
        reference a read client compares reply heights against (empty
        before start)."""
        if self.controller is None:
            return {}
        return self.controller.delivery_frontier()

    def pool_occupancy(self) -> dict:
        """This node's request-pool backpressure snapshot (empty before
        start).  The sharded front door (shard.ShardSet) reads this from
        each shard's submit target to expose one combined submit/
        backpressure surface over the per-shard pools."""
        if self.pool is None:
            return {}
        return self.pool.occupancy()

    def pool_pending_infos(self) -> list:
        """RequestInfos still pooled on this node (empty before start) —
        the per-shard drain probe of a live reshard (shard front doors
        union this over a shard's replicas to decide when a moved
        key-range has fully drained)."""
        if self.pool is None:
            return []
        return self.pool.pending_infos()

    # ------------------------------------------------------------------ wiring

    def validate_configuration(self, nodes: list[int]) -> None:
        """consensus.go:342-364."""
        self.config.validate()
        node_set = set()
        for val in nodes:
            if val == 0:
                raise ValueError(f"nodes contains node id 0 which is not permitted, nodes: {nodes}")
            node_set.add(val)
        if self.config.self_id not in node_set:
            raise ValueError(
                f"nodes does not contain the SelfID: {self.config.self_id}, nodes: {nodes}"
            )
        if len(node_set) != len(nodes):
            raise ValueError(f"nodes contains duplicate IDs, nodes: {nodes}")

    def _set_nodes(self, nodes: list[int]) -> None:
        self.nodes = sorted(nodes)
        self.num_nodes = len(nodes)
        self._node_set = set(nodes)

    def _create_components(self) -> None:
        """consensus.go:387-450."""
        self.view_changer = ViewChanger(
            self_id=self.config.self_id,
            n=self.num_nodes,
            nodes_list=self.nodes,
            leader_rotation=self.config.leader_rotation,
            # window granularity pre-multiplies by the window depth so every
            # get_leader_id / blacklist computation stays reference-shaped
            decisions_per_leader=self.config.effective_decisions_per_leader,
            speed_up_view_change=self.config.speed_up_view_change,
            logger=self.logger,
            signer=self.signer,
            verifier=self.verifier,
            checkpoint=self.checkpoint,
            in_flight=self.in_flight,
            state=self.state,
            resend_timeout=self.config.view_change_resend_interval,
            view_change_timeout=self.config.view_change_timeout,
            in_msg_q_size=self.config.incoming_message_buffer_size,
            backpressure=self.config.inbox_backpressure,
            metrics_view_change=self.metrics.view_change,
            metrics_blacklist=self.metrics.blacklist,
            metrics_view=self.metrics.view,
            vc_phases=self.vc_phases,
            recorder=self.recorder,
            # debounce clock for the event-driven standby prebuild
            scheduler=self.scheduler,
        )
        self.collector = StateCollector(
            self_id=self.config.self_id,
            n=self.num_nodes,
            logger=self.logger,
            collect_timeout=self.config.collect_timeout,
            scheduler=self.scheduler,
            # adaptive detection (ISSUE 15): the state-fetch leg of a
            # failover gives up on missing peers at measured network
            # scale instead of always burning the constant
            collect_timeout_fn=self._rtt_scaled_fn(),
        )
        view_sequences = ViewSequencesHolder()
        self.controller = Controller(
            self_id=self.config.self_id,
            n=self.num_nodes,
            nodes_list=self.nodes,
            leader_rotation=self.config.leader_rotation,
            decisions_per_leader=self.config.effective_decisions_per_leader,
            request_pool=self.pool,  # set for real in _create_pool on first start
            batcher=None,
            leader_monitor=None,
            verifier=self.verifier,
            logger=self.logger,
            assembler=self.assembler,
            application=self,  # facade: detects reconfigs (consensus.go:430)
            synchronizer=self,  # facade: detects reconfigs
            signer=self.signer,
            request_inspector=self.request_inspector,
            proposer_builder=None,
            checkpoint=self.checkpoint,
            failure_detector=self,  # facade: complain -> view changer
            view_changer=self.view_changer,
            collector=self.collector,
            state=self.state,
            in_flight=self.in_flight,
            comm=self.comm,
            view_sequences=view_sequences,
            metrics_view=self.metrics.view,
            metrics_consensus=self.metrics.consensus,
            recorder=self.recorder,
            vc_phases=self.vc_phases,
            # the commit inter-arrival EWMA lives in scheduler time — the
            # same domain as the heartbeat/complain timers it feeds
            clock=self.scheduler.now,
            # intake-side shun enforcement (ISSUE 18): survives reconfig
            # rebuilds because the table lives on the facade
            misbehavior=self.misbehavior,
        )
        # ViewChanger wiring (consensus.go:445-450,466-470)
        self.view_changer.application = self.controller.deliver
        self.view_changer.comm = self.controller
        self.view_changer.synchronizer = self.controller
        self.view_changer.controller = self.controller
        self.view_changer.pruner = self.controller
        self.view_changer.view_sequences = view_sequences

        self.controller.proposer_builder = self._proposal_maker(view_sequences)

    def _proposal_maker(self, view_sequences: ViewSequencesHolder) -> ProposalMaker:
        """consensus.go:319-340."""
        return ProposalMaker(
            decisions_per_leader=self.config.effective_decisions_per_leader,
            checkpoint=self.checkpoint,
            state=self.state,
            comm=self.controller,
            decider=self.controller,
            logger=self.logger,
            metrics_blacklist=self.metrics.blacklist,
            metrics_view=self.metrics.view,
            signer=self.signer,
            membership_notifier=self.membership_notifier,
            self_id=self.config.self_id,
            synchronizer=self.controller,
            failure_detector=self,
            verifier=self.verifier,
            n=self.num_nodes,
            nodes_list=self.nodes,
            in_msg_q_size=self.config.incoming_message_buffer_size,
            view_sequences=view_sequences,
            pipeline_depth=self.config.pipeline_depth,
            backpressure=self.config.inbox_backpressure,
            recorder=self.recorder,
        )

    def _forward_timeout_fn(self):
        """The RTT-derived forward-timeout provider (ISSUE 14
        satellite)."""
        return _scaled_rtt_fn(
            self.config.request_forward_rtt_multiplier, self.comm)

    def _rtt_scaled_fn(self):
        """The adaptive-detection RTT provider (ISSUE 15): shared by the
        heartbeat monitor's complain-timer derivation and the state
        collector's collect-timeout derivation — both legs of the same
        failover path."""
        return _scaled_rtt_fn(self.config.heartbeat_rtt_multiplier, self.comm)

    def _flip_drain_limit(self) -> int:
        """The flip-time backlog fast-forward budget in REQUESTS: enough
        to fill flip_drain_windows deep windows of the new view at once
        (ISSUE 15)."""
        return (self.config.flip_drain_windows
                * self.config.pipeline_depth
                * self.config.request_batch_max_count)

    def _create_pool(self) -> None:
        """consensus.go:139-151."""
        self.pool = Pool(
            self.logger,
            self.request_inspector,
            self.controller,
            PoolOptions(
                queue_size=self.config.request_pool_size,
                forward_timeout=self.config.request_forward_timeout,
                complain_timeout=self.config.request_complain_timeout,
                auto_remove_timeout=self.config.request_auto_remove_timeout,
                request_max_bytes=self.config.request_max_bytes,
                submit_timeout=self.config.request_pool_submit_timeout,
                admission_high_water=self.config.admission_high_water,
                forward_timeout_fn=self._forward_timeout_fn(),
                flip_drain_limit=self._flip_drain_limit(),
            ),
            self.scheduler,
            metrics=self.metrics.pool,
            recorder=self.recorder,
        )
        self.controller.request_pool = self.pool

    def _continue_create_components(self) -> None:
        """consensus.go:452-463."""
        batcher = BatchBuilder(
            self.pool,
            self.scheduler,
            self.config.request_batch_max_count,
            self.config.request_batch_max_bytes,
            self.config.request_batch_max_interval,
            adaptive=self.config.request_batch_adaptive,
            fill_slack=self.config.request_batch_fill_slack,
        )
        self.pool._on_submitted = batcher.on_submitted
        leader_monitor = HeartbeatMonitor(
            self.logger,
            self.config.leader_heartbeat_timeout,
            self.config.leader_heartbeat_count,
            self.controller,
            self.num_nodes,
            self.controller,
            self.controller.view_sequences,
            self.config.num_of_ticks_behind_before_syncing,
            pipeline_depth=self.config.pipeline_depth,
            # detection instrumentation (ROADMAP item 1): the silence-to-
            # complain interval lands in the VC phase tracker + the
            # viewchange metric bundle — round 15 showed DETECTION, not
            # the VC protocol, owns ~99% of the failover cliff
            vc_phases=self.vc_phases,
            # adaptive detection (ISSUE 15): the effective complain timer
            # derives from the transport's RTT EWMA and the controller's
            # commit inter-arrival EWMA, with the configured constant as
            # ceiling/fallback and anti-thrash backoff per repeated
            # complaint against the same view
            rtt_multiplier=self.config.heartbeat_rtt_multiplier,
            backoff_base=self.config.detection_backoff_base,
            backoff_max=self.config.detection_backoff_max,
            rtt_fn=getattr(self.comm, "rtt_seconds", None),
            commit_interval_fn=self.controller.commit_interval_seconds,
            metrics=self.metrics.view_change,
            # receipt-time clock for the observed-gap EWMA — the same
            # time domain as the ticks that consume the derived timer
            now_fn=self.scheduler.now,
        )
        self.controller.batcher = batcher
        self.controller.leader_monitor = leader_monitor
        self.view_changer.requests_timer = self.pool

    def _set_view_and_seq(self, view: int, seq: int, dec: int) -> tuple[int, int, int]:
        """consensus.go:465-505."""
        new_view, new_seq = view, seq
        # decisions in view is incremented after delivery; expect dec+1 next,
        # unless genesis
        new_dec = dec + 1
        if seq == 0:
            new_dec = 0
        view_change = self.state.load_view_change_if_applicable()
        self._restore_view_change = False
        if view_change is not None and view_change.next_view >= view:
            self.logger.debugf("Restoring from view change with view %d", view_change.next_view)
            new_view = view_change.next_view
            self._restore_view_change = True
        view_seq = self.state.load_new_view_if_applicable()
        if view_seq is not None and view_seq.seq >= seq:
            self.logger.debugf(
                "Restoring from new view with view %d and seq %d", view_seq.view, view_seq.seq
            )
            new_view = view_seq.view
            new_seq = view_seq.seq
            new_dec = 0
        return new_view, new_seq, new_dec

    async def _start_components(
        self, view: int, seq: int, dec: int, config_sync: bool
    ) -> None:
        """consensus.go:513-523 (+507-511 waitForEachOther barrier)."""
        self.collector.start()
        self.view_changer.controller_started_event = asyncio.Event()
        self.view_changer.start(view)
        if self._restore_view_change:
            self.view_changer.restore_trigger()
        self._tickers.append(
            Ticker(self.scheduler, self.viewchanger_tick_interval,
                   lambda: self.view_changer.tick(self.scheduler.now()))
        )
        self._tickers.append(
            # misbehavior decay (ISSUE 18): halve per-sender provable
            # scores on a fixed cadence — the redemption path that
            # releases shunned senders once they stop forging
            Ticker(self.scheduler, self.config.misbehavior_decay_interval,
                   lambda: self.misbehavior.decay())
        )
        self._tickers.append(
            # ADAPTIVE cadence (ISSUE 15): the monitor's check interval
            # derives from its effective complain timer (a quarter of it,
            # never above the configured base), closing the granularity
            # gap where a fixed tick let arm-to-fire overshoot a shrunk
            # timer by multiples.  The lambdas re-resolve the monitor so
            # a reconfig-rebuilt controller keeps feeding the live one.
            Ticker(self.scheduler, self.heartbeat_tick_interval,
                   lambda: self.controller.leader_monitor.tick(self.scheduler.now()),
                   interval_fn=lambda: self.controller.leader_monitor
                   .suggested_tick_interval(self.heartbeat_tick_interval))
        )
        try:
            await self.controller.start(
                view, seq + 1, dec, self.config.sync_on_start if config_sync else False
            )
        finally:
            # always release the barrier — a failed start must not leave the
            # viewchanger task parked forever (controller.go:813)
            self.view_changer.controller_started_event.set()

    def _stop_tickers(self) -> None:
        for t in self._tickers:
            t.stop()
        self._tickers.clear()
