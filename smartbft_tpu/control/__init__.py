"""Self-driving control plane (ISSUE 20): the verdict→action reflex arc.

PR 14 built the senses (declarative SLOs, multi-window burn-rate
verdicts) and earlier PRs built the actuators (breaker, occupancy
autoscaler, live reshard, ordered-stream reconfig, RTT-derived timers);
this package connects them.  Two layers, separable on purpose — the same
split :mod:`~smartbft_tpu.shard.autoscale` uses:

* :mod:`~smartbft_tpu.control.policy` — the pure DECISION core: health
  verdicts + live occupancy/RTT/drain EWMAs in, typed
  :class:`~smartbft_tpu.control.policy.Remediation` out, with per-action
  hysteresis, cooldowns re-armed on failure, a global anti-thrash
  budget, and a breaker/transition veto.  Injectable clock, no I/O.
* :mod:`~smartbft_tpu.control.loop` — the DRIVER: consumes one
  cluster's verdict stream and executes decisions through EXISTING seams
  only (``ShardSet.reshard`` for scale, ordered reconfig requests for
  derived-knob commits), so every automated action is itself an ordered,
  fork-free, exactly-once decision (the Vertical Paxos rule).
"""

from .loop import ControlLoop, run_control_loop
from .policy import (
    ControlPolicy,
    Remediation,
    TransitionArbiter,
    count_reversals,
    derive_knobs,
)

__all__ = [
    "ControlPolicy",
    "Remediation",
    "TransitionArbiter",
    "ControlLoop",
    "run_control_loop",
    "derive_knobs",
    "count_reversals",
]
