"""Driver for the self-driving control plane.

Connects one cluster's verdict stream to the pure policy core and
executes its decisions through EXISTING seams only:

* scale-out / scale-in → ``ShardSet.reshard`` (epoch-fenced, drains and
  re-parks in-flight work — PR 8 machinery, untouched);
* knob retunes → ``App.submit_reconfig`` on every shard, i.e. an
  ordered, internal, pool-deduplicated reconfig request.  The Vertical
  Paxos rule: an automated action IS an ordered decision, so remediation
  inherits fork-freedom and exactly-once from the stream it rides.

Every executed (or failed) action lands as a ``ctl.remediate``
flight-recorder span carrying cause → verdict → action, adjacent to the
``slo.breach`` span that triggered it on the merged timeline; the
matching ``ctl.clear`` span closes the arc when the verdict returns to
healthy.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
from typing import Any, Dict, List, Optional

from .policy import ControlPolicy, Remediation, TransitionArbiter

__all__ = ["ControlLoop", "run_control_loop"]

OWNER = "controller"


class ControlLoop:
    """Tick-driven reflex arc for one :class:`ShardedCluster`.

    ``tick()`` is synchronous decision + bookkeeping; ``step()`` is
    ``tick()`` plus execution of whatever it decided.  The split keeps
    the decision path testable without an event loop and lets the chaos
    harness drive ticks on the logical clock.
    """

    def __init__(
        self,
        cluster,
        *,
        policy: Optional[ControlPolicy] = None,
        arbiter: Optional[TransitionArbiter] = None,
        recorder=None,
        logger: Optional[logging.Logger] = None,
    ) -> None:
        self.cluster = cluster
        self.base_config = cluster.base_config
        self.current_config = self.base_config
        self.policy = policy or ControlPolicy.from_config(
            self.base_config, clock=cluster.scheduler.now
        )
        self.arbiter = arbiter or TransitionArbiter()
        if recorder is None:
            recorder = cluster._recorder_for("ctl")
        if recorder is None:  # trace=False clusters hand out None
            from ..obs import NOP_RECORDER

            recorder = NOP_RECORDER
        self.recorder = recorder
        self.logger = logger or logging.getLogger("smartbft.control")
        self.executed: List[Dict[str, Any]] = []
        self._awaiting_clear: Optional[str] = None
        self._retune_seq = 0

    # ------------------------------------------------------------------
    # signal sampling

    def sample(self) -> Dict[str, Any]:
        """Live EWMAs from the cluster: occupancy, RTT, commit gap, drain.

        RTT/commit-gap take the max over live nodes (the slowest link is
        what forward timeouts must cover); drain rate sums over shards
        (the outbox cap serves aggregate throughput).  In-process comms
        have no RTT estimator — ``rtt_s`` is then ``None`` and the
        forward-timeout knob simply is not derived.
        """
        occ = self.cluster.set.occupancy()
        rtt: Optional[float] = None
        gap: Optional[float] = None
        drain = 0.0
        for shard in self.cluster.shard_list:
            for app in shard.live_apps():
                cons = app.consensus
                if cons is not None:
                    frontier = cons.delivery_frontier()
                    g = frontier.get("commit_gap_s")
                    if g is not None and g > 0.0:
                        gap = g if gap is None else max(gap, g)
                comm = getattr(app, "comm", None)
                rtt_fn = getattr(comm, "rtt_seconds", None)
                if rtt_fn is not None:
                    r = rtt_fn()
                    if r is not None and r > 0.0:
                        rtt = r if rtt is None else max(rtt, r)
            pocc = shard.pool_occupancy()
            drain += float(pocc.get("drain_rate", 0.0) or 0.0)
        return {
            "occupancy": occ,
            "rtt_s": rtt,
            "commit_gap_s": gap,
            "drain_rate": drain if drain > 0.0 else None,
        }

    # ------------------------------------------------------------------
    # decision

    def tick(self) -> Remediation:
        verdict = self.cluster.health.tick()
        signals = self.sample()
        in_transition = (
            self.cluster.set.reshard_in_progress or self.arbiter.holder is not None
        )
        breaker_open = bool(getattr(self.cluster.coalescer, "breaker_open", False))
        rem = self.policy.decide(
            verdict,
            signals,
            num_shards=self.cluster.set.num_shards,
            in_transition=in_transition,
            breaker_open=breaker_open,
            current_config=self.current_config,
            base_config=self.base_config,
        )
        status = verdict.get("status")
        if self._awaiting_clear is not None and status == "healthy":
            if self.recorder.enabled:
                self.recorder.record(
                    "ctl.clear",
                    node="ctl",
                    extra={"after": self._awaiting_clear},
                )
            self._awaiting_clear = None
        rem.__dict__["_verdict_status"] = status  # carried for the span
        return rem

    # ------------------------------------------------------------------
    # execution

    async def _execute_scale(self, rem: Remediation) -> bool:
        if not self.arbiter.try_acquire(OWNER):
            # Legacy autoscaler (or a prior action) owns the transition;
            # treat as failed so the cooldown re-arms and we re-evaluate
            # against the post-transition topology.
            return False
        try:
            await self.cluster.reshard(rem.target_shards)
            return True
        except Exception:
            self.logger.exception("controller reshard to %d failed", rem.target_shards)
            return False
        finally:
            self.arbiter.release(OWNER)

    async def _execute_retune(self, rem: Remediation) -> bool:
        new_cfg = dataclasses.replace(self.current_config, **rem.knobs)
        self._retune_seq += 1
        rid = "ctl-retune-%d" % self._retune_seq
        ok = True
        for shard in self.cluster.shard_list:
            try:
                app = shard._submit_app()
                await app.submit_reconfig(
                    "%s-s%d" % (rid, shard.shard_id),
                    [a.id for a in shard.apps],
                    new_cfg,
                )
            except Exception:
                self.logger.exception(
                    "retune reconfig on shard %d failed", shard.shard_id
                )
                ok = False
        if ok:
            self.current_config = new_cfg
        return ok

    async def execute(self, rem: Remediation) -> bool:
        if rem.status != "act":
            return False
        t0 = self.cluster.scheduler.now()
        if rem.action in ("scale_out", "scale_in"):
            ok = await self._execute_scale(rem)
        elif rem.action == "retune":
            ok = await self._execute_retune(rem)
        else:
            return False
        self.policy.note_result(rem, ok)
        self._awaiting_clear = rem.action
        if self.recorder.enabled:
            self.recorder.record(
                "ctl.remediate",
                node="ctl",
                dur=self.cluster.scheduler.now() - t0,
                extra={
                    "cause": rem.cause,
                    "verdict": rem.__dict__.get("_verdict_status", ""),
                    "action": rem.action,
                    "ok": ok,
                    "target": rem.target_shards,
                    "knobs": dict(rem.knobs),
                    "reason": rem.reason,
                },
            )
        self.executed.append({**rem.as_dict(), "ok": ok})
        return ok

    async def step(self) -> Remediation:
        rem = self.tick()
        if rem.status == "act":
            await self.execute(rem)
        return rem

    def snapshot(self) -> Dict[str, Any]:
        return {
            "policy": self.policy.snapshot(),
            "executed": list(self.executed),
            "arbiter": {
                "holder": self.arbiter.holder,
                "acquired": self.arbiter.acquired,
                "contended": self.arbiter.contended,
            },
        }


async def run_control_loop(
    cluster,
    *,
    loop: Optional[ControlLoop] = None,
    interval: Optional[float] = None,
    stop: Optional[asyncio.Event] = None,
) -> ControlLoop:
    """Wall-clock driver mirroring ``run_autoscaler``: tick every
    ``interval`` seconds until ``stop`` is set.  Returns the loop so the
    caller can read its snapshot."""
    ctl = loop or ControlLoop(cluster)
    period = interval if interval is not None else ctl.policy.interval
    stop = stop or asyncio.Event()
    while not stop.is_set():
        try:
            await asyncio.wait_for(stop.wait(), timeout=period)
        except asyncio.TimeoutError:
            pass
        if stop.is_set():
            break
        await ctl.step()
    return ctl
