"""Pure decision core for the self-driving control plane.

Verdict stream in, typed :class:`Remediation` out.  No I/O, no awaits,
injectable clock — the same discipline as
:class:`~smartbft_tpu.shard.autoscale.OccupancyAutoscaler`, which this
core folds in (occupancy saturation is one of its scale-out causes).

The anti-thrash machinery layers four independent guards, applied to a
*candidate* action (so the veto counters measure suppressed real actions,
not idle ticks):

1. transition/breaker veto — never act mid-reshard or while the verify
   host-fallback breaker is open (the system is already remediating);
2. per-action cooldown — re-armed on failure as well as success, so a
   reshard that errors out does not get retried in a tight loop;
3. global budget — at most ``control_budget_actions`` actions per
   ``control_budget_window`` seconds across ALL action kinds;
4. hysteresis reversal guard — an action that undoes a recent one
   (scale-in after scale-out, a knob flipped back to its previous value)
   is vetoed inside ``control_hysteresis`` seconds.  This is the Mir-BFT
   thrash lesson: oscillation is worse than either steady state.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "Remediation",
    "TransitionArbiter",
    "ControlPolicy",
    "derive_knobs",
    "count_reversals",
]

# Derived forward timeouts below this are noise: a non-trivial quorum
# round trip cannot complete faster regardless of measured RTT.
FORWARD_FLOOR_S = 0.010

# Never derive an outbox cap below this; a tiny cap would wedge the
# transport the controller is trying to tune.
OUTBOX_FLOOR = 256


@dataclass
class Remediation:
    """One decision: what to do (or why nothing was done) and why.

    ``status`` is ``"act"`` for an executable decision, ``"veto"`` when a
    candidate action was suppressed by a guard, and ``"idle"`` when no
    candidate existed.  Only ``"act"`` entries consume cooldown/budget.
    """

    action: str  # "scale_out" | "scale_in" | "retune" | "none"
    cause: str  # triggering SLO/signal name, e.g. "latency.commit_p99_ms"
    status: str  # "act" | "veto" | "idle"
    reason: str
    at: float
    target_shards: int = 0
    knobs: Dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "action": self.action,
            "cause": self.cause,
            "status": self.status,
            "reason": self.reason,
            "at": round(self.at, 3),
            "target_shards": self.target_shards,
            "knobs": dict(self.knobs),
        }


class TransitionArbiter:
    """Mutual exclusion between topology-transition initiators.

    The legacy ``run_autoscaler`` loop and the control loop can both
    decide to reshard; whichever acquires the arbiter first owns the
    transition and the other's attempt is counted and dropped (it will
    re-evaluate on its next tick against the post-transition topology).
    Strictly non-reentrant: a second ``try_acquire`` by the SAME owner
    while held also fails, which turns any accounting bug into a loud
    stall instead of a silent double transition.
    """

    def __init__(self) -> None:
        self._holder: Optional[str] = None
        self.acquired = 0
        self.contended = 0

    @property
    def holder(self) -> Optional[str]:
        return self._holder

    def try_acquire(self, owner: str) -> bool:
        if self._holder is not None:
            self.contended += 1
            return False
        self._holder = owner
        self.acquired += 1
        return True

    def release(self, owner: str) -> None:
        if self._holder == owner:
            self._holder = None


def _quantize_s(x: float) -> float:
    # Millisecond quantization: reconfig mirrors carry *_ms ints, so
    # sub-ms drift in a derived value would otherwise retune forever.
    return round(x, 3)


def derive_knobs(
    base,
    current,
    *,
    rtt_s: Optional[float] = None,
    commit_gap_s: Optional[float] = None,
    drain_rate: Optional[float] = None,
) -> Dict[str, Any]:
    """Recompute timer/hold/cap knobs from measured EWMAs.

    The PR 15 derivation pattern generalized: each knob is
    ``multiplier x EWMA`` clamped to ``[floor, BASE-config value]``.
    Ceilings come from the *base* (boot-time) config, never the current
    one, so repeated retunes can only move within the operator's
    envelope — they cannot ratchet it.

    A knob is included only when it moved by more than
    ``control_knob_deadband`` (relative) from ``current``; the deadband
    plus ms quantization is what makes a retune converge in one commit
    instead of livelocking on EWMA jitter.
    """
    candidates: Dict[str, Any] = {}
    if rtt_s is not None and rtt_s > 0.0:
        fwd = base.control_forward_rtt_multiplier * rtt_s
        fwd = min(max(fwd, FORWARD_FLOOR_S), base.request_forward_timeout)
        candidates["request_forward_timeout"] = _quantize_s(fwd)
    if commit_gap_s is not None and commit_gap_s > 0.0:
        hold = base.control_hold_commit_multiplier * commit_gap_s
        hold = min(max(hold, 0.0), base.request_batch_max_interval)
        candidates["verify_flush_hold"] = _quantize_s(hold)
    if drain_rate is not None and drain_rate > 0.0:
        cap = int(drain_rate * base.control_outbox_drain_window)
        cap = min(max(cap, OUTBOX_FLOOR), base.transport_outbox_cap)
        candidates["transport_outbox_cap"] = cap

    deadband = base.control_knob_deadband
    knobs: Dict[str, Any] = {}
    for name, new in candidates.items():
        cur = getattr(current, name)
        if abs(new - cur) / max(abs(cur), 1e-9) > deadband:
            knobs[name] = new
    return knobs


def count_reversals(
    decisions: List[Tuple[float, str, str]], window: float
) -> int:
    """Count A→B→A flips within ``window`` in a policy decision log.

    A reversal is a ``scale_in`` within ``window`` of a ``scale_out`` (or
    vice versa).  Pure so the chaos invariant and the bench row share one
    definition of "oscillation".
    """
    reversals = 0
    opposite = {"scale_out": "scale_in", "scale_in": "scale_out"}
    acts = [(t, a) for (t, a, _why) in decisions if a in opposite]
    for i, (t, a) in enumerate(acts):
        for (t2, a2) in acts[i + 1 :]:
            if t2 - t > window:
                break
            if a2 == opposite[a]:
                reversals += 1
    return reversals


class ControlPolicy:
    """Verdicts + live signals in, :class:`Remediation` out.

    Candidate first, veto second: each tick we first determine what the
    signals *call for* (scale-out on a commit-latency burn or occupancy
    saturation, scale-in on sustained idle, a knob retune while
    unhealthy), and only then run the candidate through the guard chain.
    Retunes are gated on an unhealthy verdict on purpose: a healthy
    steady state produces zero actions, which is exactly the
    "zero actions outside fault windows" chaos invariant.
    """

    def __init__(
        self,
        *,
        interval: float = 1.0,
        cooldown: float = 30.0,
        hysteresis: float = 120.0,
        idle_hold: float = 60.0,
        budget_actions: int = 4,
        budget_window: float = 300.0,
        min_shards: int = 1,
        max_shards: int = 8,
        high_occupancy: float = 0.85,
        low_occupancy: float = 0.25,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.interval = float(interval)
        self.cooldown = float(cooldown)
        self.hysteresis = float(hysteresis)
        self.idle_hold = float(idle_hold)
        self.budget_actions = int(budget_actions)
        self.budget_window = float(budget_window)
        self.min_shards = int(min_shards)
        self.max_shards = int(max_shards)
        self.high_occupancy = float(high_occupancy)
        self.low_occupancy = float(low_occupancy)
        self.clock = clock

        self._cooldown_until: Dict[str, float] = {}
        self._actions: List[Tuple[float, str]] = []  # acted only
        self._knob_history: List[Tuple[float, str, Any, Any]] = []
        self._idle_since: Optional[float] = None
        self._last_shed = 0
        self.decisions: List[Tuple[float, str, str]] = []  # acted only
        self.counters: Dict[str, int] = {
            "ticks": 0,
            "decisions": 0,
            "succeeded": 0,
            "failed": 0,
            "veto_transition": 0,
            "veto_breaker": 0,
            "veto_cooldown": 0,
            "veto_budget": 0,
            "veto_reversal": 0,
            "scale_out": 0,
            "scale_in": 0,
            "retune": 0,
        }

    @classmethod
    def from_config(cls, config, *, clock: Callable[[], float] = time.monotonic) -> "ControlPolicy":
        return cls(
            interval=config.control_interval,
            cooldown=config.control_cooldown,
            hysteresis=config.control_hysteresis,
            idle_hold=config.control_idle_hold,
            budget_actions=config.control_budget_actions,
            budget_window=config.control_budget_window,
            min_shards=config.autoscale_min_shards,
            max_shards=config.autoscale_max_shards,
            high_occupancy=config.autoscale_high_occupancy,
            low_occupancy=config.autoscale_low_occupancy,
            clock=clock,
        )

    # ------------------------------------------------------------------
    # candidate detection

    def _breach_names(self, verdict: Dict[str, Any]) -> List[str]:
        return [r.get("slo", "") for r in verdict.get("reasons", ())]

    def _saturated(self, occ: Dict[str, Any]) -> bool:
        # Folded OccupancyAutoscaler saturation test: pressure shows up
        # as fill, parked waiters, or fresh admission shedding.
        if occ.get("total_capacity", 0) == 0:
            return False
        shed = int(occ.get("shed_admission", 0)) + int(occ.get("shed_timeout", 0))
        shed_delta = shed - self._last_shed
        self._last_shed = shed
        return (
            occ.get("fill", 0.0) >= self.high_occupancy
            or occ.get("total_waiters", 0) > 0
            or shed_delta > 0
        )

    def _idle(self, occ: Dict[str, Any], healthy: bool) -> bool:
        return (
            healthy
            and occ.get("total_capacity", 0) != 0
            and occ.get("fill", 1.0) <= self.low_occupancy
            and occ.get("total_waiters", 0) == 0
        )

    def _candidate(
        self,
        verdict: Dict[str, Any],
        signals: Dict[str, Any],
        *,
        num_shards: int,
        current_config,
        base_config,
        now: float,
    ) -> Optional[Remediation]:
        breaches = self._breach_names(verdict)
        healthy = verdict.get("status") == "healthy"
        occ = signals.get("occupancy", {}) or {}
        saturated = self._saturated(occ)

        # Scale out BEFORE the knee: the commit-latency burn fires while
        # queueing delay grows but occupancy has not yet pinned.
        if "latency.commit_p99_ms" in breaches and num_shards < self.max_shards:
            return Remediation(
                action="scale_out",
                cause="latency.commit_p99_ms",
                status="act",
                reason="commit p99 burn-rate breach",
                at=now,
                target_shards=min(num_shards + 1, self.max_shards),
            )
        if saturated and num_shards < self.max_shards:
            return Remediation(
                action="scale_out",
                cause="pool.fill",
                status="act",
                reason="occupancy saturated (fill/waiters/shed)",
                at=now,
                target_shards=min(num_shards + 1, self.max_shards),
            )

        # Sustained idle → scale in (tracked across ticks; any
        # non-idle tick resets the hold timer).
        if self._idle(occ, healthy):
            if self._idle_since is None:
                self._idle_since = now
            if (
                now - self._idle_since >= self.idle_hold
                and num_shards > self.min_shards
            ):
                return Remediation(
                    action="scale_in",
                    cause="pool.fill",
                    status="act",
                    reason="sustained idle >= %.0fs" % self.idle_hold,
                    at=now,
                    target_shards=max(num_shards - 1, self.min_shards),
                )
        else:
            self._idle_since = None

        # Retune only while unhealthy: derive timer/hold/cap knobs from
        # the measured EWMAs and commit whatever cleared the deadband.
        if not healthy and current_config is not None and base_config is not None:
            knobs = derive_knobs(
                base_config,
                current_config,
                rtt_s=signals.get("rtt_s"),
                commit_gap_s=signals.get("commit_gap_s"),
                drain_rate=signals.get("drain_rate"),
            )
            knobs = self._filter_knob_reversals(knobs, current_config, now)
            if knobs:
                cause = breaches[0] if breaches else "health.degraded"
                return Remediation(
                    action="retune",
                    cause=cause,
                    status="act",
                    reason="re-derive knobs from RTT/commit-gap/drain EWMAs",
                    at=now,
                    knobs=knobs,
                )
        return None

    def _filter_knob_reversals(
        self, knobs: Dict[str, Any], current_config, now: float
    ) -> Dict[str, Any]:
        # Drop any knob that would flip back to the value it held before
        # the most recent change inside the hysteresis window (A→B→A).
        kept: Dict[str, Any] = {}
        for name, new in knobs.items():
            reverted = False
            for (t, field_name, old, _new) in reversed(self._knob_history):
                if now - t > self.hysteresis:
                    break
                if field_name == name and old == new:
                    reverted = True
                    break
            if not reverted:
                kept[name] = new
        return kept

    # ------------------------------------------------------------------
    # veto chain

    def _veto(
        self,
        cand: Remediation,
        *,
        in_transition: bool,
        breaker_open: bool,
        now: float,
    ) -> Optional[Remediation]:
        def vetoed(counter: str, reason: str) -> Remediation:
            self.counters[counter] += 1
            return Remediation(
                action=cand.action,
                cause=cand.cause,
                status="veto",
                reason=reason,
                at=now,
                target_shards=cand.target_shards,
                knobs=dict(cand.knobs),
            )

        if in_transition:
            return vetoed("veto_transition", "reshard/reconfig transition in progress")
        if breaker_open:
            return vetoed("veto_breaker", "verify breaker open (host fallback active)")
        until = self._cooldown_until.get(cand.action, 0.0)
        if now < until:
            return vetoed(
                "veto_cooldown", "%s cooldown until t=%.1f" % (cand.action, until)
            )
        recent = [t for (t, _a) in self._actions if now - t <= self.budget_window]
        if len(recent) >= self.budget_actions:
            return vetoed(
                "veto_budget",
                "anti-thrash budget: %d actions within %.0fs"
                % (len(recent), self.budget_window),
            )
        if cand.action in ("scale_out", "scale_in"):
            opposite = "scale_in" if cand.action == "scale_out" else "scale_out"
            for (t, a) in reversed(self._actions):
                if now - t > self.hysteresis:
                    break
                if a == opposite:
                    return vetoed(
                        "veto_reversal",
                        "would reverse %s from t=%.1f within hysteresis" % (a, t),
                    )
        return None

    # ------------------------------------------------------------------
    # public surface

    def decide(
        self,
        verdict: Dict[str, Any],
        signals: Dict[str, Any],
        *,
        num_shards: int,
        in_transition: bool = False,
        breaker_open: bool = False,
        current_config=None,
        base_config=None,
    ) -> Remediation:
        now = self.clock()
        self.counters["ticks"] += 1
        cand = self._candidate(
            verdict,
            signals,
            num_shards=num_shards,
            current_config=current_config,
            base_config=base_config,
            now=now,
        )
        if cand is None:
            return Remediation(
                action="none", cause="", status="idle", reason="no candidate", at=now
            )
        veto = self._veto(
            cand, in_transition=in_transition, breaker_open=breaker_open, now=now
        )
        if veto is not None:
            return veto

        # Commit the decision to history: cooldown, budget window,
        # per-knob hysteresis bookkeeping.
        self.counters["decisions"] += 1
        self.counters[cand.action] += 1
        self._cooldown_until[cand.action] = now + self.cooldown
        self._actions.append((now, cand.action))
        self.decisions.append((now, cand.action, cand.reason))
        if cand.action == "retune" and current_config is not None:
            for name, new in cand.knobs.items():
                self._knob_history.append(
                    (now, name, getattr(current_config, name), new)
                )
        if cand.action in ("scale_out", "scale_in"):
            self._idle_since = None
        return cand

    def note_result(self, rem: Remediation, ok: bool) -> None:
        """Record execution outcome; failure re-arms the cooldown.

        Re-arming from *completion* time matters: a reshard that takes
        20s to fail would otherwise have burned most of its cooldown
        before the failure was even known.
        """
        if rem.status != "act":
            return
        if ok:
            self.counters["succeeded"] += 1
        else:
            self.counters["failed"] += 1
            self._cooldown_until[rem.action] = self.clock() + self.cooldown

    def reversals(self) -> int:
        return count_reversals(self.decisions, self.hysteresis)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "counters": dict(self.counters),
            "decisions": list(self.decisions),
            "reversals": self.reversals(),
            "cooldowns": dict(self._cooldown_until),
        }
