"""Batch builder: blocks until the batch is full or a timeout elapses.

Re-design of /root/reference/internal/bft/batcher.go:13-92.  The reference's
``select {closeChan, timeout, submittedChan}`` becomes an asyncio wait over a
submitted-event and a scheduler timer — closing the reference's TODO
("use task-scheduler based on logical time", batcher.go:46): the timeout
runs on the shared logical-time Scheduler, so tests drive it
deterministically.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..utils.clock import Scheduler
from .pool import Pool


class BatchBuilder:
    def __init__(
        self,
        pool: Pool,
        scheduler: Scheduler,
        max_msg_count: int,
        max_size_bytes: int,
        batch_timeout: float,
    ):
        self._pool = pool
        self._scheduler = scheduler
        self._max_msg_count = max_msg_count
        self._max_size_bytes = max_size_bytes
        self._batch_timeout = batch_timeout
        self._closed = False
        self._wakeup: Optional[asyncio.Future] = None
        self._pending_signal = False

    def on_submitted(self) -> None:
        """Wired as the pool's submitted signal (1-slot, like the reference's
        buffered submittedChan)."""
        if self._wakeup is not None and not self._wakeup.done():
            self._wakeup.set_result("submitted")
        else:
            self._pending_signal = True

    async def next_batch(self) -> Optional[list[bytes]]:
        """Return the next proposal batch; None if closed (batcher.go:40-63)."""
        batch, full = self._pool.next_requests(
            self._max_msg_count, self._max_size_bytes, check=True
        )
        if full:
            return batch
        if self._closed:
            return None

        deadline = self._scheduler.now() + self._batch_timeout
        timer = self._scheduler.schedule(self._batch_timeout, self._on_timeout)
        try:
            while True:
                if self._pending_signal:
                    self._pending_signal = False
                else:
                    self._wakeup = asyncio.get_running_loop().create_future()
                    reason = await self._wakeup
                    self._wakeup = None
                    if reason == "closed":
                        return None
                    if reason == "timeout":
                        batch, _ = self._pool.next_requests(
                            self._max_msg_count, self._max_size_bytes, check=False
                        )
                        return batch
                if self._closed:
                    return None
                if self._scheduler.now() >= deadline:
                    batch, _ = self._pool.next_requests(
                        self._max_msg_count, self._max_size_bytes, check=False
                    )
                    return batch
                batch, full = self._pool.next_requests(
                    self._max_msg_count, self._max_size_bytes, check=True
                )
                if full:
                    return batch
        finally:
            timer.cancel()
            self._wakeup = None

    def _on_timeout(self) -> None:
        if self._wakeup is not None and not self._wakeup.done():
            self._wakeup.set_result("timeout")

    def close(self) -> None:
        self._closed = True
        if self._wakeup is not None and not self._wakeup.done():
            self._wakeup.set_result("closed")

    def closed(self) -> bool:
        return self._closed

    def reset(self) -> None:
        self._closed = False
        self._pending_signal = False
