"""Batch builder: blocks until the batch is full or a timeout elapses.

Re-design of /root/reference/internal/bft/batcher.go:13-92.  The reference's
``select {closeChan, timeout, submittedChan}`` becomes an asyncio wait over a
submitted-event and a scheduler timer — closing the reference's TODO
("use task-scheduler based on logical time", batcher.go:46): the timeout
runs on the shared logical-time Scheduler, so tests drive it
deterministically.

Arrival-driven mode (``adaptive=True``): the fixed cadence above taxes every
partial wave with the full ``batch_timeout`` even when the pool's arrival
rate says the wave can never fill in time.  Adaptive mode applies the
TagRateTracker/occupancy-gating idiom to the proposer: on every wakeup it
compares the wave's remaining deficit against what the pool's arrival-rate
EWMA predicts will land before the deadline, and proposes IMMEDIATELY once
the fill is implausible (``deficit > rate * fill_slack * time_left``).  A
wave the rate predicts WILL fill still forms to full depth, so saturation
keeps its deep amortizing batches; the deadline stays the hard bound.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..utils.clock import Scheduler
from .pool import Pool


class BatchBuilder:
    def __init__(
        self,
        pool: Pool,
        scheduler: Scheduler,
        max_msg_count: int,
        max_size_bytes: int,
        batch_timeout: float,
        adaptive: bool = False,
        fill_slack: float = 1.0,
    ):
        self._pool = pool
        self._scheduler = scheduler
        self._max_msg_count = max_msg_count
        self._max_size_bytes = max_size_bytes
        self._batch_timeout = batch_timeout
        self._adaptive = adaptive
        self._fill_slack = fill_slack
        self._closed = False
        self._wakeup: Optional[asyncio.Future] = None
        self._pending_signal = False
        #: proposes cut short by the fill prediction (observability)
        self.early_proposes = 0

    def on_submitted(self) -> None:
        """Wired as the pool's submitted signal (1-slot, like the reference's
        buffered submittedChan)."""
        if self._wakeup is not None and not self._wakeup.done():
            self._wakeup.set_result("submitted")
        else:
            self._pending_signal = True

    def _fill_implausible(self, deadline: float) -> bool:
        """Adaptive gate: can the wave still plausibly reach max_msg_count
        before ``deadline`` at the measured arrival rate?  Rate 0 (idle or
        cold pool) makes any deficit implausible — the no-load case where
        waiting out the cadence buys nothing."""
        deficit = self._max_msg_count - self._pool.available_count()
        if deficit <= 0:
            return False  # already full; the caller's full-check wins
        remaining = deadline - self._scheduler.now()
        if remaining <= 0:
            return True
        return deficit > self._pool.arrival_rate() * self._fill_slack * remaining

    async def next_batch(self) -> Optional[list[bytes]]:
        """Return the next proposal batch; None if closed (batcher.go:40-63)."""
        batch, full = self._pool.next_requests(
            self._max_msg_count, self._max_size_bytes, check=True
        )
        if full:
            return batch
        if self._closed:
            return None

        deadline = self._scheduler.now() + self._batch_timeout
        if self._adaptive and self._fill_implausible(deadline):
            # the wave cannot fill in time: propose whatever is pooled NOW
            # instead of paying the cadence.  An empty pool falls through
            # to the wait — there is nothing to propose early.
            batch, _ = self._pool.next_requests(
                self._max_msg_count, self._max_size_bytes, check=False
            )
            if batch:
                self.early_proposes += 1
                return batch
        timer = self._scheduler.schedule(self._batch_timeout, self._on_timeout)
        try:
            while True:
                if self._pending_signal:
                    self._pending_signal = False
                else:
                    self._wakeup = asyncio.get_running_loop().create_future()
                    reason = await self._wakeup
                    self._wakeup = None
                    if reason == "closed":
                        return None
                    if reason == "timeout":
                        batch, _ = self._pool.next_requests(
                            self._max_msg_count, self._max_size_bytes, check=False
                        )
                        return batch
                if self._closed:
                    return None
                if self._scheduler.now() >= deadline:
                    batch, _ = self._pool.next_requests(
                        self._max_msg_count, self._max_size_bytes, check=False
                    )
                    return batch
                batch, full = self._pool.next_requests(
                    self._max_msg_count, self._max_size_bytes, check=True
                )
                if full:
                    return batch
                if self._adaptive and self._fill_implausible(deadline):
                    batch, _ = self._pool.next_requests(
                        self._max_msg_count, self._max_size_bytes, check=False
                    )
                    if batch:
                        self.early_proposes += 1
                        return batch
        finally:
            timer.cancel()
            self._wakeup = None

    def _on_timeout(self) -> None:
        if self._wakeup is not None and not self._wakeup.done():
            self._wakeup.set_result("timeout")

    def close(self) -> None:
        self._closed = True
        if self._wakeup is not None and not self._wakeup.done():
            self._wakeup.set_result("closed")

    def closed(self) -> bool:
        return self._closed

    def reset(self) -> None:
        self._closed = False
        self._pending_signal = False
