"""The Controller: orchestrates views, decisions, sync, and leadership.

Re-design of /root/reference/internal/bft/controller.go:88-965.  The
reference's ``run()`` goroutine selects over decision / view-change /
abort-view / leader-token / sync channels; here those become one typed event
queue drained by a single asyncio task, which preserves the reference's
ordering guarantees (a queued decision is always delivered before a
subsequently queued abort) without channel machinery.

The Decide handoff keeps the reference's rendezvous semantics
(controller.go:873-890): the View awaits a future that the controller loop
resolves only after the application delivered the decision.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Optional

from ..api import (
    Application,
    Assembler,
    Comm,
    Logger,
    RequestInspector,
    Signer,
    Synchronizer,
    Verifier,
)
from ..codec import decode
from ..messages import (
    Commit,
    HeartBeat,
    HeartBeatResponse,
    Message,
    NewView,
    NewViewRecord,
    PrePrepare,
    Prepare,
    SignedViewData,
    StateTransferRequest,
    StateTransferResponse,
    ViewChange,
    ViewMetadata,
)
from ..metrics import ConsensusMetrics, ViewMetrics
from ..types import (
    Checkpoint,
    Proposal,
    Reconfig,
    RequestInfo,
    ViewAndSeq,
    blacklist_of,
    cached_view_metadata,
)
from .pool import Pool, RequestTimeoutHandler, remove_delivered_requests
from .state import ABORT, COMMITTED
from .util import InFlightData, compute_quorum, get_leader_id
from ..utils.tasks import create_logged_task
from .view import (
    ViewSequence,
    ViewSequencesHolder,
    proposal_sequence_of_msg,
    view_number_of_msg,
)


@dataclass
class _Decision:
    proposal: Proposal
    signatures: list
    requests: list
    done: asyncio.Future


@dataclass
class _ViewChangeEvt:
    view_number: int
    proposal_seq: int


@dataclass
class _AbortViewEvt:
    view: int


class _ProposeEvt:
    pass


class _SyncEvt:
    pass


class _StopEvt:
    pass


class Controller(RequestTimeoutHandler):
    """Composed by the Consensus facade; fields mirror controller.go:88-144."""

    def __init__(
        self,
        *,
        self_id: int,
        n: int,
        nodes_list: list[int],
        leader_rotation: bool,
        decisions_per_leader: int,
        request_pool: Pool,
        batcher,
        leader_monitor,
        verifier: Verifier,
        logger: Logger,
        assembler: Assembler,
        application: Application,
        synchronizer: Synchronizer,
        signer: Signer,
        request_inspector: RequestInspector,
        proposer_builder,
        checkpoint: Checkpoint,
        failure_detector,
        view_changer,
        collector,
        state,
        in_flight: InFlightData,
        comm: Comm,
        view_sequences: ViewSequencesHolder,
        metrics_view: Optional[ViewMetrics] = None,
        metrics_consensus: Optional[ConsensusMetrics] = None,
        recorder=None,
        vc_phases=None,
        clock=None,
        misbehavior=None,
    ):
        self.id = self_id
        self.n = n
        self.nodes_list = nodes_list
        self._peers = [nid for nid in nodes_list if nid != self_id]
        self.leader_rotation = leader_rotation
        self.decisions_per_leader = decisions_per_leader
        self.request_pool = request_pool
        self.batcher = batcher
        self.leader_monitor = leader_monitor
        self.verifier = verifier
        self.logger = logger
        self.assembler = assembler
        self.application = application
        self.deliver = MutuallyExclusiveDeliver(self)
        self.synchronizer = synchronizer
        self.signer = signer
        self.request_inspector = request_inspector
        self.proposer_builder = proposer_builder
        self.checkpoint = checkpoint
        self.failure_detector = failure_detector
        self.view_changer = view_changer
        self.collector = collector
        self.state = state
        self.in_flight = in_flight
        self.comm = comm
        self.view_sequences = view_sequences
        self.metrics_view = metrics_view
        self.metrics_consensus = metrics_consensus
        #: flight recorder (obs.TraceRecorder; the nop singleton when
        #: tracing is off — every hot-path site guards on .enabled)
        from ..obs.recorder import NOP_RECORDER

        self.recorder = recorder if recorder is not None else NOP_RECORDER
        #: obs.ViewChangePhaseTracker — the first delivery in a new view
        #: closes an open view-change round's `first_commit` phase
        self.vc_phases = vc_phases
        #: core.misbehavior.MisbehaviorTable (ISSUE 18) or None: shunned
        #: senders' votes are dropped at intake (a vote-forgery flood
        #: stops costing verify-plane launches) and their forwarded
        #: requests lose the admission-gate bypass
        self.misbehavior = misbehavior
        self._shunned_drops = 0  # throttled warn counter (intake shed)

        self.quorum = 0
        self.curr_view = None
        self.curr_view_number = 0
        self.curr_decisions_in_view = 0
        self.verification_sequence = 0

        # Internal control events only (1-slot tokens + decision rendezvous,
        # all bounded by construction).  Inbound network messages never queue
        # here: process_messages dispatches synchronously into the View /
        # ViewChanger / HeartbeatMonitor / StateCollector inboxes, each of
        # which enforces its own bound (the reference instead bounds the
        # controller's inMsgs channel, consensus.go:337).
        self._events: asyncio.Queue = asyncio.Queue()
        self._stopped = False
        self._task: Optional[asyncio.Task] = None
        self._propose_pending = False  # 1-slot leader token (controller.go:748-761)
        # propose-side launch shadow: batch formation + proposal assembly
        # run in this task, OFF the controller event loop, so decisions and
        # view events keep flowing while the leader waits on the batcher
        # (1-slot, like the token: at most one assembly in flight)
        self._assembly_task: Optional[asyncio.Task] = None
        self._fwd_submit_failures = 0  # throttled warn counter (handle_request)
        self._shed_submits = 0  # throttled info counter (submit_request)
        self._leader_memo_key = None  # (view, decisions, ckpt version) memo
        self._leader_memo = 0
        self._sync_pending = False  # 1-slot sync token (controller.go:718-730)
        self._sync_lock = asyncio.Lock()  # deliver-vs-sync (controller.go:143,940)
        self._reconfig: Optional[Reconfig] = None
        # commit inter-arrival EWMA (ISSUE 15, the Pool._drain_rate idiom):
        # one subtraction + two multiplies per delivery, read by the
        # heartbeat monitor's adaptive complain-timer derivation.  The
        # clock is the consensus scheduler's (logical in tests, wall under
        # WallClockDriver) so the signal lives in the same time domain as
        # the timers it feeds.
        self._clock = clock if clock is not None else time.monotonic
        self._last_commit_t: Optional[float] = None
        self._commit_gap_ewma = 0.0
        # last PROOF the leader is alive (heartbeat receipt time, fed by the
        # HeartbeatMonitor) — lets commit_interval_seconds tell "no load"
        # (leader alive, nothing to commit) from "no leader" (silence)
        self._leader_alive_at: Optional[float] = None

    # ------------------------------------------------------------------ info

    def blacklist(self) -> list[int]:
        prop, _ = self.checkpoint.get()
        return blacklist_of(prop)

    def latest_seq(self) -> int:
        prop, _ = self.checkpoint.get()
        if not prop.metadata:
            return 0
        return cached_view_metadata(prop.metadata).latest_sequence

    def leader_id(self) -> int:
        # memoized per (view, decisions, checkpoint version): recomputing
        # the blacklist from checkpoint metadata on EVERY inbound message
        # (process_messages routes by leader) measured ~1s per n=64 bench
        # run; all three inputs change only at decision/view boundaries
        key = (
            self.curr_view_number,
            self.curr_decisions_in_view,
            self.checkpoint.version,
        )
        if key == self._leader_memo_key:
            return self._leader_memo
        leader = get_leader_id(
            self.curr_view_number, self.n, self.nodes_list, self.leader_rotation,
            self.curr_decisions_in_view, self.decisions_per_leader, self.blacklist(),
        )
        self._leader_memo_key = key
        self._leader_memo = leader
        return leader

    def get_leader_id(self) -> int:
        return self.leader_id()

    def i_am_the_leader(self) -> tuple[bool, int]:
        leader = self.leader_id()
        return leader == self.id, leader

    def commit_interval_seconds(self) -> Optional[float]:
        """The measured commit inter-arrival EWMA (seconds), or None
        before two deliveries have landed — the cluster-visible liveness
        cadence the adaptive complain timer derives from.

        Idle decay (ISSUE 15 residual e): a busy-era EWMA of tens of ms
        would otherwise cadence-lock the complain timer at hair-trigger
        forever once traffic stops.  When the leader has PROVEN itself
        alive after the last commit (a heartbeat arrived — see
        on_leader_sign_of_life) and the commit silence has outgrown the
        EWMA, the silence span itself is reported: the derived timer then
        relaxes toward its configured ceiling as the lull extends.  Silence
        WITHOUT a fresh sign of life keeps the tight busy-era value — a
        possibly-dead leader must still be detected fast."""
        ewma = self._commit_gap_ewma
        if ewma <= 0:
            return None
        if (
            self._last_commit_t is not None
            and self._leader_alive_at is not None
            and self._leader_alive_at > self._last_commit_t
        ):
            # commit silence WITNESSED by a live leader: grows while
            # heartbeats keep arriving, freezes the moment they stop — a
            # leader that dies mid-lull must not keep relaxing the timer
            idle = self._leader_alive_at - self._last_commit_t
            if idle > 2.0 * ewma:
                return idle
        return ewma

    def on_leader_sign_of_life(self, t: float) -> None:
        """HeartbeatMonitor receipt hook: the current leader demonstrated
        liveness at ``t`` (same clock domain as ``clock``)."""
        self._leader_alive_at = t

    def delivery_frontier(self) -> dict:
        """The committed delivery frontier (ISSUE 19): the latest
        delivered sequence, the current view, and the commit inter-
        arrival EWMA.  The read plane's freshness reference — a client
        holding a frontier can bound how stale a follower-read reply is
        in DECISIONS (frontier seq minus reply height) instead of
        guessing in wall time."""
        return {
            "seq": self.latest_seq(),
            "view": self.curr_view_number,
            "commit_gap_s": self._commit_gap_ewma,
        }

    # ------------------------------------------------------------------ requests

    async def submit_request(self, request: bytes, *,
                             forwarded: bool = False) -> None:
        """consensus entry (controller.go:249-264).  ``forwarded`` marks a
        follower's forward landing here: it bypasses the admission gate
        (the request already holds a pool slot cluster-side; shedding it
        would only re-arm the follower's complain timer)."""
        info = self.request_inspector.request_id(request)
        try:
            await self.request_pool.submit(request, forwarded=forwarded)
        except Exception as e:
            # a shed submit is ROUTINE past the admission knee — throttle
            # like the forwarded-path warnings (per-request records on
            # this hot path cost whole seconds per open-loop bench run)
            self._shed_submits += 1
            if self._shed_submits == 1 or self._shed_submits % 1000 == 0:
                self.logger.infof(
                    "Request %s was not submitted (%d sheds so far), error: %s",
                    info, self._shed_submits, e,
                )
            raise
        self.logger.debugf("Request %s was submitted", info)

    async def handle_request(self, sender: int, req: bytes):
        """A forwarded client request lands at the leader
        (controller.go:231-247).

        Returns the shed exception when the pool's OVERLOAD machinery
        refused the submit (admission gate / bounded-wait timeout) so a
        transport can propagate a structured reject to the forwarding
        replica (net.framing.FT_REJECT); every other outcome — submitted,
        not-the-leader drop, bad request, dedup — returns None.  In-
        process callers ignore the return value, so the contract is
        purely additive."""
        i_am, leader = self.i_am_the_leader()
        if not i_am:
            self.logger.warnf(
                "Got request from %d but the leader is %d, dropping request", sender, leader
            )
            return None
        try:
            self.verifier.verify_request(req)
        except Exception as e:
            self.logger.warnf("Got bad request from %d: %s", sender, e)
            return None
        # shunned forwarders lose the admission-gate bypass (ISSUE 18):
        # forwarded=True exists because an honest follower's forward
        # already holds a pool slot cluster-side — a sender this node has
        # caught forging votes gets no such credit, so its submissions
        # compete through the front-door gate and are shed FIRST under
        # overload while honest shards keep their SLO
        forwarded = not (self.misbehavior is not None
                         and self.misbehavior.is_shunned(sender))
        try:
            await self.submit_request(req, forwarded=forwarded)
        except Exception as e:
            # the reference warns on forwarded-submit failure too
            # (controller.go:258-263); a full pool here is routine under
            # load, so throttle like the inbox-overflow warnings — per-
            # request logging on this hot path costs seconds per bench run
            self._fwd_submit_failures += 1
            if self._fwd_submit_failures == 1 or self._fwd_submit_failures % 1000 == 0:
                self.logger.warnf(
                    "Got request from %d but couldn't submit it (%d failures so far): %s",
                    sender, self._fwd_submit_failures, e,
                )
            from .pool import AdmissionRejected, SubmitTimeoutError

            if isinstance(e, (AdmissionRejected, SubmitTimeoutError)):
                return e
        return None

    # -- pool timeout chain (controller.go:266-297) ------------------------

    def on_request_timeout(self, request: bytes, info: RequestInfo) -> None:
        i_am, leader = self.i_am_the_leader()
        if i_am:
            self.logger.infof(
                "Request %s timeout expired, this node is the leader, nothing to do", info
            )
            return
        self.logger.infof(
            "Request %s timeout expired, forwarding request to leader: %d", info, leader
        )
        self.comm.send_transaction(leader, request)

    def on_leader_fwd_request_timeout(self, request: bytes, info: RequestInfo) -> None:
        i_am, leader = self.i_am_the_leader()
        if i_am:
            self.leader_monitor.stop_leader_send_msg()
            return
        self.logger.warnf(
            "Request %s leader-forwarding timeout expired, complaining about leader: %d",
            info, leader,
        )
        self.failure_detector.complain(self.curr_view_number, True)

    def on_auto_remove_timeout(self, info: RequestInfo) -> None:
        self.logger.debugf("Request %s auto-remove timeout expired", info)

    # -- heartbeat events (controller.go:301-318) --------------------------

    def on_heartbeat_timeout(self, view: int, leader_id: int) -> None:
        i_am, current_leader = self.i_am_the_leader()
        if i_am:
            return
        if leader_id != current_leader:
            self.logger.warnf(
                "Heartbeat timeout expired, but current leader: %d differs from reported leader: %d; ignoring",
                current_leader, leader_id,
            )
            return
        self.logger.warnf("Heartbeat timeout expired, complaining about leader: %d", leader_id)
        self.failure_detector.complain(self.curr_view_number, True)

    # ------------------------------------------------------------------ routing

    def _intake_filter(self, sender: int, m: Message) -> bool:
        """Misbehavior gate for the PrePrepare/Prepare/Commit intake
        (ISSUE 18) — True means DROP.  Only Prepare/Commit votes from
        locally shunned senders are shed: PrePrepares, view-change
        traffic, and heartbeats always pass, so the liveness machinery
        that produces SHARED evidence against a bad leader keeps running
        even when this node has privately written the sender off.  A
        stale-view message is counted observationally (never shuns —
        honest replicas racing a view change emit them) and still flows
        to the view, whose own view gating drops it pre-verification."""
        mb = self.misbehavior
        if mb is None:
            return False
        if isinstance(m, (Prepare, Commit)) and mb.is_shunned(sender):
            mb.note_shed(sender)
            self._shunned_drops += 1
            if self._shunned_drops == 1 or self._shunned_drops % 1000 == 0:
                self.logger.warnf(
                    "Dropping vote from shunned sender %d at intake "
                    "(%d sheds so far)", sender, self._shunned_drops,
                )
            return True
        if view_number_of_msg(m) < self.curr_view_number:
            mb.note(sender, "stale_view")
        return False

    def _route_view_message_tail(self, sender: int, m: Message) -> None:
        """Shared tail of pre-prepare/prepare/commit routing: view-change
        evidence fan-out + artificial leader heartbeat (both intakes)."""
        if self.view_changer is not None:
            self.view_changer.handle_view_message(sender, m)
        if sender == self.leader_id():
            self.leader_monitor.inject_artificial_heartbeat(
                sender,
                HeartBeat(view=view_number_of_msg(m), seq=proposal_sequence_of_msg(m)),
            )

    def process_messages(self, sender: int, m: Message) -> None:
        """Dispatch inbound consensus messages (controller.go:321-344)."""
        if isinstance(m, (PrePrepare, Prepare, Commit)):
            if self._intake_filter(sender, m):
                return
            if self.curr_view is not None:
                self.curr_view.handle_message(sender, m)
            self._route_view_message_tail(sender, m)
        elif isinstance(m, (ViewChange, SignedViewData, NewView)):
            if self.view_changer is not None:
                self.view_changer.handle_message(sender, m)
        elif isinstance(m, (HeartBeat, HeartBeatResponse)):
            self.leader_monitor.process_msg(sender, m)
        elif isinstance(m, StateTransferRequest):
            self._respond_to_state_transfer_request(sender)
        elif isinstance(m, StateTransferResponse):
            self.collector.handle_message(sender, m)
        else:
            self.logger.warnf("Unexpected message type, ignoring")

    async def process_messages_async(self, sender: int, m: Message) -> None:
        """Async intake mirror of :meth:`process_messages` for transports
        that can block on backpressure (Configuration.inbox_backpressure):
        View/ViewChanger intake may suspend the sending task on a full
        inbox; every other route is synchronous."""
        if isinstance(m, (PrePrepare, Prepare, Commit)):
            if self._intake_filter(sender, m):
                return
            if self.curr_view is not None:
                intake = getattr(self.curr_view, "handle_message_async", None)
                if intake is not None:
                    await intake(sender, m)
                else:
                    self.curr_view.handle_message(sender, m)
            self._route_view_message_tail(sender, m)
        elif isinstance(m, (ViewChange, SignedViewData, NewView)):
            if self.view_changer is not None:
                await self.view_changer.handle_message_async(sender, m)
        else:
            self.process_messages(sender, m)

    # -- wave-batched intake ------------------------------------------------

    def _ingest_view_run(self, run: list) -> None:
        """Synchronous view intake for one run of view-bound messages."""
        view = self.curr_view
        if view is None:
            return
        rec = self.recorder
        if rec.enabled:
            rec.record("wave.ingest", view=self.curr_view_number,
                       extra={"count": len(run)})
        ingest = getattr(view, "ingest_batch", None)
        if ingest is not None:
            ingest(run)
        else:
            for sender, m in run:
                view.handle_message(sender, m)

    def _finish_view_run(self, run: list) -> None:
        """Shared tail of both flush paths: view-change evidence fan-out +
        artificial heartbeats, then reset the run."""
        for sender, m in run:
            self._route_view_message_tail(sender, m)
        run.clear()

    def _flush_view_run(self, run: list) -> None:
        """Hand a run of consecutive view-bound messages to the view in ONE
        ingest_batch call (one work-event wakeup per wave instead of ~n),
        then fan the view-change evidence / artificial heartbeats out."""
        if not run:
            return
        self._ingest_view_run(run)
        self._finish_view_run(run)

    async def _flush_view_run_async(self, run: list) -> None:
        """Backpressure-capable flush: identical to :meth:`_flush_view_run`
        except a view exposing ``ingest_batch_async`` is awaited (may block
        the delivering task on a full inbox)."""
        if not run:
            return
        view = self.curr_view
        ingest_async = getattr(view, "ingest_batch_async", None) \
            if view is not None else None
        if ingest_async is not None:
            await ingest_async(run)
        else:
            self._ingest_view_run(run)
        self._finish_view_run(run)

    def process_messages_batch(self, items) -> None:
        """Dispatch a whole ingest tick of (sender, msg) pairs, registering
        each consecutive run of pre-prepare/prepare/commit messages into
        the view as one wave.  Relative message order is preserved: a
        non-view message flushes the pending run before it dispatches."""
        run: list = []
        for sender, m in items:
            if isinstance(m, (PrePrepare, Prepare, Commit)):
                if not self._intake_filter(sender, m):
                    run.append((sender, m))
                continue
            self._flush_view_run(run)
            self.process_messages(sender, m)
        self._flush_view_run(run)

    async def process_messages_batch_async(self, items) -> None:
        """Backpressure-capable mirror of :meth:`process_messages_batch`."""
        run: list = []
        for sender, m in items:
            if isinstance(m, (PrePrepare, Prepare, Commit)):
                if not self._intake_filter(sender, m):
                    run.append((sender, m))
                continue
            await self._flush_view_run_async(run)
            await self.process_messages_async(sender, m)
        await self._flush_view_run_async(run)

    def _respond_to_state_transfer_request(self, sender: int) -> None:
        vs = self.view_sequences.load()
        if vs is None:
            self.logger.panicf("ViewSequences is nil")
        self.comm.send_consensus(
            sender,
            StateTransferResponse(view_num=self.curr_view_number, sequence=vs.proposal_seq),
        )

    # ------------------------------------------------------------------ views

    def _start_view(self, proposal_sequence: int) -> None:
        """controller.go:375-396."""
        view, init_phase = self.proposer_builder.new_proposer(
            self.leader_id(), proposal_sequence, self.curr_view_number,
            self.curr_decisions_in_view, self.quorum,
        )
        self.curr_view = view
        view.start()
        leader, _ = self.i_am_the_leader()
        role = "follower"
        if leader:
            window_has_room = getattr(view, "can_accept_more_proposals", None)
            if init_phase in (COMMITTED, ABORT) or (
                window_has_room is not None and window_has_room()
            ):
                self._acquire_leader_token()
            role = "leader"
        self.leader_monitor.change_role(role, self.curr_view_number, self.leader_id())
        self.logger.infof(
            "Starting view with number %d, sequence %d, and decisions %d",
            self.curr_view_number, proposal_sequence, self.curr_decisions_in_view,
        )

    async def _change_view(
        self, new_view_number: int, new_proposal_sequence: int, new_decisions_in_view: int
    ) -> None:
        """controller.go:428-454."""
        if self._stopped:
            return
        latest_view = self.curr_view_number
        if latest_view > new_view_number:
            return
        leader = self.curr_view.get_leader_id() if self.curr_view else 0
        stopped = self.curr_view.stopped() if self.curr_view else True
        if (
            not stopped
            and latest_view == new_view_number
            and self.leader_id() == leader
            and self.curr_decisions_in_view == new_decisions_in_view
        ):
            self.logger.debugf("Got view change to %d but view is already running", new_view_number)
            return
        if not await self._abort_view(latest_view):
            return
        self.curr_view_number = new_view_number
        self.curr_decisions_in_view = new_decisions_in_view
        self._start_view(new_proposal_sequence)
        if new_view_number > latest_view:
            # a real view FLIP (not a rotation restart): ask the verify
            # plane to launch its next waves immediately — the mesh idled
            # through the depose, and the new view's first deep windows
            # must not also pay the coalescing window/hold before their
            # quorum waves go out (ISSUE 15; verifiers without the seam
            # no-op)
            warm = getattr(self.verifier, "note_view_flip", None)
            if warm is not None:
                try:
                    warm()
                except Exception as e:  # noqa: BLE001 — warmth is advisory
                    self.logger.warnf("view-flip verify warm failed: %r", e)
        if self.i_am_the_leader()[0]:
            self.batcher.reset()

    async def _abort_view(self, view: int) -> bool:
        """controller.go:456-473."""
        if view < self.curr_view_number:
            return False
        self._propose_pending = False  # drain leader token
        if self.curr_view is not None:
            await self.curr_view.abort()
        # Uncommitted in-flight batches must become proposable again in the
        # next view.  Batches the view-change ladder DOES redeliver cannot
        # be double-proposed despite the release: delivery removal runs on
        # every delivery path and also populates the recently-deleted dedup
        # map on pool misses, so a released request is either removed before
        # the new view can batch it (it was pooled here) or rejected at
        # re-submission/forwarding (ReqAlreadyProcessedError) — pinned by
        # the exactly-once assertion in the ladder view-change test.
        self.request_pool.release_in_flight()
        return True

    # -- externally invoked transitions ------------------------------------

    def sync(self) -> None:
        """Trigger a sync (controller.go:449-454): 1-slot token."""
        if self.i_am_the_leader()[0]:
            self.batcher.close()
        if not self._sync_pending:
            self._sync_pending = True
            self._events.put_nowait(_SyncEvt())

    def abort_view(self, view: int) -> None:
        """ViewChanger asks to abort (controller.go:457-463)."""
        self.batcher.close()
        self._events.put_nowait(_AbortViewEvt(view=view))

    def view_changed(self, new_view_number: int, new_proposal_sequence: int) -> None:
        """ViewChanger announces the new view (controller.go:466-473)."""
        if self.i_am_the_leader()[0]:
            self.batcher.close()
        self._events.put_nowait(
            _ViewChangeEvt(view_number=new_view_number, proposal_seq=new_proposal_sequence)
        )

    def _acquire_leader_token(self) -> None:
        if not self._propose_pending:
            self._propose_pending = True
            self._events.put_nowait(_ProposeEvt())

    def on_window_capacity(self) -> None:
        """A pipelined view re-opened propose capacity WITHOUT a delivery
        (its launch-shadow gate unlocked, or a WAL-bounding drain finished).
        Deliveries re-arm the token in _decide; this seam covers the two
        windowed transitions that happen between deliveries — otherwise the
        leader would idle under the in-flight launch with room to propose."""
        if self._stopped:
            return
        if self.i_am_the_leader()[0]:
            self._acquire_leader_token()

    # ------------------------------------------------------------------ propose

    async def _propose(self) -> None:
        """controller.go:475-487.  In pipelined mode (pipeline_depth > 1)
        the view accepts proposals while previous decisions are still in
        flight; the token re-arms after each propose until the window fills,
        and again on every delivery (_decide).

        Propose-side launch shadow: batch formation + assembly run in a
        concurrent task (_assemble_and_propose), NOT inline on the event
        loop — the old inline ``await next_batch()`` serialized every
        queued decision behind up to a full batch interval of waiting, so
        delivery fan-out stalled exactly when the leader was idling for
        requests.  The 1-slot assembly task mirrors the leader token."""
        self._propose_pending = False
        if self._stopped or self.batcher.closed():
            return
        if self._assembly_task is not None and not self._assembly_task.done():
            return  # assembly in flight; it re-arms the token when done
        view = self.curr_view
        window_has_room = getattr(view, "can_accept_more_proposals", None)
        if window_has_room is not None and not window_has_room():
            # window full: the next delivery (_decide) or the view's
            # capacity seam (on_window_capacity) re-arms the token
            return
        self._assembly_task = create_logged_task(
            self._assemble_and_propose(view, window_has_room),
            name=f"controller-assemble-{self.id}", logger=self.logger,
        )

    async def _assemble_and_propose(self, view, window_has_room) -> None:
        """One batch-form + assemble + propose cycle, running in the shadow
        of the in-flight wave's verify launch.  Every controller-state
        mutation here is loop-synchronous (no awaits between the post-batch
        guard and the propose), so the event loop never observes a half
        -proposed state."""
        next_batch = await self.batcher.next_batch()
        if not next_batch:
            if not (self._stopped or self.batcher.closed()):
                self._acquire_leader_token()  # try again later
            return
        if view is not self.curr_view or self._stopped or self.batcher.closed():
            # view changed/aborted while batching: the requests were never
            # marked in flight, so the next view re-batches them
            return
        metadata = view.get_metadata()
        proposal = self.assembler.assemble_proposal(metadata, next_batch)
        rec = self.recorder
        if rec.enabled:
            md = decode(ViewMetadata, metadata)
            rec.record("batch.propose", view=md.view_id,
                       seq=md.latest_sequence,
                       extra={"count": len(next_batch)})
        view.propose(proposal)
        if window_has_room is not None:
            # pipelined mode: reserve the batch until delivery removes it —
            # the next window slot's batch must be FRESH requests, not the
            # same FIFO front re-proposed (duplicate delivery otherwise)
            self.request_pool.mark_in_flight(
                self.request_inspector.request_id(r) for r in next_batch
            )
            if window_has_room():
                self._acquire_leader_token()

    # ------------------------------------------------------------------ loop

    async def _run(self) -> None:
        try:
            while True:
                evt = await self._events.get()
                if isinstance(evt, _StopEvt):
                    return
                if isinstance(evt, _Decision):
                    await self._decide(evt)
                elif isinstance(evt, _ViewChangeEvt):
                    await self._change_view(evt.view_number, evt.proposal_seq, 0)
                elif isinstance(evt, _AbortViewEvt):
                    await self._abort_view(evt.view)
                elif isinstance(evt, _ProposeEvt):
                    await self._propose()
                elif isinstance(evt, _SyncEvt):
                    await self._handle_sync_event()
        finally:
            self.logger.infof("Exiting")
            if self.curr_view is not None:
                await self.curr_view.abort()
            self._drain_pending_decisions()

    def _drain_pending_decisions(self) -> None:
        while True:
            try:
                evt = self._events.get_nowait()
            except asyncio.QueueEmpty:
                return
            if isinstance(evt, _Decision) and not evt.done.done():
                evt.done.set_result(None)

    async def _handle_sync_event(self) -> None:
        """controller.go:509-523."""
        self._sync_pending = False
        view, seq, dec = await self._sync()
        self.maybe_prune_revoked_requests()
        if view > 0 or seq > 0:
            await self._change_view(view, seq, dec)
        else:
            vs = self.view_sequences.load()
            if vs is None:
                self.logger.panicf("ViewSequences is nil")
            await self._change_view(
                self.curr_view_number, vs.proposal_seq, self.curr_decisions_in_view
            )

    # ------------------------------------------------------------------ decide

    async def decide(self, proposal: Proposal, signatures: list, requests: list) -> None:
        """Called by the View; resolves after delivery (controller.go:873-890)."""
        if self._stopped:
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._events.put_nowait(
            _Decision(proposal=proposal, signatures=signatures, requests=requests, done=fut)
        )
        await fut

    async def _decide(self, d: _Decision) -> None:
        """controller.go:528-558."""
        reconfig = await self.deliver.deliver(d.proposal, d.signatures)
        if reconfig.in_latest_decision:
            self._reconfig = reconfig
            self.close()
        self.logger.debugf("Node %d delivered proposal", self.id)
        # Bulk removal: not-pooled requests (routine on followers, which see
        # most requests only inside batches) are counted, not raised/logged
        # per item — at RequestBatch=500 x 64 replicas the per-request
        # exception+logging path alone cost seconds per bench run.
        remove_delivered_requests(self.request_pool, d.requests, self.logger)
        if not d.done.done():
            d.done.set_result(None)
        if self._stopped:
            return
        self.curr_decisions_in_view += 1
        now = self._clock()
        if self._last_commit_t is not None:
            gap = now - self._last_commit_t
            if gap > 0:
                self._commit_gap_ewma = gap if self._commit_gap_ewma <= 0 \
                    else 0.7 * self._commit_gap_ewma + 0.3 * gap
        self._last_commit_t = now
        md = decode(ViewMetadata, d.proposal.metadata)
        vp = self.vc_phases
        if vp is not None and vp.open:
            # first commit closes an open VC round; the pool depth at this
            # flip is the stalled backlog the new view now drains
            vp.decision(md.view_id, backlog=self.request_pool.size())
        rec = self.recorder
        if rec.enabled:
            rec.record("decision.deliver", view=md.view_id,
                       seq=md.latest_sequence,
                       extra={"count": len(d.requests)})
            for info in d.requests:
                rec.record("req.deliver", key=str(info), view=md.view_id,
                           seq=md.latest_sequence)
        if self._check_if_rotate(list(md.black_list)):
            self.logger.debugf("Restarting view to rotate the leader")
            await self._change_view(
                self.curr_view_number, md.latest_sequence + 1, self.curr_decisions_in_view
            )
            self.request_pool.restart_timers()
        self.maybe_prune_revoked_requests()
        if self.i_am_the_leader()[0]:
            self._acquire_leader_token()

    def _check_if_rotate(self, blacklist: list[int]) -> bool:
        """controller.go:560-574 (called after increment).

        ``decisions_per_leader`` is the EFFECTIVE per-decision value
        (window granularity pre-multiplies by pipeline_depth), so in
        pipelined rotation mode this fires exactly at window boundaries —
        the just-delivered decision is then the window anchor, the windowed
        view has drained (its propose gate confines the window to the
        delivery frontier's window), and no in-flight sequence above the
        anchor can hold a commit quorum when the view is torn down."""
        if blacklist and self.misbehavior is not None:
            # corroboration accounting (ISSUE 18): the SHARED deterministic
            # blacklist named these nodes — record which of them this
            # node's local misbehavior table had independently suspected
            self.misbehavior.note_blacklisted(blacklist)
        view = self.curr_view_number
        dec = self.curr_decisions_in_view
        curr_leader = get_leader_id(
            view, self.n, self.nodes_list, self.leader_rotation,
            dec - 1, self.decisions_per_leader, blacklist,
        )
        next_leader = get_leader_id(
            view, self.n, self.nodes_list, self.leader_rotation,
            dec, self.decisions_per_leader, blacklist,
        )
        rotate = curr_leader != next_leader
        if rotate:
            self.logger.infof("Rotating leader from %d to %d", curr_leader, next_leader)
        return rotate

    # ------------------------------------------------------------------ sync

    async def _sync(self) -> tuple[int, int, int]:
        """controller.go:576-680.  Returns (view, seq, decisions); zeros mean
        'nothing learned'."""
        begin = time.monotonic()
        async with self._sync_lock:
            sync_response = await asyncio.get_running_loop().run_in_executor(
                None, self.synchronizer.sync
            )
        if self.metrics_consensus:
            self.metrics_consensus.latency_sync.observe(time.monotonic() - begin)
        if sync_response.reconfig.in_latest_decision:
            self.close()
            self.view_changer.close()

        latest_decision = sync_response.latest
        latest_seq = latest_view = latest_dec = 0
        latest_md = None
        if latest_decision is not None and latest_decision.proposal.metadata:
            latest_md = decode(ViewMetadata, latest_decision.proposal.metadata)
            latest_seq = latest_md.latest_sequence
            latest_view = latest_md.view_id
            latest_dec = latest_md.decisions_in_view
        else:
            self.logger.infof("Synchronizer returned with an empty proposal metadata")

        controller_sequence = self.latest_seq()
        new_proposal_sequence = controller_sequence + 1
        controller_view_num = self.curr_view_number
        new_view_num = controller_view_num
        new_decisions_in_view = 0

        if latest_seq > controller_sequence:
            self.logger.infof(
                "Synchronizer returned with sequence %d while the controller is at sequence %d",
                latest_seq, controller_sequence,
            )
            self.checkpoint.set(latest_decision.proposal, latest_decision.signatures)
            self.verification_sequence = latest_decision.proposal.verification_sequence
            new_proposal_sequence = latest_seq + 1
            new_decisions_in_view = latest_dec + 1
        elif (
            latest_md is not None
            and latest_seq == controller_sequence
            and latest_view >= controller_view_num
        ):
            # Caught-up sync: the synchronizer's latest decision is one we
            # already have, and it belongs to the view being (re)entered —
            # so the NEXT decision in that view is latest_dec + 1, exactly
            # as in the learned-something branch above.  Leaving 0 here
            # restarts the live view with decisions_in_view=0, after which
            # this node rejects the leader's correct dec=latest_dec+1
            # proposals forever ("invalid decisions in view") — a wedge the
            # socket kill-rejoin soak hit when a wall-clock straggler sync
            # fired on the restarted ex-leader right after it caught up.
            new_decisions_in_view = latest_dec + 1

        if latest_view > controller_view_num:
            new_view_num = latest_view

        response = await self._fetch_state()
        if response is None:
            self.logger.infof("Fetching state failed")
            if latest_md is None or latest_view < controller_view_num:
                return 0, 0, 0
        else:
            if response.view <= controller_view_num and latest_view < controller_view_num:
                return 0, 0, 0
            if response.view > new_view_num and response.seq == latest_seq + 1:
                self.logger.infof(
                    "Node %d collected state with view %d and sequence %d",
                    self.id, response.view, response.seq,
                )
                self.state.save(
                    NewViewRecord(
                        metadata=ViewMetadata(
                            view_id=response.view,
                            latest_sequence=latest_seq,
                            decisions_in_view=0,
                        )
                    )
                )
                new_view_num = response.view
                new_decisions_in_view = 0

        if latest_md is not None:
            self._maybe_prune_in_flight(latest_md)

        if new_view_num > controller_view_num:
            self.view_changer.inform_new_view(new_view_num)

        return new_view_num, new_proposal_sequence, new_decisions_in_view

    def _maybe_prune_in_flight(self, sync_md: ViewMetadata) -> None:
        """controller.go:682-705."""
        in_flight = self.in_flight.in_flight_proposal()
        if in_flight is None:
            return
        in_flight_md = decode(ViewMetadata, in_flight.metadata)
        if sync_md.latest_sequence < in_flight_md.latest_sequence:
            return
        self.logger.infof(
            "Synced to sequence %d, deleting in-flight as it is stale", sync_md.latest_sequence
        )
        self.in_flight.prune_synced(sync_md.latest_sequence)

    async def _fetch_state(self) -> Optional[ViewAndSeq]:
        """controller.go:707-716."""
        self.collector.clear_collected()
        self.broadcast_consensus(StateTransferRequest())
        return await self.collector.collect_state_responses()

    def maybe_prune_revoked_requests(self) -> None:
        """controller.go:733-746."""
        new_seq = self.verifier.verification_sequence()
        if new_seq == self.verification_sequence:
            return
        old = self.verification_sequence
        self.verification_sequence = new_seq
        self.logger.infof("Verification sequence changed: %d --> %d", old, new_seq)

        def predicate(req: bytes):
            try:
                self.verifier.verify_request(req)
                return None
            except Exception as e:
                return e

        self.request_pool.prune(predicate)

    # ------------------------------------------------------------------ start/stop

    async def _sync_on_start(
        self, start_view: int, start_seq: int, start_dec: int
    ) -> tuple[int, int, int]:
        """controller.go:763-778."""
        sync_view, sync_seq, sync_dec = await self._sync()
        self.maybe_prune_revoked_requests()
        view, seq, dec = start_view, start_seq, start_dec
        if sync_view > start_view:
            view = sync_view
            dec = sync_dec
        if sync_seq > start_seq:
            seq = sync_seq
            dec = sync_dec
        return view, seq, dec

    async def start(
        self,
        start_view_number: int,
        start_proposal_sequence: int,
        start_decisions_in_view: int,
        sync_on_start: bool,
    ) -> None:
        """controller.go:781-814."""
        self._stopped = False
        q, f = compute_quorum(self.n)
        self.quorum = q
        self.verification_sequence = self.verifier.verification_sequence()
        if sync_on_start:
            (
                start_view_number,
                start_proposal_sequence,
                start_decisions_in_view,
            ) = await self._sync_on_start(
                start_view_number, start_proposal_sequence, start_decisions_in_view
            )
        self.curr_view_number = start_view_number
        self.curr_decisions_in_view = start_decisions_in_view
        self._start_view(start_proposal_sequence)
        self._task = create_logged_task(
            self._run(), name=f"controller-{self.id}", logger=self.logger
        )

    def close(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._events.put_nowait(_StopEvt())

    async def stop(self, pool_pause: bool = False) -> None:
        """controller.go:829-861."""
        self.close()
        self.batcher.close()
        # release a run-loop blocked in collect_state_responses: its timeout
        # lives on the logical scheduler, which may no longer be advancing by
        # the time stop() is called (the reference's collector timeout is
        # wall-clock and always fires, statecollector.go:100-106)
        self.collector.stop()
        if pool_pause:
            self.request_pool.stop_timers()
        else:
            self.request_pool.close()
        self.leader_monitor.close()
        self._propose_pending = False
        if self._task is not None:
            await self._task
            self._task = None
        if self._assembly_task is not None:
            # the closed batcher resolves any parked next_batch wait, so
            # this never blocks; awaiting keeps shutdown orphan-free
            try:
                await self._assembly_task
            finally:
                self._assembly_task = None

    def stopped(self) -> bool:
        return self._stopped

    # ------------------------------------------------------------------ comm

    def broadcast_consensus(self, m: Message) -> None:
        """Broadcast (controller.go:912-926).  Prefers the Comm's native
        ``broadcast_consensus`` seam — the vectorized message plane encodes
        the message ONCE there and shares the frozen decoded object across
        all recipients — falling back to the per-peer send loop for Comm
        implementations without it."""
        bcast = getattr(self.comm, "broadcast_consensus", None)
        if bcast is not None:
            bcast(m, self._peers)  # membership-scoped encode-once fan-out
        else:
            for node in self.nodes_list:
                if node == self.id:
                    continue
                self.comm.send_consensus(node, m)
        if isinstance(m, (PrePrepare, Prepare, Commit)):
            if self.i_am_the_leader()[0]:
                self.leader_monitor.heartbeat_was_sent()

    def send_consensus(self, target: int, m: Message) -> None:
        self.comm.send_consensus(target, m)

    def send_transaction(self, target: int, request: bytes) -> None:
        self.comm.send_transaction(target, request)

    def nodes(self) -> list[int]:
        return list(self.nodes_list)


class MutuallyExclusiveDeliver:
    """Deliver guarded against concurrent sync (controller.go:928-965)."""

    def __init__(self, controller: Controller):
        self.c = controller

    async def deliver(self, proposal: Proposal, signatures: list) -> Reconfig:
        pending_md = decode(ViewMetadata, proposal.metadata)
        async with self.c._sync_lock:
            latest = self.c.latest_seq()
            if latest != 0 and latest >= pending_md.latest_sequence:
                self.c.logger.infof(
                    "Attempted to deliver block %d via view change but meanwhile view change "
                    "already synced to seq %d, returning result from sync",
                    pending_md.latest_sequence, latest,
                )
                sync_result = await asyncio.get_running_loop().run_in_executor(
                    None, self.c.synchronizer.sync
                )
                self.c.checkpoint.set(
                    sync_result.latest.proposal, sync_result.latest.signatures
                )
                r = sync_result.reconfig
                return Reconfig(
                    in_latest_decision=getattr(
                        r, "in_replicated_decisions", getattr(r, "in_latest_decision", False)
                    ),
                    current_nodes=tuple(r.current_nodes),
                    current_config=r.current_config,
                )
            begin = time.monotonic()
            # executor offload: the app's deliver may block (disk/IPC), and
            # other components must keep making progress meanwhile — the
            # reference's deliver blocks only the controller goroutine.
            # Applications whose deliver is non-blocking (in-memory ledger
            # append: the test harness, the bench) declare
            # ``blocking_deliver = False`` and run inline — the executor
            # round-trip (submit + two loop wakeups) costs more than such
            # delivers themselves, measured ~0.1 ms x n x decisions per
            # n=64 bench run.
            if getattr(self.c.application, "blocking_deliver", True):
                result = await asyncio.get_running_loop().run_in_executor(
                    None, self.c.application.deliver, proposal, signatures
                )
            else:
                result = self.c.application.deliver(proposal, signatures)
            if self.c.metrics_view:
                self.c.metrics_view.latency_batch_save.observe(time.monotonic() - begin)
            self.c.checkpoint.set(proposal, signatures)
            return result
