"""Leader liveness monitoring via heartbeats.

Re-design of /root/reference/internal/bft/heartbeatmonitor.go:47-414.  The
reference runs a goroutine selecting over tick/msg/command channels; every
input here is already a callback on the consensus event loop, so the monitor
is a plain event-driven object fed by a scheduler Ticker — same transitions,
no task.

Leader: broadcast HeartBeat{view,seq} every timeout/count, suppressed when
real traffic was recently sent.  Follower: complain on heartbeat timeout;
detect being one sequence behind for N consecutive ticks -> sync; collect
HeartBeatResponses — f+1 higher-view responses force the leader to sync.
"""

from __future__ import annotations

from typing import Optional

from ..api import Logger
from ..messages import HeartBeat, HeartBeatResponse, Message
from .util import compute_quorum
from .view import ViewSequencesHolder

LEADER = "leader"
FOLLOWER = "follower"


class HeartbeatMonitor:
    def __init__(
        self,
        logger: Logger,
        heartbeat_timeout: float,
        heartbeat_count: int,
        comm,
        num_nodes: int,
        handler,
        view_sequences: ViewSequencesHolder,
        num_of_ticks_behind_before_syncing: int,
        pipeline_depth: int = 1,
        vc_phases=None,
    ):
        self._log = logger
        self._hb_timeout = heartbeat_timeout
        self._hb_count = heartbeat_count
        self._comm = comm
        self._n = num_nodes
        self._handler = handler  # Controller: on_heartbeat_timeout / sync
        self._view_sequences = view_sequences
        self._ticks_behind_limit = num_of_ticks_behind_before_syncing
        #: optional obs.ViewChangePhaseTracker: heartbeat-timeout firings
        #: report their ARM-TO-FIRE interval (last heartbeat seen -> the
        #: complain) — the detection latency that dominates failover
        self._vc_phases = vc_phases
        # pipelined mode: a healthy follower may trail the leader by up to
        # TWO window depths (base window + launch shadow) while quorums it
        # is not part of complete — lagging inside that span is the
        # persistent-behind case (counter, then sync), not the
        # fell-off-the-ledger case (immediate sync).  Single-slot mode
        # (depth 1) has no shadow: keep the reference-faithful tolerance
        # of 1 so a 2-behind follower still syncs immediately.
        self._lag_tolerance = 2 * pipeline_depth if pipeline_depth > 1 else 1

        self._view = 0
        self._leader_id = 0
        self._follower = True
        self._stop_send_heartbeat_from_leader = False
        self._last_heartbeat: Optional[float] = None
        self._last_tick: float = 0.0
        self._hb_resp_collector: dict[int, int] = {}
        self._timed_out = False
        self._sync_req = False
        self._behind_seq = 0
        self._behind_counter = 0
        self._follower_behind = False
        self._closed = False

    # ------------------------------------------------------------------ inputs

    def change_role(self, role: str, view: int, leader_id: int) -> None:
        """heartbeatmonitor.go:174-195,330-343."""
        self._log.infof(
            "Changing to %s role, current view: %d, current leader: %d", role, view, leader_id
        )
        self._stop_send_heartbeat_from_leader = False
        self._view = view
        self._leader_id = leader_id
        self._follower = role == FOLLOWER
        self._timed_out = False
        self._last_heartbeat = self._last_tick
        self._hb_resp_collector = {}
        self._sync_req = False

    def stop_leader_send_msg(self) -> None:
        """Demote to non-sending without changing view (monitor keeps
        follower-ticking) — heartbeatmonitor.go:161-171,325-328."""
        self._stop_send_heartbeat_from_leader = True

    def process_msg(self, sender: int, msg: Message) -> None:
        if self._closed:
            return
        if isinstance(msg, HeartBeat):
            self._handle_heartbeat(sender, msg, artificial=False)
        elif isinstance(msg, HeartBeatResponse):
            self._handle_heartbeat_response(sender, msg)
        else:
            self._log.warnf("Unexpected message type, ignoring")

    def inject_artificial_heartbeat(self, sender: int, msg: Message) -> None:
        """Real leader traffic counts as a sign of life
        (controller.go:330-332)."""
        if self._closed or not isinstance(msg, HeartBeat):
            return
        self._handle_heartbeat(sender, msg, artificial=True)

    def heartbeat_was_sent(self) -> None:
        """Leader sent real traffic; suppress the next heartbeat
        (heartbeatmonitor.go:408-414)."""
        self._last_heartbeat = self._last_tick

    def close(self) -> None:
        self._closed = True

    # ------------------------------------------------------------------ ticks

    def tick(self, now: float) -> None:
        """heartbeatmonitor.go:345-350."""
        if self._closed:
            return
        self._last_tick = now
        if self._last_heartbeat is None:
            self._last_heartbeat = now
        if self._follower or self._stop_send_heartbeat_from_leader:
            self._follower_tick(now)
        else:
            self._leader_tick(now)

    def _leader_tick(self, now: float) -> None:
        """Emit a heartbeat every hb_timeout/hb_count (go:352-376)."""
        if (now - self._last_heartbeat) * self._hb_count < self._hb_timeout:
            return
        vs = self._view_sequences.load()
        if vs is None or not vs.view_active:
            self._log.infof("ViewSequence uninitialized or view inactive")
            return
        self._comm.broadcast_consensus(HeartBeat(view=self._view, seq=vs.proposal_seq))
        self._last_heartbeat = now

    def _follower_tick(self, now: float) -> None:
        """Complain on silence; sync when persistently behind (go:378-406)."""
        if self._timed_out or self._last_heartbeat is None:
            self._last_heartbeat = now
            return
        delta = now - self._last_heartbeat
        if delta >= self._hb_timeout:
            self._log.warnf(
                "Heartbeat timeout (%s) from %d expired; last heartbeat was observed %s ago",
                self._hb_timeout, self._leader_id, delta,
            )
            if self._vc_phases is not None:
                # delta IS the complain-timer arm-to-fire time: the timer
                # armed at the last observed heartbeat and fired now
                self._vc_phases.detection(delta)
            self._handler.on_heartbeat_timeout(self._view, self._leader_id)
            self._timed_out = True
            return
        if not self._follower_behind:
            return
        self._behind_counter += 1
        if self._behind_counter >= self._ticks_behind_limit:
            self._log.warnf(
                "Syncing since the follower with seq %d is behind the leader for the last %d ticks",
                self._behind_seq, self._ticks_behind_limit,
            )
            self._handler.sync()
            self._behind_counter = 0

    # ------------------------------------------------------------------ msgs

    def _handle_heartbeat(self, sender: int, hb: HeartBeat, artificial: bool) -> None:
        """heartbeatmonitor.go:216-257."""
        if hb.view < self._view:
            self._send_heartbeat_response(sender)
            return
        if not self._stop_send_heartbeat_from_leader and sender != self._leader_id:
            self._log.debugf(
                "Heartbeat sender is not leader, ignoring; leader: %d, sender: %d",
                self._leader_id, sender,
            )
            return
        if hb.view > self._view:
            self._log.debugf(
                "Heartbeat view is bigger than expected, syncing and ignoring; expected-view=%d, received-view: %d",
                self._view, hb.view,
            )
            self._handler.sync()
            return

        active, our_seq = self._view_active()
        if active and not artificial:
            if our_seq + self._lag_tolerance < hb.seq:
                self._log.debugf(
                    "Heartbeat sequence is bigger than expected, leader's sequence is %d and ours is %d, syncing",
                    hb.seq, our_seq,
                )
                self._handler.sync()
                return
            if our_seq < hb.seq <= our_seq + self._lag_tolerance:
                self._follower_behind = True
                if our_seq > self._behind_seq:
                    self._behind_seq = our_seq
                    self._behind_counter = 0
            else:
                self._follower_behind = False
        else:
            self._follower_behind = False

        self._last_heartbeat = self._last_tick

    def _handle_heartbeat_response(self, sender: int, hbr: HeartBeatResponse) -> None:
        """f+1 higher-view responses force a sync (go:260-286)."""
        if self._follower or self._sync_req:
            return
        if self._view >= hbr.view:
            return
        self._hb_resp_collector[sender] = hbr.view
        _, f = compute_quorum(self._n)
        if len(self._hb_resp_collector) >= f + 1:
            self._log.infof(
                "Received HeartBeatResponse triggered a call to HeartBeatEventHandler Sync, view: %d",
                hbr.view,
            )
            self._handler.sync()
            self._sync_req = True

    def _send_heartbeat_response(self, target: int) -> None:
        self._comm.send_consensus(target, HeartBeatResponse(view=self._view))

    def _view_active(self) -> tuple[bool, int]:
        vs = self._view_sequences.load()
        if vs is None or not vs.view_active:
            return False, 0
        return True, vs.proposal_seq
