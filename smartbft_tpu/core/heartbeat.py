"""Leader liveness monitoring via heartbeats.

Re-design of /root/reference/internal/bft/heartbeatmonitor.go:47-414.  The
reference runs a goroutine selecting over tick/msg/command channels; every
input here is already a callback on the consensus event loop, so the monitor
is a plain event-driven object fed by a scheduler Ticker — same transitions,
no task.

Leader: broadcast HeartBeat{view,seq} every timeout/count, suppressed when
real traffic was recently sent.  Follower: complain on heartbeat timeout;
detect being one sequence behind for N consecutive ticks -> sync; collect
HeartBeatResponses — f+1 higher-view responses force the leader to sync.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..api import Logger
from ..messages import HeartBeat, HeartBeatResponse, Message
from .util import compute_quorum
from .view import ViewSequencesHolder

LEADER = "leader"
FOLLOWER = "follower"

#: hard lower bound of a DERIVED complain timer (seconds): below this the
#: leader's emission interval (timeout / count) would race the event loop
#: itself and loopback jitter would read as leader death
DETECTION_FLOOR = 0.05

#: the monitor ticks at effective_timeout / THIS so arm-to-fire can
#: overshoot the timer by at most one tick (a quarter of it) — the fix
#: for the round-16 granularity gap where a fixed 1 s tick cadence let
#: detection overshoot a shrunk timer by multiples
DETECTION_RESOLUTION = 4


class HeartbeatMonitor:
    def __init__(
        self,
        logger: Logger,
        heartbeat_timeout: float,
        heartbeat_count: int,
        comm,
        num_nodes: int,
        handler,
        view_sequences: ViewSequencesHolder,
        num_of_ticks_behind_before_syncing: int,
        pipeline_depth: int = 1,
        vc_phases=None,
        rtt_multiplier: float = 0.0,
        backoff_base: float = 2.0,
        backoff_max: float = 8.0,
        rtt_fn: Optional[Callable[[], Optional[float]]] = None,
        commit_interval_fn: Optional[Callable[[], Optional[float]]] = None,
        metrics=None,
        now_fn: Optional[Callable[[], float]] = None,
    ):
        self._log = logger
        self._hb_timeout = heartbeat_timeout
        self._hb_count = heartbeat_count
        self._comm = comm
        self._n = num_nodes
        self._handler = handler  # Controller: on_heartbeat_timeout / sync
        self._view_sequences = view_sequences
        self._ticks_behind_limit = num_of_ticks_behind_before_syncing
        #: optional obs.ViewChangePhaseTracker: heartbeat-timeout firings
        #: report their ARM-TO-FIRE interval (last heartbeat seen -> the
        #: complain) — the detection latency that dominates failover
        self._vc_phases = vc_phases
        # adaptive detection (ISSUE 15): the effective complain timer is
        # derived from live signals — the transport's per-peer RTT EWMA,
        # the controller's commit inter-arrival EWMA, and this monitor's
        # own observed-heartbeat-gap EWMA — clamped to the configured
        # constant as ceiling/fallback.  Both LEADER emission cadence and
        # FOLLOWER complain timing use the same derivation, so the
        # count-x emission margin survives the shrink.
        # rtt_multiplier <= 0 keeps the constant.
        self._rtt_multiplier = rtt_multiplier
        self._rtt_fn = rtt_fn
        self._commit_interval_fn = commit_interval_fn
        #: exponential backoff across consecutive complaints against the
        #: SAME view (a flaky network keeps killing the resulting view
        #: changes; widening the timer stops the leadership thrash) —
        #: reset when a HIGHER view installs
        self._backoff_base = max(backoff_base, 1.0)
        self._backoff_max = max(backoff_max, 1.0)
        self._backoff_round = 0
        self._complained_view = -1
        #: optional metrics.ViewChangeMetrics — the effective timer and
        #: its inputs ride cmd=metrics as gauges
        self._metrics = metrics
        #: EWMA of the OBSERVED heartbeat inter-arrival (real or
        #: artificial) — the most direct measurement of how stale a LIVE
        #: leader can look.  Folding it into the derivation guarantees a
        #: follower never complains faster than mult x the cadence the
        #: leader actually demonstrates, which protects a cold-signal
        #: leader (fresh restart, idle cluster: its emission falls back
        #: to ceiling/count) from warm followers whose RTT/commit terms
        #: alone would derive a hair-trigger timer below its emission
        #: interval.  Samples are taken with ``now_fn`` (the consensus
        #: scheduler clock) at RECEIPT time — measuring them against the
        #: tick-quantized ``_last_tick`` would floor every sample at one
        #: tick interval (eff/4) and feed the derivation its own tick
        #: cadence, a runaway loop (eff -> mult*eff/4 -> ceiling) that
        #: re-opened the round-12 detection cliff when first tried.
        self._hb_gap_ewma = 0.0
        self._now = now_fn
        self._last_hb_seen_at: Optional[float] = None
        #: first-observation grace (the cold-leader guard): the DERIVED
        #: complain timer only applies once this view's leader has been
        #: observed at least once (any heartbeat, real or artificial).
        #: Until then the configured constant governs — warm followers
        #: carrying hair-trigger signals from the previous view must not
        #: depose a new leader they have never heard from (whose own
        #: cold derivation may pace its first emission at ceiling/count).
        self._leader_observed = False
        # pipelined mode: a healthy follower may trail the leader by up to
        # TWO window depths (base window + launch shadow) while quorums it
        # is not part of complete — lagging inside that span is the
        # persistent-behind case (counter, then sync), not the
        # fell-off-the-ledger case (immediate sync).  Single-slot mode
        # (depth 1) has no shadow: keep the reference-faithful tolerance
        # of 1 so a 2-behind follower still syncs immediately.
        self._lag_tolerance = 2 * pipeline_depth if pipeline_depth > 1 else 1

        self._view = 0
        self._leader_id = 0
        self._follower = True
        self._stop_send_heartbeat_from_leader = False
        self._last_heartbeat: Optional[float] = None
        self._last_tick: float = 0.0
        #: learned tick inter-arrival (local-pause detector, ISSUE 16): a
        #: tick landing far past this cadence means THIS process was
        #: starved (GC pause, saturated event loop, host preemption) — a
        #: span during which no heartbeat could have been observed from a
        #: perfectly live leader.  The follower complain base is credited
        #: with the stall so local starvation never reads as leader
        #: silence (the spurious-failover storm that capped the round-18
        #: open-loop sweep).  A leader that truly died inside the pause is
        #: still caught: silence keeps accruing normally from the first
        #: post-pause tick on.
        self._tick_gap_ewma = 0.0
        #: folded cadence samples — the expectation is only trusted once
        #: it has warmed up (a couple of sparse hand-driven ticks must not
        #: read every subsequent gap as a pause)
        self._tick_gap_samples = 0
        #: discounted local pauses (observability)
        self.local_pauses = 0
        self._hb_resp_collector: dict[int, int] = {}
        self._timed_out = False
        self._sync_req = False
        self._behind_seq = 0
        self._behind_counter = 0
        self._follower_behind = False
        self._closed = False

    # ------------------------------------------------------------------ inputs

    def change_role(self, role: str, view: int, leader_id: int) -> None:
        """heartbeatmonitor.go:174-195,330-343."""
        self._log.infof(
            "Changing to %s role, current view: %d, current leader: %d", role, view, leader_id
        )
        self._stop_send_heartbeat_from_leader = False
        if view > self._complained_view:
            # a HIGHER view installed: the complaints worked, stop backing
            # off.  Re-entering the SAME view (a failed VC recycled it)
            # keeps the widened timer — that is the whole point.
            self._backoff_round = 0
        self._view = view
        self._leader_id = leader_id
        self._follower = role == FOLLOWER
        self._timed_out = False
        self._last_heartbeat = self._last_tick
        self._hb_resp_collector = {}
        self._sync_req = False
        # new view, new leader to observe: re-arm the first-observation
        # grace, and never fold the dead span of the view change into the
        # gap EWMA (the next receipt starts a fresh measurement)
        self._leader_observed = False
        self._last_hb_seen_at = None

    def stop_leader_send_msg(self) -> None:
        """Demote to non-sending without changing view (monitor keeps
        follower-ticking) — heartbeatmonitor.go:161-171,325-328."""
        self._stop_send_heartbeat_from_leader = True

    def process_msg(self, sender: int, msg: Message) -> None:
        if self._closed:
            return
        if isinstance(msg, HeartBeat):
            self._handle_heartbeat(sender, msg, artificial=False)
        elif isinstance(msg, HeartBeatResponse):
            self._handle_heartbeat_response(sender, msg)
        else:
            self._log.warnf("Unexpected message type, ignoring")

    def inject_artificial_heartbeat(self, sender: int, msg: Message) -> None:
        """Real leader traffic counts as a sign of life
        (controller.go:330-332)."""
        if self._closed or not isinstance(msg, HeartBeat):
            return
        self._handle_heartbeat(sender, msg, artificial=True)

    def heartbeat_was_sent(self) -> None:
        """Leader sent real traffic; suppress the next heartbeat
        (heartbeatmonitor.go:408-414)."""
        self._last_heartbeat = self._last_tick

    def close(self) -> None:
        self._closed = True

    # ------------------------------------------------------------------ timers

    def _signal(self, fn) -> Optional[float]:
        """One advisory signal read: None on no provider / no measurement
        / failure — telemetry must never wedge the liveness monitor."""
        if fn is None:
            return None
        try:
            v = fn()
        except Exception:  # noqa: BLE001 — derivation is advisory
            return None
        return v if v is not None and v > 0 else None

    def _derive(self) -> tuple[float, float, float]:
        """Derivation only — NO metric side effects.  Returns
        ``(derived, rtt, commit_gap)`` with unmeasured signals as 0.0.
        The cadence query calls this on every ticker re-arm; gauge/trace
        publication rides :meth:`effective_timeout` on the tick path, so
        at the adaptive floor cadence the per-re-arm cost stays at two
        EWMA reads."""
        ceiling = self._hb_timeout
        mult = self._rtt_multiplier
        if mult <= 0:
            return ceiling, 0.0, 0.0
        rtt = self._signal(self._rtt_fn)
        commit_gap = self._signal(self._commit_interval_fn)
        if rtt is None and commit_gap is None:
            return ceiling, 0.0, 0.0
        derived = mult * max(rtt or 0.0, commit_gap or 0.0,
                             self._hb_gap_ewma)
        backoff = min(
            self._backoff_base ** self._backoff_round, self._backoff_max
        )
        return (
            min(max(derived * backoff, DETECTION_FLOOR), ceiling),
            rtt or 0.0,
            commit_gap or 0.0,
        )

    def effective_timeout(self) -> float:
        """The EFFECTIVE complain timer (seconds): the adaptive derivation
        of ISSUE 15, or the configured constant when the multiplier is off
        or no signal is measured yet.

        ``max(rtt, commit_interval, observed_heartbeat_gap)`` is the
        conservative envelope of how stale a LIVE leader can look: real
        leader traffic arrives at commit cadence (and injects artificial
        heartbeats), any heartbeat needs one link traversal, and the
        observed-gap term guarantees we never complain faster than
        ``mult`` x the emission cadence this leader actually
        demonstrates — so a cold-signal leader (fresh restart, idle
        cluster) whose emission fell back toward ceiling/count cannot be
        spuriously deposed by warm followers.  Backoff multiplies in,
        then the ceiling clamps: a derived timer can only ever be MORE
        aggressive than the configured constant.  With the multiplier
        off (the default) this is one comparison and a return — no
        signal reads, no gauge writes."""
        derived, rtt, commit_gap = self._derive()
        if self._rtt_multiplier <= 0:
            return derived
        if self._metrics is not None:
            m = self._metrics
            m.detection_timeout_seconds.set(derived)
            m.detection_rtt_seconds.set(rtt)
            m.detection_commit_interval_seconds.set(commit_gap)
            m.detection_backoff_round.set(self._backoff_round)
        if self._vc_phases is not None:
            self._vc_phases.note_effective_timer(
                derived, rtt, commit_gap, self._backoff_round
            )
        return derived

    def suggested_tick_interval(self, base_interval: float) -> float:
        """The monitor's next tick interval: a quarter of the effective
        timeout, never above the configured base cadence (so an
        unadapted monitor ticks exactly as before) and never below 10 ms
        (the wall-clock driver's own resolution).  Consumed by the
        consensus facade's adaptive ticker — deriving the CHECK cadence
        from the timer is what makes arm-to-fire <= 1.25x the timer
        instead of 'timer plus however stale the fixed tick was'.
        Publication-free: only the tick path writes the timer gauges.

        A LEADER divides by ``heartbeat_count`` too when that is finer:
        emission only happens on ticks, so a coarser cadence would floor
        the emitted inter-arrival at the tick interval — and since
        followers fold the OBSERVED gap into their derivation, an
        emission floor of eff/4 feeds back as mult*eff/4 and runs the
        cluster's timers up to the ceiling (measured: re-opened the
        detection cliff).  Ticking at eff/count keeps the demonstrated
        cadence equal to the derived one.

        With the multiplier off the STATIC cadence is returned untouched:
        the ceiling/4 (or ceiling/count) could still undercut a coarse
        configured tick, and '0 keeps the constant' promises reference-
        faithful emission traffic, not just a reference-faithful timer."""
        if self._rtt_multiplier <= 0:
            return base_interval
        eff, _, _ = self._derive()
        div = DETECTION_RESOLUTION
        if not (self._follower or self._stop_send_heartbeat_from_leader):
            div = max(div, self._hb_count)
        return min(base_interval, max(eff / div, 0.01))

    # ------------------------------------------------------------------ ticks

    def tick(self, now: float) -> None:
        """heartbeatmonitor.go:345-350."""
        if self._closed:
            return
        prev = self._last_tick
        self._last_tick = now
        if self._last_heartbeat is None:
            self._last_heartbeat = now
        follower = self._follower or self._stop_send_heartbeat_from_leader
        gap = now - prev
        if prev > 0 and gap > 0:
            if self._tick_gap_samples >= 8 and gap > 4.0 * self._tick_gap_ewma:
                # local pause: the tick driver was starved for far longer
                # than its learned cadence, so nothing COULD have been
                # observed in that span.  Credit the excess to the
                # follower's complain base (never past `now`); the leader
                # path wants the opposite — emit immediately after the
                # stall — so it is left untouched.  The EWMA does not fold
                # the outlier (one pause must not stretch the expectation).
                self.local_pauses += 1
                if follower and self._last_heartbeat is not None:
                    self._last_heartbeat = min(
                        now, self._last_heartbeat + (gap - self._tick_gap_ewma)
                    )
            else:
                self._tick_gap_ewma = gap if self._tick_gap_ewma <= 0 \
                    else 0.8 * self._tick_gap_ewma + 0.2 * gap
                self._tick_gap_samples += 1
        if follower:
            self._follower_tick(now)
        else:
            self._leader_tick(now)

    def _leader_tick(self, now: float) -> None:
        """Emit a heartbeat every effective_timeout/hb_count (go:352-376;
        the adaptive derivation shrinks emission in step with the
        followers' complain timers — see effective_timeout)."""
        if (now - self._last_heartbeat) * self._hb_count < self.effective_timeout():
            return
        vs = self._view_sequences.load()
        if vs is None or not vs.view_active:
            self._log.infof("ViewSequence uninitialized or view inactive")
            return
        self._comm.broadcast_consensus(HeartBeat(view=self._view, seq=vs.proposal_seq))
        self._last_heartbeat = now

    def _follower_tick(self, now: float) -> None:
        """Complain on silence; sync when persistently behind (go:378-406)."""
        if self._timed_out or self._last_heartbeat is None:
            self._last_heartbeat = now
            return
        delta = now - self._last_heartbeat
        # first-observation grace: until THIS view's leader has shown one
        # sign of life, the constant governs — the derived timer carries
        # signals from the previous view and must not judge a leader it
        # has never measured (a dead new leader costs one constant round,
        # exactly the pre-adaptive behavior)
        effective = (self.effective_timeout() if self._leader_observed
                     else self._hb_timeout)
        if delta >= effective:
            self._log.warnf(
                "Heartbeat timeout (%s) from %d expired; last heartbeat was observed %s ago",
                effective, self._leader_id, delta,
            )
            if self._vc_phases is not None:
                # delta IS the complain-timer arm-to-fire time: the timer
                # armed at the last observed heartbeat and fired now
                self._vc_phases.detection(delta)
            # consecutive complaints against the same view widen the next
            # derived timer (anti-thrash backoff); a fresh view's first
            # complaint starts the ladder at round 0
            if self._view <= self._complained_view:
                self._backoff_round += 1
            else:
                self._backoff_round = 0
            self._complained_view = self._view
            self._handler.on_heartbeat_timeout(self._view, self._leader_id)
            self._timed_out = True
            return
        if not self._follower_behind:
            return
        self._behind_counter += 1
        if self._behind_counter >= self._ticks_behind_limit:
            self._log.warnf(
                "Syncing since the follower with seq %d is behind the leader for the last %d ticks",
                self._behind_seq, self._ticks_behind_limit,
            )
            self._handler.sync()
            self._behind_counter = 0

    # ------------------------------------------------------------------ msgs

    def _handle_heartbeat(self, sender: int, hb: HeartBeat, artificial: bool) -> None:
        """heartbeatmonitor.go:216-257."""
        if hb.view < self._view:
            self._send_heartbeat_response(sender)
            return
        if not self._stop_send_heartbeat_from_leader and sender != self._leader_id:
            self._log.debugf(
                "Heartbeat sender is not leader, ignoring; leader: %d, sender: %d",
                self._leader_id, sender,
            )
            return
        if hb.view > self._view:
            self._log.debugf(
                "Heartbeat view is bigger than expected, syncing and ignoring; expected-view=%d, received-view: %d",
                self._view, hb.view,
            )
            self._handler.sync()
            return

        active, our_seq = self._view_active()
        if active and not artificial:
            if our_seq + self._lag_tolerance < hb.seq:
                self._log.debugf(
                    "Heartbeat sequence is bigger than expected, leader's sequence is %d and ours is %d, syncing",
                    hb.seq, our_seq,
                )
                self._handler.sync()
                return
            if our_seq < hb.seq <= our_seq + self._lag_tolerance:
                self._follower_behind = True
                if our_seq > self._behind_seq:
                    self._behind_seq = our_seq
                    self._behind_counter = 0
            else:
                self._follower_behind = False
        else:
            self._follower_behind = False

        # fold the observed inter-arrival into the gap EWMA (a sign-of-
        # life cadence sample — artificial heartbeats count, they ARE
        # leader liveness).  Receipt-time clock, NOT _last_tick: tick
        # quantization would floor every sample at the tick interval and
        # feed the derivation back into itself (see __init__).  Capped at
        # the ceiling so one stale span cannot poison the derivation.
        self._leader_observed = True
        t = self._now() if self._now is not None else self._last_tick
        if self._last_hb_seen_at is not None:
            gap = min(t - self._last_hb_seen_at, self._hb_timeout)
            if gap > 0:
                self._hb_gap_ewma = gap if self._hb_gap_ewma <= 0 \
                    else 0.7 * self._hb_gap_ewma + 0.3 * gap
        self._last_hb_seen_at = t
        self._last_heartbeat = self._last_tick
        # idle-decay seam (ISSUE 15 residual e): tell the commit-interval
        # EWMA's owner the leader just proved itself alive — commit silence
        # WITNESSED by live heartbeats reads as "no load" and relaxes the
        # derived complain timer, while silence without them stays "maybe
        # no leader" and keeps the tight busy-era cadence
        sign_of_life = getattr(self._handler, "on_leader_sign_of_life", None)
        if sign_of_life is not None:
            sign_of_life(t)

    def _handle_heartbeat_response(self, sender: int, hbr: HeartBeatResponse) -> None:
        """f+1 higher-view responses force a sync (go:260-286)."""
        if self._follower or self._sync_req:
            return
        if self._view >= hbr.view:
            return
        self._hb_resp_collector[sender] = hbr.view
        _, f = compute_quorum(self._n)
        if len(self._hb_resp_collector) >= f + 1:
            self._log.infof(
                "Received HeartBeatResponse triggered a call to HeartBeatEventHandler Sync, view: %d",
                hbr.view,
            )
            self._handler.sync()
            self._sync_req = True

    def _send_heartbeat_response(self, target: int) -> None:
        self._comm.send_consensus(target, HeartBeatResponse(view=self._view))

    def _view_active(self) -> tuple[bool, int]:
        vs = self._view_sequences.load()
        if vs is None or not vs.view_active:
            return False, 0
        return True, vs.proposal_seq
