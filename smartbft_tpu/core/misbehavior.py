"""Per-sender misbehavior accounting: the defense substrate for ISSUE 18.

PBFT tolerates f lying replicas, but *tolerating* is not *free*: every
forged Prepare/Commit a Byzantine sender pushes at the shared verify plane
costs real device launch capacity (the amplification attack the coalescer
invites — Mir-BFT's request-duplication flood, aimed at signatures), and
until this table existed a failed verify verdict vanished into an
aggregate failure count nobody could act on.

:class:`MisbehaviorTable` turns per-signer verify attribution (the
``crypto.provider`` paths now report WHO signed every invalid verdict)
into a local defense decision:

* **accounting** — per-sender counters by cause, exported via
  :meth:`snapshot` (bench `byzantine` rows, chaos oracles) and mirrored
  into the embedder's metrics by the provider;
* **shunning** — a sender whose *cryptographically provable* misbehavior
  (invalid signature values, digest-binding forgeries, unknown-signer
  claims) crosses ``shun_threshold`` within a decay window is locally
  shunned: the Controller drops its Prepare/Commit votes at intake
  (BEFORE they reach the verify plane, so the flood stops costing
  launches) and its forwarded client requests lose the PR 8
  admission-gate bypass (forgers are shed first under overload);
* **redemption** — :meth:`decay` halves every score (the Consensus facade
  ticks it), so a sender that stops misbehaving drains back below the
  release threshold and is un-shunned: transient key-rollover mishaps do
  not amount to a permanent local partition.

What shunning deliberately does NOT do: touch the deterministic
window-boundary blacklist (``core.util.compute_blacklist_update``).  That
blacklist is recomputed identically by every replica from *shared*
view-change evidence; feeding node-local observations into it would fork
the computation.  The two layers compose instead: equivocating leaders
land on the shared blacklist via the view changes they cause, while vote
forgers — who never need to be leader to burn launch capacity — are cut
off locally by this table.  :meth:`note_blacklisted` records when the
shared blacklist corroborates a local suspect (the ``corroborated``
counter chaos oracles read).

Only provable causes count toward shunning.  Observational causes
(``stale_view`` replays, wrong-digest votes) are counted for visibility
but never shun: an honest replica racing a view change emits both.
"""

from __future__ import annotations

import threading
from typing import Optional

__all__ = ["MisbehaviorTable", "PROVABLE_CAUSES", "OBSERVED_CAUSES"]

#: causes that are cryptographically attributable to the sender — no
#: third party can make an honest node emit one (signatures don't forge)
PROVABLE_CAUSES = frozenset({
    "invalid_sig",       # well-formed vote, signature value fails the engine
    "binding_mismatch",  # signed ConsenterSigMsg binds a foreign digest
    "unknown_signer",    # claims a signer id outside the membership
})

#: causes an honest sender can exhibit under races/faults — counted for
#: the operator, never fed into the shun score
OBSERVED_CAUSES = frozenset({
    "stale_view",        # replayed message from a view this node left
    "sync_poisoned",     # tampered sync material (net-layer attribution)
    "stale_read",        # read reply contradicting an f+1 committed stamp
                         # (stale beyond the client's bound, or a digest
                         # mismatch at matched height) — read replies are
                         # unsigned, so this is evidence, never shun input
})


class MisbehaviorTable:
    """Per-sender misbehavior scores with threshold shunning and decay.

    Thread-safe: verify attribution arrives from coalescer worker threads
    (the sync provider paths) while intake shedding reads ``is_shunned``
    on the event loop.  The shunned set is mirrored into a lock-free
    frozenset so the hot intake path costs one attribute read + one set
    membership test.
    """

    def __init__(self, *, self_id: int = 0, shun_threshold: int = 8,
                 release_threshold: Optional[int] = None,
                 logger=None, recorder=None, metrics=None):
        """``shun_threshold``: provable-cause score at which a sender is
        shunned (8 = far above anything honest: an honest replica's votes
        simply verify).  ``release_threshold``: decayed score at which a
        shunned sender is released (default half the shun threshold —
        hysteresis against flapping at the boundary)."""
        if shun_threshold < 1:
            raise ValueError(f"shun_threshold must be >= 1, got {shun_threshold}")
        self.self_id = self_id
        self.shun_threshold = shun_threshold
        self.release_threshold = (
            release_threshold if release_threshold is not None
            else max(1, shun_threshold // 2)
        )
        if self.release_threshold >= shun_threshold:
            raise ValueError("release_threshold must be below shun_threshold")
        self.logger = logger
        self.recorder = recorder
        self.metrics = metrics  # BlacklistMetrics-shaped or None
        self._lock = threading.Lock()
        #: sender -> cause -> lifetime count (never decays; the export)
        self._counts: dict[int, dict[str, int]] = {}
        #: sender -> decayed provable score (the shun input)
        self._scores: dict[int, float] = {}
        #: lock-free mirror for the intake hot path
        self._shunned: frozenset[int] = frozenset()
        #: votes dropped at intake per shunned sender
        self._shed: dict[int, int] = {}
        self.shun_events = 0
        self.release_events = 0
        #: local suspects later confirmed by the SHARED deterministic
        #: blacklist (note_blacklisted) — the corroboration oracle
        self.corroborated: set[int] = set()

    # ------------------------------------------------------------ recording

    def note(self, sender: int, cause: str, n: int = 1) -> None:
        """Record ``n`` observations of ``cause`` against ``sender``.
        Provable causes feed the shun score; observed causes only count."""
        if n <= 0 or sender == self.self_id:
            # a replica never shuns itself — its own verify failures are
            # an engine/keyring problem, not wire misbehavior
            return
        with self._lock:
            by_cause = self._counts.setdefault(sender, {})
            by_cause[cause] = by_cause.get(cause, 0) + n
            if cause not in PROVABLE_CAUSES:
                return
            score = self._scores.get(sender, 0.0) + n
            self._scores[sender] = score
            if sender in self._shunned or score < self.shun_threshold:
                return
            self._shunned = self._shunned | {sender}
            self.shun_events += 1
            shunned_now = len(self._shunned)
        if self.metrics is not None:
            self.metrics.count_black_list.set(float(shunned_now))
        if self.recorder is not None and getattr(self.recorder, "enabled", False):
            self.recorder.record("misbehavior.shun", key=f"sender-{sender}",
                                 extra={"cause": cause, "score": score})
        if self.logger is not None:
            self.logger.warnf(
                "MISBEHAVIOR: shunning sender %d (provable score %.0f >= %d, "
                "last cause %s) — votes dropped at intake, forward bypass "
                "revoked", sender, score, self.shun_threshold, cause,
            )

    def note_shed(self, sender: int, n: int = 1) -> None:
        """Count votes dropped at intake because ``sender`` is shunned."""
        with self._lock:
            self._shed[sender] = self._shed.get(sender, 0) + n

    def note_blacklisted(self, nodes) -> None:
        """The SHARED deterministic blacklist named ``nodes``: record which
        of them this table had independently suspected (score > 0)."""
        with self._lock:
            for node in nodes:
                if self._scores.get(node, 0.0) > 0 or node in self._shunned:
                    self.corroborated.add(int(node))

    # ------------------------------------------------------------ reading

    def is_shunned(self, sender: int) -> bool:
        return sender in self._shunned

    def shunned(self) -> frozenset[int]:
        return self._shunned

    def score(self, sender: int) -> float:
        with self._lock:
            return self._scores.get(sender, 0.0)

    def counts(self, sender: int) -> dict:
        with self._lock:
            return dict(self._counts.get(sender, {}))

    # ------------------------------------------------------------ lifecycle

    def decay(self, factor: float = 0.5) -> None:
        """Halve every provable score; release shunned senders that have
        drained below the release threshold.  The Consensus facade ticks
        this on the shared scheduler, so logical-clock tests control
        redemption timing exactly."""
        released = []
        with self._lock:
            for sender in list(self._scores):
                score = self._scores[sender] * factor
                if score < 0.5:
                    del self._scores[sender]
                    score = 0.0
                else:
                    self._scores[sender] = score
                if sender in self._shunned and score <= self.release_threshold:
                    self._shunned = self._shunned - {sender}
                    self.release_events += 1
                    released.append(sender)
            shunned_now = len(self._shunned)
        if released:
            if self.metrics is not None:
                self.metrics.count_black_list.set(float(shunned_now))
            if self.logger is not None:
                self.logger.infof(
                    "MISBEHAVIOR: released %s from the local shun set "
                    "(decayed below %d)", released, self.release_threshold,
                )

    def snapshot(self) -> dict:
        """Accounting export (bench `byzantine` rows, chaos oracles)."""
        with self._lock:
            return {
                "by_sender": {s: dict(c) for s, c in self._counts.items()},
                "scores": dict(self._scores),
                "shunned": sorted(self._shunned),
                "shed_votes": dict(self._shed),
                "shun_events": self.shun_events,
                "release_events": self.release_events,
                "corroborated": sorted(self.corroborated),
            }
