"""Pipelined in-flight window: k sequences outstanding at once.

No reference counterpart — this is the one deliberate protocol DEPARTURE
from /root/reference (SURVEY §7(c) anticipated it).  The reference keeps
exactly one sequence in flight: the leader re-acquires the propose token
only after the current decision delivers (controller.go:555-557) and the
View pipelines only vote *collection* one sequence ahead
(view.go:107-113,860-894).  On an accelerator whose fixed per-launch cost
dominates the quorum-verification kernel, that shape pays one launch per
decision, strictly serialized — the launch floor can never be amortized.

:class:`WindowedView` runs a window of up to ``2k`` per-sequence slots,
each a miniature three-phase machine (pre-prepare -> prepare -> commit),
with three global invariants that keep the safety argument inductive:

* **In-order prepare-send**: a slot persists its ProposedRecord and sends
  its prepare only after every lower slot did (WAL suffix stays ordered,
  so crash restore rebuilds the window unambiguously).
* **In-order commit-send**: a slot signs/broadcasts its commit only after
  every lower slot did.  Hence a commit quorum at seq s implies quorum
  commit-sends at every s' < s, and the multi-in-flight view change
  (viewchanger.check_in_flight_ladder) inherits the single-slot quorum-
  intersection argument rung by rung.
* **In-order delivery**: slot s hands its decision to the Controller only
  after s-1 delivered (the reference's decide rendezvous, unchanged).

Commit-signature verification is NOT ordered: each slot flushes its quorum
wave as an independent task through ``verify_consenter_sigs_batch_async``,
so the waves of k consecutive sequences sit in the coalescer concurrently
and merge into ONE device launch — the cross-decision batching axis that
divides the launch floor by the window depth.

**Launch-shadow overlap.**  The propose window is TWO windows deep: the
leader fills the base window [low, low+k) unconditionally, and once every
base-window slot has staged its commit — the point where the only work
left in the base window is the device verify wave plus in-order delivery
— it keeps proposing into the shadow region [low+k, low+2k).  The shadow
sequences run their whole protocol plane (pre-prepare, prepares, commit
staging) UNDER the in-flight launch, and their verify waves accumulate in
the coalescer, flushing the moment the device frees.  Without the shadow
the protocol plane idles for the full launch duration at every window
boundary, so the launch cost is serialized with the protocol cost instead
of hidden behind it.  When shadow capacity opens without a delivery the
view notifies the Controller through the ``capacity_cb`` seam so the
leader token re-arms (``Controller.on_window_capacity``).  Message intake
accepts sequences up to 3k ahead of the delivery frontier — one extra
window of skew tolerance for replicas whose frontier trails the
leader's — so slot memory is bounded by 3k slots.

**Window-granular rotation** (``rotation_granularity='window'``): the
reference rotation protocol chains each pre-prepare to the PREVIOUS
decision's commit certificate (view.go:606-647,1022-1062), which a
pipelined leader does not hold yet — so per-decision chaining and
pipelining are mutually exclusive.  Instead of abandoning rotation, the
windowed view anchors the chain on the LAST DECISION OF EACH WINDOW: only
the first pre-prepare of a window carries prev-commit signatures (the
previous window's anchor certificate, read from the checkpoint) plus the
recomputed blacklist; every other proposal in the window carries the SAME
blacklist and an empty certificate, which followers enforce.  Window
boundaries are defined by the cluster-agreed per-view decision count
(``decisions_in_view % k == 0``), so they are identical on every replica
— including one that crash-restarts mid-window or joins by sync.  The
cost: the pipeline drains at each window boundary (the anchor must
DELIVER before the next window's first proposal can be built or
verified), so the launch shadow does not cross boundaries in rotation
mode.  ``decisions_per_leader`` is interpreted in windows — config
pre-multiplies it into decisions (Configuration.
effective_decisions_per_leader) so every get_leader_id/blacklist
computation stays reference-shaped.  With rotation off
(``decisions_per_leader == 0``) the blacklist is empty by protocol and
pre-prepares carry no prev-commit signatures, which this class enforces.

WAL truncation cadence: a ProposedRecord carries the truncate mark only
when its sequence IS the delivery frontier (mid-window records must
survive a crash for restore to rebuild the ladder).  Under sustained
saturation the frontier-aligned append would otherwise never land, so the
view bounds segment growth itself: after ``max(8k, 64)`` consecutive
non-truncating saves it stops admitting new proposals (``_drain_pending``)
until the window drains; the next proposal then lands at the delivery
frontier with the truncate mark, old segments are deleted at the next
file rotation, and proposing resumes.  The cost is one window's latency
every few dozen decisions; any natural load dip truncates for free and
resets the counter.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field, replace
from typing import Optional

from ..api import Logger, Signer, Verifier
from ..codec import decode, encode
from ..messages import (
    Commit,
    CommitRecord,
    Message,
    PreparesFrom,
    PrePrepare,
    Prepare,
    Proposal,
    ProposedRecord,
    Signature,
    ViewMetadata,
)
from ..metrics import BlacklistMetrics, ViewMetrics
from ..types import (
    VerifyPlaneDown,
    blacklist_of,
    cached_view_metadata,
    proposal_digest,
)
from ..metrics import current_plane
from .rotation import RotationState
from .state import ABORT, COMMITTED, PREPARED, PROPOSED
from .util import SignerIndex, VoteSet, compute_quorum, iter_bits
from ..utils.tasks import create_logged_task
from .view import (
    ViewAborted,
    ViewSequence,
    ViewSequencesHolder,
    proposal_sequence_of_msg,
    verify_sigs_batch,
    view_number_of_msg,
)

#: slot-local pseudo-phase: quorum of valid commits collected, awaiting
#: in-order delivery (the single-slot View has no equivalent state — it
#: delivers immediately)
READY = 100


@dataclass
class _Slot:
    seq: int
    #: shared per-cluster SignerIndex: the slot's vote sets and masks all
    #: key on the same dense bit layout (integer ops, no hashing)
    index: Optional[SignerIndex] = None
    phase: int = COMMITTED
    pre_prepare: Optional[PrePrepare] = None
    proposal: Optional[Proposal] = None
    digest: str = ""
    requests: list = field(default_factory=list)
    prepares: VoteSet = None  # type: ignore[assignment]
    commits: VoteSet = None  # type: ignore[assignment]
    prepare_sent: Optional[Prepare] = None
    commit_sent: Optional[Commit] = None
    my_sig: Optional[Signature] = None
    prepare_voters: list[int] = field(default_factory=list)
    prepares_taken_mask: int = 0
    commits_taken_mask: int = 0
    pending_sigs: list = field(default_factory=list)
    seen_mask: int = 0  # signers with an accepted (verified-valid) commit
    valid_sigs: list = field(default_factory=list)
    verify_inflight: bool = False
    verify_failures: int = 0
    begin: float = 0.0

    def __post_init__(self):
        self.prepares = VoteSet(
            lambda _s, m: isinstance(m, Prepare), self.index
        )

        def accept_commit(sender: int, m: Message) -> bool:
            if not isinstance(m, Commit) or m.signature is None:
                return False
            return m.signature.signer == sender  # view.go:160-171

        self.commits = VoteSet(accept_commit, self.index)


@dataclass(frozen=True)
class _ProposalInfo:
    digest: str
    view: int
    seq: int


class WindowedView:
    """Drop-in View replacement for ``pipeline_depth >= 2`` (static leader
    or window-granular rotation).

    Same interface the Controller and ViewChanger consume: handle_message /
    start / abort / stopped / propose / get_metadata / get_leader_id plus
    the ``phase`` / ``proposal_sequence`` / ``number`` attributes.
    """

    #: WAL-drain trigger: consecutive non-truncating saves before proposing
    #: pauses for one window so a truncating append can land.  None derives
    #: max(8 * window, 64); tests/deployments override the class attribute
    #: to tighten the segment-growth bound.
    DRAIN_AFTER_SAVES: Optional[int] = None

    def __init__(
        self,
        *,
        self_id: int,
        n: int,
        nodes_list: list[int],
        leader_id: int,
        quorum: int,
        number: int,
        decider,
        failure_detector,
        synchronizer,
        logger: Logger,
        comm,
        verifier: Verifier,
        signer: Signer,
        proposal_sequence: int,
        decisions_in_view: int,
        state,
        retrieve_checkpoint,
        view_sequences: ViewSequencesHolder,
        window: int,
        in_flight=None,
        metrics_view: Optional[ViewMetrics] = None,
        capacity_cb=None,
        decisions_per_leader: int = 0,
        membership_notifier=None,
        metrics_blacklist: Optional[BlacklistMetrics] = None,
        recorder=None,
    ):
        self.self_id = self_id
        self.n = n
        self.nodes_list = nodes_list
        self.leader_id = leader_id
        self.quorum = quorum
        self.number = number
        self.decider = decider
        self.failure_detector = failure_detector
        self.synchronizer = synchronizer
        self.logger = logger
        self.comm = comm
        self.verifier = verifier
        self.signer = signer
        self.proposal_sequence = proposal_sequence  # lowest undelivered seq
        self.decisions_in_view = decisions_in_view
        self.state = state
        self.retrieve_checkpoint = retrieve_checkpoint
        self.view_sequences = view_sequences
        self.window = max(2, int(window))
        self.in_flight = in_flight
        self.metrics = metrics_view
        # flight recorder: per-slot quorum-completion + WAL-persist marks
        # for the critical-path decomposition (obs.critpath); the nop
        # singleton keeps every site at one attribute read when off
        from ..obs.recorder import NOP_RECORDER

        self.recorder = recorder if recorder is not None else NOP_RECORDER
        #: one dense signer-id index shared by every slot's vote sets
        self._signer_index = SignerIndex(nodes_list)
        #: called (no args) when propose capacity re-opens WITHOUT a
        #: delivery — the launch-shadow gate unlocking, or a WAL drain
        #: completing; the Controller re-arms the leader token on it
        self.capacity_cb = capacity_cb

        # reference-anchored bookkeeping for metadata checks: the expected
        # decisions_in_view of seq s is start_dec + (s - start_seq)
        self._start_seq = proposal_sequence
        self._start_dec = decisions_in_view

        # window-granular rotation (decisions_per_leader is the EFFECTIVE
        # per-decision value, i.e. config decisions_per_leader x window)
        self.decisions_per_leader = decisions_per_leader
        self.rotation = decisions_per_leader > 0
        self._rotation = RotationState(
            self_id=self_id,
            n=n,
            nodes_list=nodes_list,
            leader_id=leader_id,
            get_view_number=lambda: self.number,
            decisions_per_leader=decisions_per_leader,
            verifier=verifier,
            retrieve_checkpoint=retrieve_checkpoint,
            membership_notifier=membership_notifier,
            logger=logger,
            metrics_blacklist=metrics_blacklist,
        )
        # the blacklist established by the current window's FIRST proposal:
        # followers require every later proposal in the window to match it
        # (_staged_blacklist tracks the staging frontier) and the leader
        # stamps it into mid-window metadata (_proposing_blacklist).  Both
        # seed from the checkpoint — mid-window (re)starts land between two
        # boundary recomputations, and every delivered proposal of a window
        # carries that window's blacklist, so the checkpoint metadata IS the
        # current window blacklist.
        ckpt_bl: list[int] = []
        if self.rotation:
            ckpt_prop, _ = retrieve_checkpoint()
            if ckpt_prop is not None:
                ckpt_bl = blacklist_of(ckpt_prop)
        self._staged_blacklist: list[int] = list(ckpt_bl)
        self._proposing_blacklist: list[int] = list(ckpt_bl)

        #: exposed for the Controller's init-phase logic; tracks the lowest
        #: undelivered slot (COMMITTED when none)
        self.phase = COMMITTED
        self.my_proposal_sig: Optional[Signature] = None  # per-slot; kept for API parity

        self.slots: dict[int, _Slot] = {}
        self._next_propose_seq = proposal_sequence  # leader only
        self._prepare_frontier = proposal_sequence - 1  # highest seq whose prepare was sent
        self._commit_frontier = proposal_sequence - 1  # highest seq whose commit was sent
        # per-seq history of our own prepare/commit for lagging-replica
        # assists (the single-slot View keeps exactly seq-1,
        # view.go:718-756; a window keeps its whole trailing edge)
        self._sent_history: dict[int, tuple[Optional[Prepare], Optional[Commit]]] = {}
        self._last_voted_proposal_by_id: dict[int, Commit] = {}

        # Direct synchronous ingest — no per-message queue.  Every task in
        # this process shares one event loop, so _process_msg (which never
        # awaits) is atomic with respect to the advance loop; routing a
        # message straight into its slot's vote set replaces the reference's
        # channel hop (view.go:274) and saves a queue put/get plus a task
        # wakeup per message — at n=64 that is ~12k hops per decision.
        # Memory stays bounded WITHOUT an inbox cap: vote sets dedup per
        # sender, pre-prepare slots are 1-per-seq, and the window holds at
        # most 3*window slots (base + launch shadow + intake skew).
        self._work = asyncio.Event()
        self._verify_results: list[tuple] = []
        self._aborted = False
        self._abort_event = asyncio.Event()
        # persistent abort sentinel for the decide rendezvous: created
        # lazily on first delivery, reused for every decision, cancelled
        # once in _run's teardown — the per-decision create+cancel pair
        # was a measurable fixed cost of the deliver segment
        self._abort_wait_task: Optional[asyncio.Task] = None
        self._task: Optional[asyncio.Task] = None
        self._verify_tasks: set[asyncio.Task] = set()
        self._restored_broadcasts: list[Message] = []

        # WAL segment-growth bound under saturation (module docstring): a
        # drain pauses proposing until the window empties so the next
        # ProposedRecord lands frontier-aligned with the truncate mark
        self._drain_after = self.DRAIN_AFTER_SAVES or max(8 * self.window, 64)
        self._saves_since_truncate = 0
        self._drain_pending = False
        self._could_accept = True  # last can_accept_more_proposals() edge

    # ------------------------------------------------------------------ life

    def start(self) -> None:
        self._task = create_logged_task(
            self._run(), name=f"wview-{self.self_id}-{self.number}",
            logger=self.logger,
        )

    def stopped(self) -> bool:
        return self._aborted

    def _stop(self) -> None:
        if not self._aborted:
            self._aborted = True
            self._work.set()
            self._abort_event.set()

    async def handle_message_async(self, sender: int, msg: Message) -> None:
        """Async-intake shim: direct ingest never blocks (memory is bounded
        by vote-set dedup + the slot window), so backpressure is a no-op."""
        self.handle_message(sender, msg)

    async def abort(self) -> None:
        """view.go:1000-1010 semantics; see View.abort for the cancellation
        contract."""
        self._stop()
        # depose-time plane warmth (ISSUE 15): waves this window already
        # handed to the coalescer flush + launch NOW instead of idling in
        # the coalescing window/hold while the view change runs — the
        # mesh keeps verifying through the depose and the flip lands on a
        # warm plane.  Cancelling our awaiting tasks below does not
        # cancel the launches themselves; verifiers without the seam
        # no-op.
        depose = getattr(self.verifier, "note_view_depose", None)
        if depose is not None:
            try:
                depose()
            except Exception as e:  # noqa: BLE001 — warmth is advisory
                self.logger.warnf("depose verify warm failed: %r", e)
        for t in list(self._verify_tasks):
            t.cancel()
        if self._task is not None:
            try:
                await self._task
            except asyncio.CancelledError:
                cur = asyncio.current_task()
                # Task.cancelling is 3.11+; on 3.10 a finished view task
                # means the cancellation was the view's own — swallow it
                cancelling = getattr(cur, "cancelling", None)
                if not self._task.done() or (
                    cancelling is not None and cancelling()
                ):
                    raise

    def get_leader_id(self) -> int:
        return self.leader_id

    # ------------------------------------------------------------------ intake

    def handle_message(self, sender: int, msg: Message) -> None:
        if self._aborted:
            return
        try:
            self._process_msg(sender, msg)
        except ViewAborted:
            pass  # _stop() already latched; the run loop exits on its own
        except Exception as e:
            # contain ingest failures the way the old queued path's run-loop
            # handler did: tear the view down loudly instead of letting the
            # exception escape into the transport's receive loop
            self.logger.errorf(
                "WindowedView %d failed processing a message from %d: %r",
                self.number, sender, e,
            )
            self._stop()
        self._work.set()

    def ingest_batch(self, items) -> None:
        """Wave-batched intake: register a whole wave of (sender, msg)
        pairs — e.g. all n-1 prepares of a phase — in ONE call with ONE
        run-loop wakeup, instead of ~n handle_message call chains each
        setting the work event.  Direct ingest never blocks (vote-set dedup
        + the slot window bound memory), so the batch is synchronous."""
        if self._aborted:
            return
        t0 = time.perf_counter()
        try:
            for sender, msg in items:
                self._process_msg(sender, msg)
        except ViewAborted:
            pass
        except Exception as e:
            self.logger.errorf(
                "WindowedView %d failed processing a message batch: %r",
                self.number, e,
            )
            self._stop()
        current_plane().vote_reg_us += (time.perf_counter() - t0) * 1e6
        self._work.set()

    # ------------------------------------------------------------------ windows

    def _dec_of(self, seq: int) -> int:
        """Cluster-agreed decisions_in_view of ``seq`` (verified in
        _verify_proposal, so every replica derives the same value)."""
        return self._start_dec + (seq - self._start_seq)

    def _is_window_first(self, seq: int) -> bool:
        """Rotation mode: is ``seq`` the first decision of a window?  The
        grid is anchored on the per-view decision count, NOT on this view
        object's construction point — a mid-window crash-restart or sync
        constructs the view mid-grid and must agree with the cluster."""
        return self._dec_of(seq) % self.window == 0

    def _checkpoint_at(self, seq: int) -> bool:
        """True iff the checkpoint holds exactly the decision below ``seq``
        — the anchor a window-first pre-prepare chains to.  On the propose
        hot path, so the metadata decode rides the bounded cache."""
        prop, _ = self.retrieve_checkpoint()
        latest = 0
        if prop is not None and prop.metadata:
            latest = cached_view_metadata(prop.metadata).latest_sequence
        return latest == seq - 1

    # ------------------------------------------------------------------ leader

    def can_accept_more_proposals(self) -> bool:
        """Leader: may another proposal enter the window right now?

        Rotation off — base window [low, low+k) is always proposable, and
        the shadow region [low+k, low+2k) opens only once every base-window
        slot has staged its commit (commit frontier at the base edge): from
        that point the base window is waiting purely on the device wave +
        in-order delivery, so the next window's protocol plane runs in the
        shadow of the in-flight launch instead of idling behind it.

        Rotation on (window granularity) — proposing is confined to the
        delivery frontier's window: the next window's first pre-prepare
        chains to THIS window's anchor certificate, which exists only once
        the anchor has delivered (and the checkpoint advanced to it).  The
        pipeline therefore drains at each boundary; no launch shadow
        crosses it."""
        if self._aborted or self._drain_pending:
            return False
        nxt = self._next_propose_seq
        low = self.proposal_sequence
        if self.rotation:
            if self._dec_of(nxt) // self.window != self._dec_of(low) // self.window:
                return False
            if self._is_window_first(nxt) and not self._checkpoint_at(nxt):
                # the delivery frontier can run ahead of the checkpoint by
                # one decide rendezvous (proposal_sequence advances before
                # the controller delivers); the chain needs the certificate
                return False
            return True
        if nxt < low + self.window:
            return True
        if nxt >= low + 2 * self.window:
            return False
        return self._commit_frontier >= low + self.window - 1

    def get_metadata(self) -> bytes:
        """Metadata for the NEXT unproposed sequence (view.go:896-948).

        Rotation off: empty blacklist, no prev-commit digest.  Rotation on:
        a window-first sequence recomputes the blacklist from the anchor
        checkpoint and binds the anchor certificate digest (exactly the
        single-slot per-decision flow, once per window); mid-window
        sequences restate the window blacklist with no certificate."""
        nxt = self._next_propose_seq
        metadata = ViewMetadata(
            view_id=self.number,
            latest_sequence=nxt,
            decisions_in_view=self._dec_of(nxt),
        )
        if not self.rotation:
            return encode(metadata)
        if self._is_window_first(nxt):
            metadata = self._rotation.build_leader_metadata(metadata)
            self._proposing_blacklist = list(metadata.black_list)
        else:
            metadata = replace(metadata, black_list=list(self._proposing_blacklist))
        return encode(metadata)

    def propose(self, proposal: Proposal) -> None:
        """Leader: wrap as pre-prepare for the next window sequence and
        self-deliver first (WAL-first, view.go:951-977).  The broadcast to
        peers happens after the slot persists the ProposedRecord.  In
        rotation mode a window-first pre-prepare carries the previous
        window's anchor certificate (the checkpoint signatures)."""
        prev_sigs: list[Signature] = []
        if self.rotation and self._is_window_first(self._next_propose_seq):
            _, prev_sigs = self.retrieve_checkpoint()
        pp = PrePrepare(
            view=self.number,
            seq=self._next_propose_seq,
            proposal=proposal,
            prev_commit_signatures=list(prev_sigs),
        )
        self._next_propose_seq += 1
        if not self._aborted:
            try:
                self._process_msg(self.leader_id, pp)
            except ViewAborted:
                pass
            self._work.set()
        self.logger.debugf(
            "Proposing sequence %d in view %d (window %d..%d)",
            pp.seq, self.number, self.proposal_sequence, self._next_propose_seq - 1,
        )

    # ------------------------------------------------------------------ loop

    async def _run(self) -> None:
        try:
            for m in self._restored_broadcasts:
                self.comm.broadcast_consensus(m)
            self._restored_broadcasts = []
            while True:
                self._absorb_pending_verify_results()
                progressed = await self._advance()
                if self._aborted:
                    raise ViewAborted()
                if progressed:
                    continue
                if self._verify_results:
                    continue  # arrived during _advance's awaits
                await self._work.wait()
                self._work.clear()
                if self._aborted:
                    raise ViewAborted()
        except ViewAborted:
            pass
        except Exception as e:  # pragma: no cover - defensive
            self.logger.errorf("WindowedView %d crashed: %r", self.number, e)
            raise
        finally:
            for t in list(self._verify_tasks):
                t.cancel()
            if self._abort_wait_task is not None:
                self._abort_wait_task.cancel()
                self._abort_wait_task = None
            self.view_sequences.store(
                ViewSequence(view_active=False, proposal_seq=self.proposal_sequence)
            )

    def _absorb_pending_verify_results(self) -> None:
        while self._verify_results:
            seq, sigs, results = self._verify_results.pop(0)
            self._absorb_verify_results(seq, sigs, results)

    # ------------------------------------------------------------------ routing

    def _process_msg(self, sender: int, m: Message) -> None:
        """view.go:194-261 adapted to a window of sequences."""
        if self._aborted:
            return
        msg_view = view_number_of_msg(m)
        msg_seq = proposal_sequence_of_msg(m)

        if msg_view != self.number:
            if sender != self.leader_id:
                self._discover_if_sync_needed(sender, m)
                return
            self.failure_detector.complain(self.number, False)
            if msg_view > self.number:
                self.synchronizer.sync()
            self._stop()
            return

        low = self.proposal_sequence
        if msg_seq < low:
            self._handle_prev_seq_message(msg_seq, sender, m)
            return
        # intake span = propose span (2 windows: base + launch shadow) + one
        # window of frontier-skew tolerance, so a replica whose delivery
        # frontier trails the leader's still accepts shadow pre-prepares
        span = 3 * self.window
        if msg_seq >= low + span:
            self.logger.warnf(
                "%d got message from %d with sequence %d outside window [%d, %d)",
                self.self_id, sender, msg_seq, low, low + span,
            )
            self._discover_if_sync_needed(sender, m)
            return

        slot = self.slots.get(msg_seq)
        if slot is None:
            slot = self.slots[msg_seq] = _Slot(
                seq=msg_seq, index=self._signer_index
            )

        if isinstance(m, PrePrepare):
            if m.proposal is None:
                self.logger.warnf(
                    "%d got pre-prepare from %d with empty proposal", self.self_id, sender
                )
                return
            if sender != self.leader_id:
                self.logger.warnf(
                    "%d got pre-prepare from %d but the leader is %d",
                    self.self_id, sender, self.leader_id,
                )
                return
            if slot.pre_prepare is None and slot.phase == COMMITTED:
                slot.pre_prepare = m
            return

        if sender == self.self_id:
            return  # own votes are implicit (view.go:238-241)

        if isinstance(m, Prepare):
            slot.prepares.register_vote(sender, m)
            # in-window assist (the windowed analogue of view.go:718-756):
            # each broadcast is one-shot here, so a peer still collecting
            # prepares at a sequence we have already COMMITTED on likely
            # lost ours — resend it directly.  Gating on our phase being
            # ahead keeps steady-state traffic clean: in lockstep operation
            # prepares arrive while we are still in PROPOSED ourselves.
            if (
                not m.assist
                and slot.phase in (PREPARED, READY)
                and slot.prepare_sent is not None
            ):
                self.comm.send_consensus(sender, slot.prepare_sent)
        elif isinstance(m, Commit):
            slot.commits.register_vote(sender, m)
            if (
                not m.assist
                and slot.phase == READY
                and slot.commit_sent is not None
            ):
                self.comm.send_consensus(sender, slot.commit_sent)

    # ------------------------------------------------------------------ advance

    async def _advance(self) -> bool:
        """Run every enabled state transition once; True if any fired.

        Transitions are attempted lowest-sequence-first so the in-order
        invariants (prepare-send, commit-send, delivery) fall out of the
        iteration order plus the frontier guards."""
        progressed = False
        # Stage -> one durability wave -> finalize: each ready slot's WAL
        # record is WRITTEN during staging (record order = staged order =
        # sequence order, keeping the in-order save invariants), then ALL
        # staged records await one shared fsync wave, then finalization
        # broadcasts in sequence order.  Sequentially awaiting per-slot
        # saves instead cost k wave round-trips per window.
        staged: list = []  # (durability_future_or_None, finalize)
        for seq in sorted(self.slots):
            slot = self.slots.get(seq)
            if slot is None:
                continue
            if (
                slot.phase == COMMITTED
                and slot.pre_prepare is not None
                and seq == self._prepare_frontier + 1
                # rotation: a window-first pre-prepare chains to the previous
                # window's anchor certificate — hold it until every lower
                # sequence has DELIVERED locally (the checkpoint then sits
                # exactly at the anchor, making the chain verifiable)
                and (
                    not self.rotation
                    or not self._is_window_first(seq)
                    or seq == self.proposal_sequence
                )
            ):
                staged.append(await self._stage_proposal(slot))
                progressed = True
            if (
                slot.phase == PROPOSED
                and seq == self._commit_frontier + 1
                and self._count_prepares(slot) >= self.quorum - 1
            ):
                staged.append(self._stage_commit(slot))
                progressed = True
            if slot.phase == PREPARED:
                self._maybe_flush_verify(slot)
        if staged:
            futs = [f for f, _ in staged if f is not None]
            if futs:
                await asyncio.gather(*futs)
            if self._aborted:
                raise ViewAborted()
            for _, finalize in staged:
                finalize()
        # wave-batched delivery: a commit burst (one network flush carrying
        # the whole window's commits) turns several consecutive slots READY
        # at once — deliver the entire in-order run in THIS pass instead of
        # paying one full _advance rescan per decision
        low = self.slots.get(self.proposal_sequence)
        while low is not None and low.phase == READY:
            await self._deliver(low)
            progressed = True
            low = self.slots.get(self.proposal_sequence)
        self.phase = self._lowest_phase()
        if self.metrics:
            self.metrics.phase.set(self.phase)
        # launch-shadow/drain edge: capacity can re-open WITHOUT a delivery
        # (the base window's last commit staged, or a drain completed) — the
        # Controller only re-arms the leader token on deliveries, so tell it
        can_now = self.can_accept_more_proposals()
        if (
            can_now
            and not self._could_accept
            and self.self_id == self.leader_id
            and self.capacity_cb is not None
        ):
            self.capacity_cb()
        self._could_accept = can_now
        return progressed

    def _lowest_phase(self) -> int:
        if self._aborted:
            return ABORT
        low = self.slots.get(self.proposal_sequence)
        if low is None:
            return COMMITTED
        return low.phase if low.phase != READY else PREPARED

    # -- phase 1: proposal --------------------------------------------------

    async def _stage_proposal(self, slot: _Slot):
        """COMMITTED -> PROPOSED for one slot (view.go:351-427), split into
        stage (verify + WAL write now) and finalize (sends, after the shared
        durability wave).  Async because a rotation-mode window-first slot
        batch-verifies the anchor certificate it chains to."""
        pp = slot.pre_prepare
        proposal = pp.proposal
        try:
            requests = await self._verify_proposal(slot, pp)
        except VerifyPlaneDown as e:
            # the verify PLANE is down, not the proposal: don't blame the
            # leader — escalate to sync and re-validate after recovery
            self.logger.errorf(
                "Verify plane down validating seq %d: %s; aborting view "
                "and syncing", slot.seq, e,
            )
            self.synchronizer.sync()
            self._stop()
            raise ViewAborted() from e
        except Exception as e:
            self.logger.warnf(
                "%d received bad proposal from %d at seq %d: %s",
                self.self_id, self.leader_id, slot.seq, e,
            )
            self.failure_detector.complain(self.number, False)
            self.synchronizer.sync()
            self._stop()
            raise ViewAborted() from e

        slot.proposal = proposal
        slot.digest = proposal_digest(proposal)
        slot.requests = requests
        slot.begin = time.monotonic()
        if self.metrics:
            self.metrics.count_txs_in_batch.set(len(requests))

        prepare = Prepare(view=self.number, seq=slot.seq, digest=slot.digest)
        # WAL-first: persist before any dependent send.  Truncation is safe
        # only when this slot is the whole window (all prior seqs
        # delivered) — mid-window the previous decisions' records must
        # survive a crash for restore to rebuild the ladder.
        truncate = slot.seq == self.proposal_sequence
        fut = self._write_state(ProposedRecord(pre_prepare=pp, prepare=prepare), truncate)
        self._prepare_frontier = slot.seq

        def finalize() -> None:
            if self.in_flight is not None:
                self.in_flight.store_proposal_at(slot.seq, proposal)
            slot.prepare_sent = replace(prepare, assist=True)
            slot.phase = PROPOSED
            self._sent_history[slot.seq] = (slot.prepare_sent, None)
            if self.self_id == self.leader_id:
                self.comm.broadcast_consensus(pp)
            self.comm.broadcast_consensus(prepare)
            self.logger.infof("Processed proposal with seq %d", slot.seq)

        return fut, finalize

    async def _verify_proposal(self, slot: _Slot, pp: PrePrepare) -> list:
        """view.go:553-607 adapted to the window: structural + metadata
        checks for every slot; certificate-chain + blacklist verification at
        window boundaries (rotation mode) or rotation-off invariants."""
        proposal = pp.proposal
        requests = self.verifier.verify_proposal(proposal)
        md = decode(ViewMetadata, proposal.metadata)
        if md.view_id != self.number:
            raise ValueError(f"invalid view number: expected {self.number} got {md.view_id}")
        if md.latest_sequence != slot.seq:
            raise ValueError(
                f"invalid proposal sequence: expected {slot.seq} got {md.latest_sequence}"
            )
        expected_dec = self._dec_of(slot.seq)
        if md.decisions_in_view != expected_dec:
            raise ValueError(
                f"invalid decisions in view: expected {expected_dec} got {md.decisions_in_view}"
            )
        expected_seq = self.verifier.verification_sequence()
        if proposal.verification_sequence != expected_seq:
            raise ValueError(
                f"verification sequence mismatch: expected {expected_seq} "
                f"got {proposal.verification_sequence}"
            )
        if not self.rotation:
            # rotation-off invariants (config.validate pins
            # decisions_per_leader to 0 then): no blacklist, no chaining
            if list(md.black_list):
                raise ValueError(
                    f"rotation is inactive but blacklist is not empty: {list(md.black_list)}"
                )
            if pp.prev_commit_signatures:
                raise ValueError(
                    "pipelined mode forbids prev commit signatures in pre-prepares"
                )
            return requests

        if self._is_window_first(slot.seq):
            # window boundary: the staging gate held this slot until every
            # lower sequence delivered, so the checkpoint is exactly the
            # anchor this pre-prepare chains to — the single-slot
            # per-decision verification applies verbatim
            prev_commits = list(pp.prev_commit_signatures)
            prepare_acks = await self._rotation.verify_prev_commit_signatures(
                prev_commits, expected_seq
            )
            self._rotation.verify_blacklist(
                prev_commits, expected_seq, list(md.black_list), prepare_acks
            )
            self._rotation.verify_prev_commit_digest(prev_commits, md)
            self._staged_blacklist = list(md.black_list)
        else:
            # mid-window: no certificate (it does not exist yet) and the
            # blacklist must restate the one the window's first proposal
            # established (staging is in-order, so it is already verified)
            if pp.prev_commit_signatures:
                raise ValueError(
                    "mid-window pre-prepares must not carry prev commit signatures"
                )
            if md.prev_commit_signature_digest:
                raise ValueError(
                    "mid-window pre-prepares must not bind a prev commit digest"
                )
            if list(md.black_list) != self._staged_blacklist:
                raise ValueError(
                    f"mid-window blacklist {list(md.black_list)} differs from the "
                    f"window blacklist {self._staged_blacklist}"
                )
        return requests

    # -- phase 2: prepares --------------------------------------------------

    def _count_prepares(self, slot: _Slot) -> int:
        # incremental bitmask sweep: only signers not counted yet — the
        # common case (no new votes) is one AND + one compare, no iteration
        vs = slot.prepares
        new = vs.mask & ~slot.prepares_taken_mask
        if new:
            slot.prepares_taken_mask |= new
            for idx in iter_bits(new):
                prepare: Prepare = vs.payloads[idx]
                if prepare.digest != slot.digest:
                    self.logger.warnf(
                        "Got wrong digest at processPrepares for prepare with seq %d",
                        prepare.seq,
                    )
                    continue
                slot.prepare_voters.append(vs.signer_id(idx))
        return len(slot.prepare_voters)

    def _stage_commit(self, slot: _Slot):
        """PROPOSED -> PREPARED for one slot (view.go:441-517), stage/
        finalize split like _stage_proposal.  Every arrived prepare is
        already registered (direct ingest), so the witness sweep is just the
        counting pass (PreparesFrom is liveness evidence)."""
        self._count_prepares(slot)
        rec = self.recorder
        if rec.enabled:
            # ingest-wave granularity, like View._process_prepares: ties
            # within the quorum-completing sweep resolve in signer-index
            # order
            rec.record(
                "quorum.prepare", view=self.number, seq=slot.seq,
                # quorum == 1: no peer votes, no voter to name (the [-1]
                # empty-list index would crash the view otherwise)
                extra={"slowest_voter":
                       slot.prepare_voters[self.quorum - 2]
                       if self.quorum >= 2
                       and len(slot.prepare_voters) >= self.quorum - 1
                       else -1,
                       "voters": len(slot.prepare_voters)},
            )
        prp_from = encode(PreparesFrom(ids=slot.prepare_voters))
        sig = self.signer.sign_proposal(slot.proposal, prp_from)
        slot.my_sig = sig
        commit = Commit(
            view=self.number,
            seq=slot.seq,
            digest=slot.digest,
            signature=Signature(signer=sig.signer, value=sig.value, msg=sig.msg),
        )
        fut = self._write_state(CommitRecord(commit=commit), truncate=False)
        self._commit_frontier = slot.seq

        def finalize() -> None:
            if rec.enabled:
                # runs after the shared durability wave: the commit
                # record is on disk (the WAL-first rule), so this is the
                # wal_persist mark of the critical path
                rec.record("wal.persist", view=self.number, seq=slot.seq)
            if self.in_flight is not None:
                self.in_flight.store_prepares_at(slot.seq)
            slot.commit_sent = replace(commit, assist=True)
            slot.phase = PREPARED
            prev_p, _ = self._sent_history.get(slot.seq, (None, None))
            self._sent_history[slot.seq] = (prev_p, slot.commit_sent)
            self.comm.broadcast_consensus(commit)
            self.logger.infof("Processed prepares for proposal with seq %d", slot.seq)

        return fut, finalize

    # -- phase 3: commits (concurrent verification) -------------------------

    def _maybe_flush_verify(self, slot: _Slot) -> None:
        """Quorum-feasibility flush (View._process_commits policy), but as
        an independent task per slot: k slots' waves sit in the coalescer
        concurrently and merge into one device launch."""
        if slot.phase != PREPARED:
            return
        # drain newly registered votes into the slot's pending pool
        # (incremental bitmask sweep — integer ops on the hot path)
        vs = slot.commits
        new = vs.mask & ~slot.commits_taken_mask
        if new:
            slot.commits_taken_mask |= new
            for idx in iter_bits(new):
                commit: Commit = vs.payloads[idx]
                if commit.digest != slot.digest:
                    self.logger.warnf("Got wrong digest at processCommits for seq %d", commit.seq)
                    continue
                if slot.seen_mask >> idx & 1:
                    continue
                slot.pending_sigs.append(commit.signature)
        if slot.verify_inflight or not slot.pending_sigs:
            return
        # quorum-feasibility flush policy (View._process_commits): launch
        # only when the batch could complete the quorum
        if len(slot.valid_sigs) + len(slot.pending_sigs) < self.quorum - 1:
            return
        pending, slot.pending_sigs = slot.pending_sigs, []
        slot.verify_inflight = True
        proposal = slot.proposal
        seq = slot.seq

        async def run():
            try:
                results = await verify_sigs_batch(
                    self.verifier, pending, proposal, self.logger
                )
            except Exception as e:
                results = e
            if not self._aborted:
                self._verify_results.append((seq, pending, results))
                self._work.set()

        t = create_logged_task(
            run(), name=f"wview-verify-{self.self_id}-{seq}", logger=self.logger
        )
        self._verify_tasks.add(t)
        t.add_done_callback(self._verify_tasks.discard)

    def _absorb_verify_results(self, seq: int, sigs, results) -> None:
        slot = self.slots.get(seq)
        if slot is None:
            return
        slot.verify_inflight = False
        if isinstance(results, Exception):
            slot.verify_failures += 1
            plane_down = isinstance(results, VerifyPlaneDown)
            self.logger.warnf(
                "Batched commit verification failed for seq %d (attempt %d): %r",
                seq, slot.verify_failures, results,
            )
            if plane_down or slot.verify_failures >= 3:
                # VerifyPlaneDown means the coalescer already exhausted its
                # deadline+retry budget AND the host fallback — escalate at
                # once; other engine failures get a few view-level retries
                # first.  Either way: sync instead of killing the view task.
                self.logger.errorf(
                    "Verify plane %s at seq %d; aborting view and syncing",
                    "down (retries + host fallback exhausted)" if plane_down
                    else "failing persistently", seq,
                )
                self._stop()
                self.synchronizer.sync()
                return
            # the engine call failed (not the signatures): re-pool the
            # candidates for a retry on the next flush attempt
            index = self._signer_index
            slot.pending_sigs.extend(
                s for s in sigs
                if index.index_of(s.signer) < 0
                or not (slot.seen_mask >> index.index_of(s.signer) & 1)
            )
            return
        slot.verify_failures = 0
        index = self._signer_index
        for sig, aux in zip(sigs, results):
            if aux is None:
                self.logger.warnf("Couldn't verify %d's signature", sig.signer)
                continue
            idx = index.index_of(sig.signer)
            if idx < 0:
                continue  # not a member (cannot complete any quorum)
            bit = 1 << idx
            if slot.seen_mask & bit:
                continue
            # cap at exactly quorum-1 (certificate-size determinism; see
            # View._process_commits)
            if len(slot.valid_sigs) >= self.quorum - 1:
                break
            slot.seen_mask |= bit
            slot.valid_sigs.append(sig)
        if slot.valid_sigs and len(slot.valid_sigs) >= self.quorum - 1 and slot.phase == PREPARED:
            slot.phase = READY
            rec = self.recorder
            if rec.enabled:
                rec.record(
                    "quorum.commit", view=self.number, seq=seq,
                    extra={"slowest_voter": slot.valid_sigs[-1].signer},
                )
            self.logger.infof(
                "%d collected %d commits for seq %d from %s",
                self.self_id, len(slot.valid_sigs), seq,
                sorted(s.signer for s in slot.valid_sigs),
            )

    # -- delivery -----------------------------------------------------------

    async def _deliver(self, slot: _Slot) -> None:
        """In-order decide rendezvous with the Controller (view.go:851-858)."""
        self.logger.infof("Deciding on seq %d", slot.seq)
        if self.metrics:
            self.metrics.count_batch_all.add(1)
            self.metrics.count_txs_all.add(len(slot.requests))
            self.metrics.latency_batch_processing.observe(time.monotonic() - slot.begin)
        signatures = list(slot.valid_sigs) + [slot.my_sig]
        self.my_proposal_sig = slot.my_sig
        del self.slots[slot.seq]
        self.proposal_sequence = slot.seq + 1
        self.decisions_in_view += 1
        if self.metrics:
            self.metrics.proposal_sequence.set(self.proposal_sequence)
            self.metrics.decisions_in_view.set(self.decisions_in_view)
        self.view_sequences.store(
            ViewSequence(view_active=True, proposal_seq=self.proposal_sequence)
        )
        if self.in_flight is not None:
            self.in_flight.clear_below(self.proposal_sequence)
        # prune assist history beyond the window's trailing edge: a correct
        # replica can lag by up to the window depth, so keep a full window
        # of delivered sequences servable
        floor = slot.seq - self.window
        for s in [s for s in self._sent_history if s < floor]:
            del self._sent_history[s]
        if self._drain_pending and not self.slots:
            # WAL drain complete: the window is empty, so the next proposal
            # is frontier-aligned and its ProposedRecord truncates
            self._drain_pending = False
            self.logger.infof(
                "WindowedView %d: window drained at seq %d, proposing resumes "
                "with a truncating append", self.number, slot.seq,
            )
        # Race the decide rendezvous against abort: the controller resolves
        # the decision future from the SAME loop that processes abort events,
        # so a view parked here while an abort is dequeued ahead of its
        # decision would deadlock controller._abort_view (await view.abort()
        # -> await task -> parked here forever).  On abort the decision stays
        # queued — it is committed, and the controller loop (or its shutdown
        # drain) completes the rendezvous after the abort finishes.
        decide = create_logged_task(
            self.decider.decide(slot.proposal, signatures, slot.requests),
            name=f"wview-decide-{self.self_id}-{slot.seq}", logger=self.logger,
        )
        if self._abort_wait_task is None or self._abort_wait_task.done():
            self._abort_wait_task = create_logged_task(
                self._abort_event.wait(),
                name=f"wview-abortwait-{self.self_id}", logger=self.logger,
            )
        await asyncio.wait(
            {decide, self._abort_wait_task}, return_when=asyncio.FIRST_COMPLETED
        )
        if not decide.done():
            # abandoned rendezvous: create_logged_task's observer retrieves
            # (and loudly logs) any eventual failure of the orphaned decide
            raise ViewAborted()
        decide.result()  # propagate decide failures like the plain await did
        if self._aborted:
            raise ViewAborted()

    # ------------------------------------------------------------------ misc

    def _write_state(self, msg, truncate: bool):
        """Write a SavedMessage now; return its durability future (None when
        the write was synchronously durable — blocking WAL or test double)."""
        if truncate:
            self._saves_since_truncate = 0
        else:
            self._saves_since_truncate += 1
            if (
                self._saves_since_truncate >= self._drain_after
                and not self._drain_pending
            ):
                # bound WAL segment growth under saturation: stop admitting
                # proposals until the window drains, so the next proposal
                # lands frontier-aligned with the truncate mark
                self._drain_pending = True
                self.logger.infof(
                    "WindowedView %d: %d saves since last WAL truncation, "
                    "draining the window for a truncating append",
                    self.number, self._saves_since_truncate,
                )
        save_nowait = getattr(self.state, "save_nowait", None)
        if save_nowait is not None:
            return save_nowait(msg, truncate=truncate)
        self.state.save(msg, truncate=truncate)
        return None

    def _handle_prev_seq_message(self, msg_seq: int, sender: int, m: Message) -> None:
        """Lagging-replica assists over the window's trailing edge
        (view.go:718-756)."""
        if isinstance(m, PrePrepare):
            return
        hist = self._sent_history.get(msg_seq)
        if hist is None:
            return
        prev_prepare, prev_commit = hist
        if isinstance(m, Prepare) and not m.assist and prev_prepare is not None:
            self.comm.send_consensus(sender, prev_prepare)
        elif isinstance(m, Commit) and not m.assist and prev_commit is not None:
            self.comm.send_consensus(sender, prev_commit)

    def _discover_if_sync_needed(self, sender: int, m: Message) -> None:
        """f+1 matching future commit votes trigger a sync (view.go:758-818)."""
        if not isinstance(m, Commit):
            return
        _, f = compute_quorum(self.n)
        threshold = f + 1
        self._last_voted_proposal_by_id[sender] = m
        if len(self._last_voted_proposal_by_id) < threshold:
            return
        counts: dict[_ProposalInfo, int] = {}
        for vote in self._last_voted_proposal_by_id.values():
            info = _ProposalInfo(digest=vote.digest, view=vote.view, seq=vote.seq)
            counts[info] = counts.get(info, 0) + 1
        for info, count in counts.items():
            if count < threshold:
                continue
            if info.view < self.number:
                continue
            if info.seq < self.proposal_sequence + 3 * self.window and info.view == self.number:
                continue  # inside the intake span: not fell-behind evidence
            self.logger.warnf(
                "Seen %d votes for digest %s in view %d, sequence %d but I am in view %d and seq %d",
                count, info.digest, info.view, info.seq, self.number, self.proposal_sequence,
            )
            self._stop()
            self.synchronizer.sync()
            return

    # ------------------------------------------------------------------ restore

    def restore_window(self, records: list) -> None:
        """Rebuild the window from the WAL suffix after a crash.

        ``records`` are the parsed SavedMessages in append order.  The
        in-order save invariants make the suffix unambiguous: ProposedRecord
        seqs ascend, CommitRecord seqs ascend, and C(s) always follows P(s).
        Slots below ``proposal_sequence`` (the delivered frontier per the
        checkpoint) are skipped; restored slots re-enter PROPOSED/PREPARED
        and their prepare/commit are re-broadcast on start
        (state.go:155-247 generalized)."""
        low = self.proposal_sequence
        # Adopt the HIGHEST view present in the records, mirroring the
        # single-slot recovery (state.py _recover_proposed sets
        # view.number = pp.view): a view change's NewViewRecord may have
        # been truncated away by the new view's first proposal, leaving the
        # constructed view number one behind the records.  Filtering those
        # records out instead would forget broadcast commits — a fork risk
        # (the node's ViewData would under-report its in-flight ladder).
        record_views = [
            rec.pre_prepare.view
            for rec in records
            if isinstance(rec, ProposedRecord) and rec.pre_prepare is not None
        ]
        if record_views and max(record_views) > self.number:
            self.logger.infof(
                "WAL records are from view %d, adopting it (constructed with %d)",
                max(record_views), self.number,
            )
            self.number = max(record_views)
        by_seq: dict[int, dict] = {}
        for rec in records:
            if isinstance(rec, ProposedRecord) and rec.pre_prepare is not None:
                if rec.pre_prepare.view != self.number:
                    continue  # superseded by a later view's records
                by_seq.setdefault(rec.pre_prepare.seq, {})["P"] = rec
            elif isinstance(rec, CommitRecord) and rec.commit is not None:
                if rec.commit.view != self.number:
                    continue
                entry = by_seq.get(rec.commit.seq)
                if entry is None:
                    raise ValueError(
                        f"WAL holds a commit for seq {rec.commit.seq} without "
                        "a matching pre-prepare"
                    )
                entry["C"] = rec
        restored = 0
        for seq in sorted(by_seq):
            if seq < low:
                continue
            if seq != self._prepare_frontier + 1:
                break  # a gap: later records belong to an older window shape
            entry = by_seq[seq]
            pp: PrePrepare = entry["P"].pre_prepare
            slot = self.slots[seq] = _Slot(seq=seq, index=self._signer_index)
            slot.pre_prepare = pp
            slot.proposal = pp.proposal
            slot.digest = proposal_digest(pp.proposal)
            slot.begin = time.monotonic()
            slot.prepare_sent = replace(entry["P"].prepare, assist=True)
            slot.phase = PROPOSED
            self._prepare_frontier = seq
            self._sent_history[seq] = (slot.prepare_sent, None)
            self._restored_broadcasts.append(entry["P"].prepare)
            if self.in_flight is not None:
                self.in_flight.store_proposal_at(seq, pp.proposal)
            crec = entry.get("C")
            if crec is not None and seq == self._commit_frontier + 1:
                commit: Commit = crec.commit
                sig = commit.signature
                slot.my_sig = Signature(signer=sig.signer, value=sig.value, msg=sig.msg)
                slot.commit_sent = replace(commit, assist=True)
                slot.phase = PREPARED
                self._commit_frontier = seq
                self._sent_history[seq] = (slot.prepare_sent, slot.commit_sent)
                self._restored_broadcasts.append(commit)
                if self.in_flight is not None:
                    self.in_flight.store_prepares_at(seq)
            restored += 1
        self._next_propose_seq = max(self._next_propose_seq, self._prepare_frontier + 1)
        self.phase = self._lowest_phase()
        if restored and self.rotation:
            # the staging AND proposing frontiers resume mid-window: later
            # slots must restate the blacklist of the last restored
            # (already-verified) proposal, not the checkpoint's possibly
            # older one — a restored LEADER stamps _proposing_blacklist
            # into its next mid-window metadata, so both must advance
            last_slot = self.slots[self._prepare_frontier]
            if last_slot.proposal is not None and last_slot.proposal.metadata:
                self._staged_blacklist = list(
                    decode(ViewMetadata, last_slot.proposal.metadata).black_list
                )
                self._proposing_blacklist = list(self._staged_blacklist)
        if restored:
            self.logger.infof(
                "Restored %d pipelined slot(s), window %d..%d",
                restored, low, self._prepare_frontier,
            )
