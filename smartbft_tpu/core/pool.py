"""Bounded FIFO request pool with a three-stage timeout chain.

Re-design of /root/reference/internal/bft/requestpool.go:52-567.  The
reference uses a linked list + existence map + weighted semaphore + one
``time.AfterFunc`` goroutine per request; here the FIFO and existence map
collapse into one ordered dict, the semaphore into a waiter queue of
futures, and the per-request timers into a lazy timer wheel (per-stage
FIFO deques + ONE armed timer on the shared tick-driven
:class:`~smartbft_tpu.utils.clock.Scheduler`) so tests are deterministic
and the commit path pays no schedule/cancel pair for timers that never
fire — which at open-loop rates is nearly all of them.

Timeout chain per request (requestpool.go:493-567):
  forward timeout  -> on_request_timeout  (forward request to leader)
  complain timeout -> on_leader_fwd_request_timeout (complain -> view change)
  auto-remove      -> on_auto_remove_timeout (drop the request)
"""

from __future__ import annotations

import abc
import asyncio
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Optional

from ..api import Logger, RequestInspector
from ..metrics import RequestPoolMetrics
from ..types import RequestInfo
from ..utils.clock import Scheduler, TaskHandle

# dedup memory of recently deleted requests (requestpool.go:26)
DEFAULT_SIZE_OF_DEL_ELEMENTS = 1000


class PoolError(Exception):
    pass


class ReqAlreadyExistsError(PoolError):
    pass


class ReqAlreadyProcessedError(PoolError):
    pass


class RequestTooBigError(PoolError):
    pass


class SubmitTimeoutError(PoolError):
    pass


class AdmissionRejected(PoolError):
    """Fast-fail shed at the admission gate: pool occupancy (pooled
    requests + already-parked submitters) is past the configured
    high-water mark, so this submit is REFUSED immediately instead of
    parking behind a queue that is already past its knee.

    ``retry_after`` is the hint (seconds, same clock as the pool's
    scheduler) derived from the measured drain rate: roughly how long the
    pool needs to drain back below the high-water mark.  A client that
    retries sooner will very likely be shed again; one that waits it out
    arrives when capacity plausibly exists.  ``occupancy`` snapshots the
    gate's inputs at rejection time."""

    def __init__(self, message: str, *, retry_after: float = 0.0,
                 occupancy: Optional[dict] = None):
        super().__init__(message)
        self.retry_after = retry_after
        self.occupancy = occupancy or {}


class PoolClosedError(PoolError):
    pass


class RequestTimeoutHandler(abc.ABC):
    """Implemented by the Controller (requestpool.go:38-47)."""

    @abc.abstractmethod
    def on_request_timeout(self, request: bytes, info: RequestInfo) -> None: ...

    @abc.abstractmethod
    def on_leader_fwd_request_timeout(self, request: bytes, info: RequestInfo) -> None: ...

    @abc.abstractmethod
    def on_auto_remove_timeout(self, info: RequestInfo) -> None: ...


@dataclass
class PoolOptions:
    queue_size: int = 200
    forward_timeout: float = 10.0
    complain_timeout: float = 10.0
    auto_remove_timeout: float = 10.0
    request_max_bytes: int = 100 * 1024
    #: TOTAL wall/logical seconds one submit may spend parked on space —
    #: a single bound across every re-park, not per-wait (a pre-overload
    #: bug let each wakeup re-arm a fresh timeout, so a submitter could
    #: park forever under sustained contention)
    submit_timeout: float = 10.0
    #: admission gate: fraction of queue_size at which submit stops
    #: queueing and fails fast with AdmissionRejected.  The gate input is
    #: pooled requests PLUS already-parked submitters (queueing theory's
    #: "system size", not just the buffer).  >= 1.0 disables shedding —
    #: the pre-overload parking semantics.
    admission_high_water: float = 1.0
    #: optional live forward-timeout provider (RTT derivation, ISSUE 14
    #: satellite): when set, every forward timer arms with
    #: ``clamp(fn(), FORWARD_TIMEOUT_FLOOR, forward_timeout)`` — the
    #: configured constant stays the ceiling AND the fallback (fn
    #: returning None / raising).  Round 16 measured follower-submitted
    #: requests spending 97.6% of their latency waiting out the fixed
    #: constant; on a measured-µs-RTT link the timer collapses to the
    #: floor instead.
    forward_timeout_fn: Optional[Callable[[], Optional[float]]] = None
    #: flip-time backlog drain (ISSUE 15): how many of the OLDEST pooled
    #: requests a view-flip timer restart fast-forwards (their forward
    #: timers arm at FORWARD_TIMEOUT_FLOOR so followers push the stalled
    #: backlog to the new leader within a tick instead of waiting out a
    #: full forward timeout each).  Derived by the consensus facade as
    #: flip_drain_windows * pipeline_depth * request_batch_max_count —
    #: enough to fill the new view's deep windows immediately.  0
    #: disables (every restart uses the ordinary timeout).
    flip_drain_limit: int = 0


#: hard lower bound of a derived forward timeout: forwarding is benign
#: (leader pool dedup absorbs duplicates) but a near-zero timer would
#: fire before the submit path even returns
FORWARD_TIMEOUT_FLOOR = 0.01


# timer-wheel stages: which leg of the timeout chain an item's armed
# queue entry belongs to (see Pool._wheel_fire)
_STAGE_IDLE = -1
_STAGE_FWD = 0
_STAGE_COMPLAIN = 1
_STAGE_AUTOREMOVE = 2
_STAGE_FLIP = 3


class _Item:
    __slots__ = ("request", "addition_time", "deadline", "stage", "gen")

    def __init__(self, request: bytes, addition_time: float):
        self.request = request
        self.addition_time = addition_time
        self.deadline = 0.0
        self.stage = _STAGE_IDLE
        self.gen = 0


def remove_delivered_requests(pool, infos, logger) -> None:
    """Bulk-remove a delivered batch from ``pool``, loudly on failure.

    The shared post-delivery idiom (Controller._decide and both ViewChanger
    delivery paths): a not-pooled request is routine on followers and only
    counted, but an unexpected exception means corrupted pool state and
    must warn — the reference logs removal failures too
    (controller.go:258-263, viewchanger.go:1178-1182)."""
    infos = list(infos)
    try:
        not_pooled = pool.remove_requests(infos)
    except Exception as e:
        logger.warnf(
            "Removing delivered requests from the pool failed unexpectedly: %r", e
        )
        return
    if not_pooled:
        logger.debugf(
            "%d of %d delivered requests were not in the pool", not_pooled, len(infos)
        )


class Pool:
    """The request pool.  Owned by the consensus event loop; ``submit`` is
    async (it may wait for space), everything else is synchronous."""

    def __init__(
        self,
        logger: Logger,
        inspector: RequestInspector,
        timeout_handler: RequestTimeoutHandler,
        options: PoolOptions,
        scheduler: Scheduler,
        metrics: Optional[RequestPoolMetrics] = None,
        on_submitted: Optional[Callable[[], None]] = None,
        recorder=None,
    ):
        self._log = logger
        self._inspector = inspector
        self._th = timeout_handler
        self._opts = options
        self._scheduler = scheduler
        self._metrics = metrics
        self._on_submitted = on_submitted or (lambda: None)
        # flight recorder (obs.TraceRecorder; nop singleton when tracing
        # is off — submit's sites guard on .enabled, one attr read each)
        from ..obs.recorder import NOP_RECORDER

        self._recorder = recorder if recorder is not None else NOP_RECORDER

        self._items: "OrderedDict[RequestInfo, _Item]" = OrderedDict()
        # lazy timer wheel state: one FIFO deque of (deadline, info, gen)
        # per chain stage, and a single armed scheduler timer at the
        # earliest deadline.  See the "timers" section below.
        self._timer_qs: tuple = (deque(), deque(), deque(), deque())
        self._wheel_handle: Optional[TaskHandle] = None
        self._wheel_deadline = float("inf")
        self._gen = 0  # pool-wide monotonic arm counter (stale detection)
        self._size_bytes = 0
        self._closed = False
        self._stopped = False
        # proposed-but-undelivered reservations (pipelined leader only; no
        # reference counterpart).  The single-slot leader re-batches only
        # after delivery REMOVED the previous batch, so the FIFO front is
        # always fresh; a windowed leader batches again while k proposals
        # are still in flight, and without this set it would re-slice the
        # SAME front into every window slot — duplicate delivery of every
        # request up to the window depth.  next_requests skips reserved
        # items; delivery removal clears them; a view change releases them
        # (an uncommitted in-flight batch must become proposable again).
        self._in_flight: set[RequestInfo] = set()
        # recently-deleted dedup: one insertion-ordered dict doubles as
        # membership set and eviction queue (requestpool.go:418-437 keeps a
        # map + slice pair; popping oldest entries from one dict halves the
        # per-removal hash traffic on the n=64 bulk-removal hot path)
        self._del_map: "OrderedDict[RequestInfo, None]" = OrderedDict()
        self._space_waiters: "deque[asyncio.Future]" = deque()
        # slots promised to woken-but-not-yet-resumed waiters: counted as
        # occupied by every capacity check so a fresh submitter cannot
        # barge into a slot during the one-loop-hop wake window
        self._reserved_slots = 0
        # overload accounting: sheds by cause + a drain-rate estimate for
        # the AdmissionRejected retry-after hint (see _note_drained)
        self.shed_admission = 0
        self.shed_timeout = 0
        #: requests fast-forwarded by flip-time timer restarts (ISSUE 15)
        self.flip_drains = 0
        self._drain_anchor = scheduler.now()
        self._drain_accum = 0
        self._drain_rate = 0.0  # requests/sec, EWMA over DRAIN_WINDOW spans
        # admission-side twin of the drain estimate: how fast requests are
        # ARRIVING (admitted submits/sec).  The arrival-driven BatchBuilder
        # reads this to predict whether the in-formation wave can fill
        # before its deadline (README "Arrival-driven proposing").
        self._arrival_anchor = scheduler.now()
        self._arrival_accum = 0
        self._arrival_rate = 0.0  # requests/sec, EWMA over ARRIVAL_WINDOW spans

    # ------------------------------------------------------------------ submit

    def _admission_slots(self) -> Optional[int]:
        """The high-water mark in SLOTS, or None when the gate is off."""
        hw = self._opts.admission_high_water
        if hw >= 1.0:
            return None
        return max(1, int(hw * self._opts.queue_size))

    async def submit(self, request: bytes, *, forwarded: bool = False) -> None:
        """Add a request; dedups against in-pool and recently-deleted.

        Overload contract (requestpool.go:191-284, hardened):

        * **admission gate** — with ``admission_high_water`` < 1, a pool
          whose system size (pooled + parked submitters) is at/past the
          mark sheds THIS submit immediately with :class:`AdmissionRejected`
          (retry-after hint from the drain rate) instead of queueing past
          the knee.  ``forwarded=True`` (a follower's forward landing at
          the leader) BYPASSES the gate: the request already holds a pool
          slot cluster-side and shedding it here would only re-arm the
          follower's complain timer — internal forwards ride the existing
          timeout chain, the gate guards the client-facing door (README
          "Overload behavior");
        * **bounded wait** — below the mark but full, the submitter parks
          for at most ``submit_timeout`` TOTAL (one deadline across every
          re-park), then sheds with :class:`SubmitTimeoutError`; a shed
          submitter's request is in no pool;
        * **FIFO fairness** — parked submitters are woken oldest-first,
          and a fresh submitter never barges past them even when a removal
          just freed a slot (it parks at the tail; a woken waiter that
          loses a race re-parks at the HEAD, keeping its place).
        """
        info = self._inspector.request_id(request)
        rec = self._recorder
        if rec.enabled:
            rec.record("req.submit", key=str(info),
                       extra={"forwarded": forwarded} if forwarded else None)
        if self._closed:
            raise PoolClosedError(f"pool closed, request rejected: {info}")
        if len(request) > self._opts.request_max_bytes:
            if self._metrics:
                self._metrics.count_of_failed_add_requests.with_labels("max_bytes").add(1)
            raise RequestTooBigError(
                f"submitted request ({len(request)}) is bigger than "
                f"request max bytes ({self._opts.request_max_bytes})"
            )
        self._check_dup(info)

        hw = self._admission_slots() if not forwarded else None
        if hw is not None \
                and len(self._items) + self._reserved_slots \
                + len(self._space_waiters) >= hw:
            self.shed_admission += 1
            if self._metrics:
                self._metrics.count_of_failed_add_requests.with_labels("admission").add(1)
            if rec.enabled:
                rec.record("req.shed", key=str(info),
                           extra={"kind": "admission"})
            raise AdmissionRejected(
                f"admission control: pool at "
                f"{len(self._items)}+{len(self._space_waiters)} of "
                f"high-water {hw}/{self._opts.queue_size}, request shed: "
                f"{info}",
                retry_after=self.retry_after_hint(),
                occupancy=self.occupancy(),
            )

        deadline = self._scheduler.now() + self._opts.submit_timeout
        at_head = False
        parked_at: Optional[float] = None
        while len(self._items) + self._reserved_slots >= self._opts.queue_size \
                or (self._space_waiters and not at_head):
            if parked_at is None:
                parked_at = self._scheduler.now()
            remaining = deadline - self._scheduler.now()
            if remaining <= 0:
                self.shed_timeout += 1
                if self._metrics:
                    self._metrics.count_of_failed_add_requests.with_labels("semaphore").add(1)
                if rec.enabled:
                    rec.record("req.shed", key=str(info),
                               dur=self._scheduler.now() - parked_at,
                               extra={"kind": "timeout"})
                raise SubmitTimeoutError(
                    f"timeout submitting to request pool: {info}"
                )
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            if at_head:
                self._space_waiters.appendleft(fut)
            else:
                self._space_waiters.append(fut)
            timer = self._scheduler.schedule(
                remaining,
                lambda: fut.done() or fut.set_exception(
                    SubmitTimeoutError(f"timeout submitting to request pool: {info}")
                ),
            )
            woken_clean = False
            try:
                await fut
                woken_clean = True
            except SubmitTimeoutError:
                self.shed_timeout += 1
                if self._metrics:
                    self._metrics.count_of_failed_add_requests.with_labels("semaphore").add(1)
                if rec.enabled:
                    rec.record("req.shed", key=str(info),
                               dur=self._scheduler.now() - parked_at,
                               extra={"kind": "timeout"})
                raise
            finally:
                timer.cancel()
                if fut in self._space_waiters:
                    self._space_waiters.remove(fut)
                    # this waiter left the queue without consuming a slot;
                    # the new head must not strand on capacity it now owns
                    self._release_space()
                elif fut.done() and not fut.cancelled() \
                        and fut.exception() is None:
                    # woken by _release_space: stop reserving its slot —
                    # filled synchronously below on the clean path
                    self._reserved_slots -= 1
                    if not woken_clean:
                        # woken but exiting abnormally (task cancelled in
                        # the wake window): hand the freed slot to the next
                        # waiter instead of stranding it until a removal
                        self._release_space()
            if self._closed:
                raise PoolClosedError(f"pool closed, request rejected: {info}")
            # space may have been taken by another woken waiter; dedup
            # again and loop — re-parking at the HEAD keeps FIFO order
            at_head = True
            try:
                self._check_dup(info)
            except PoolError:
                # this waiter exits without filling the slot it was woken
                # for — hand it to the next in line, don't strand them
                self._release_space()
                raise

        item = _Item(request, self._scheduler.now())
        self._items[info] = item
        if not self._stopped:
            self._arm(info, item, _STAGE_FWD, self._forward_timeout())
        self._size_bytes += len(request)
        if rec.enabled:
            # dur = time spent parked on space (0 for an immediate add)
            rec.record("req.pool", key=str(info),
                       dur=(self._scheduler.now() - parked_at)
                       if parked_at is not None else 0.0,
                       extra={"size": len(self._items)})
        if self._metrics:
            self._metrics.count_of_requests.set(len(self._items))
        # the fairness rule parks fresh submitters behind existing waiters
        # even when a slot is free; hand any remaining capacity to them now
        self._release_space()
        self._note_arrival()
        self._on_submitted()

    def _check_dup(self, info: RequestInfo) -> None:
        if info in self._items:
            raise ReqAlreadyExistsError(f"request already exists: {info}")
        if info in self._del_map:
            raise ReqAlreadyProcessedError(f"request already processed: {info}")

    # ------------------------------------------------------------------ batch

    def size(self) -> int:
        return len(self._items)

    def size_bytes(self) -> int:
        return self._size_bytes

    def occupancy(self) -> dict:
        """One JSON-able backpressure snapshot — the per-shard building
        block of the sharded front door's combined occupancy surface
        (shard.ShardSet.occupancy sums these across shards).  ``free`` is
        how many submits can land before :meth:`submit` starts waiting;
        ``waiters`` is how many submitters are ALREADY parked on space;
        the ``shed_*`` counters and ``drain_rate`` are the admission
        gate's outputs (README "Overload behavior")."""
        hw = self._admission_slots()
        return {
            "size": len(self._items),
            "bytes": self._size_bytes,
            "capacity": self._opts.queue_size,
            # reserved slots are promised to woken waiters — not free, or
            # this would overstate headroom submit() will refuse to honor
            "free": max(0, self._opts.queue_size - len(self._items)
                        - self._reserved_slots),
            "in_flight": len(self._in_flight),
            # reserved slots belong to woken-but-not-yet-resumed waiters:
            # still counted so the reshard drain (which must wait out every
            # space-waiter) cannot observe a spuriously clean pool
            "waiters": len(self._space_waiters) + self._reserved_slots,
            "high_water": hw if hw is not None else self._opts.queue_size,
            "shed_admission": self.shed_admission,
            "shed_timeout": self.shed_timeout,
            "flip_drains": self.flip_drains,
            "drain_rate": round(self._drain_rate, 3),
            "arrival_rate": round(self.arrival_rate(), 3),
        }

    # -- drain-rate estimate (the retry-after hint's input) ----------------

    #: seconds of scheduler time one drain-rate sample spans; short enough
    #: to track a breaker trip's capacity collapse within a few waves,
    #: long enough that one bulk removal does not read as a steady rate
    DRAIN_WINDOW = 0.5

    def _note_drained(self, n: int) -> None:
        """Fold ``n`` removals into the drain-rate EWMA.  Called on every
        removal path; O(1), two float ops per call outside window edges."""
        if n <= 0:
            return
        self._drain_accum += n
        now = self._scheduler.now()
        dt = now - self._drain_anchor
        if dt >= self.DRAIN_WINDOW:
            inst = self._drain_accum / dt
            self._drain_rate = inst if self._drain_rate <= 0.0 \
                else 0.5 * self._drain_rate + 0.5 * inst
            self._drain_anchor = now
            self._drain_accum = 0

    #: shorter span than DRAIN_WINDOW: the proposer's fill prediction must
    #: track offered-rate swings within a couple of batch intervals, while
    #: the drain estimate only feeds a coarse retry hint
    ARRIVAL_WINDOW = 0.25

    def _note_arrival(self) -> None:
        """Fold one admitted submit into the arrival-rate EWMA (the
        _note_drained idiom pointed at the front door)."""
        self._arrival_accum += 1
        now = self._scheduler.now()
        dt = now - self._arrival_anchor
        if dt >= self.ARRIVAL_WINDOW:
            inst = self._arrival_accum / dt
            self._arrival_rate = inst if self._arrival_rate <= 0.0 \
                else 0.5 * self._arrival_rate + 0.5 * inst
            self._arrival_anchor = now
            self._arrival_accum = 0

    def arrival_rate(self) -> float:
        """Admitted submits/sec.  While submits keep folding window edges
        this is the EWMA; once the live window overruns ARRIVAL_WINDOW
        without a fold (arrivals too sparse to trigger one) the partial
        window IS the freshest truth, so return it directly — otherwise a
        stale busy-era EWMA would keep predicting "the wave will fill,
        keep waiting" long after traffic stopped."""
        now = self._scheduler.now()
        dt = now - self._arrival_anchor
        if dt >= self.ARRIVAL_WINDOW:
            return self._arrival_accum / dt
        return self._arrival_rate

    def available_count(self) -> int:
        """Pooled requests not reserved in-flight — exactly the population
        next_requests' check-mode fast path counts."""
        return len(self._items) - len(self._in_flight)

    def retry_after_hint(self) -> float:
        """Seconds until the pool plausibly drains back below the
        admission high-water mark at the measured drain rate.  With no
        rate measured yet (cold pool, stalled consensus) the hint is the
        submit timeout — the bound a parked caller would have waited."""
        hw = self._admission_slots()
        if hw is None:
            return 0.0
        # the same system-size expression the gate rejects on — a hint
        # computed from a smaller occupancy would invite an early retry
        # that gets shed again
        excess = (len(self._items) + self._reserved_slots
                  + len(self._space_waiters) - hw + 1)
        if excess <= 0:
            return 0.0
        now = self._scheduler.now()
        rate = self._drain_rate
        # fold the (possibly newer) partial window in so the hint reacts
        # to a drain that started after the last window edge
        dt = now - self._drain_anchor
        if dt >= self.DRAIN_WINDOW and self._drain_accum:
            rate = max(rate, self._drain_accum / dt)
        if rate <= 0.0:
            return self._opts.submit_timeout
        return min(max(excess / rate, 0.001), self._opts.auto_remove_timeout)

    def pending_infos(self) -> list[RequestInfo]:
        """Every request still pooled (including in-flight reservations),
        FIFO order.  The live-reshard drain barrier reads this: a moved
        key-range has drained exactly when no pool in the old shard still
        holds one of its clients' requests — committing past the epoch
        flip on the wrong side would double-deliver."""
        return list(self._items.keys())

    def next_requests(
        self, max_count: int, max_size_bytes: int, check: bool
    ) -> tuple[list[bytes], bool]:
        """Slice up to (max_count, max_size_bytes) from the FIFO front,
        skipping in-flight reservations; ``full`` means calling again cannot
        grow the batch (requestpool.go:297-332).  The check-mode fast path
        counts only UNRESERVED items (the bytes bound stays the pool total:
        a reservation-heavy pool may then return a sub-max batch early,
        which the batcher treats like a timeout batch — harmless)."""
        available = len(self._items) - len(self._in_flight)
        if check and available < max_count and self._size_bytes < max_size_bytes:
            return [], False
        batch: list[bytes] = []
        total = 0
        # the scan walks past reserved items at the FIFO front (O(k*batch)
        # set probes per call at full window depth); a skip cursor would
        # save that but must survive out-of-order removals and releases —
        # not worth it while the probe is a dict hit per item
        for info, item in self._items.items():
            if len(batch) >= max_count:
                break
            if info in self._in_flight:
                continue
            req_len = len(item.request)
            if total + req_len > max_size_bytes:
                return batch, True
            batch.append(item.request)
            total += req_len
        full = total >= max_size_bytes or len(batch) == max_count
        return batch, full

    def mark_in_flight(self, infos) -> None:
        """Reserve proposed-but-undelivered requests: the pipelined leader
        calls this after every propose so the next window slot batches
        FRESH requests instead of re-proposing the in-flight front."""
        self._in_flight.update(infos)

    def release_in_flight(self) -> None:
        """Drop every reservation (view change / view abort): proposals
        that did not survive into a commit are proposable again; those that
        did get removed by delivery anyway."""
        self._in_flight.clear()

    def prune(self, predicate: Callable[[bytes], Optional[Exception]]) -> None:
        """Remove requests failing re-verification (requestpool.go:335-354)."""
        snapshot = [(info, item.request) for info, item in self._items.items()]
        pruned = 0
        for info, request in snapshot:
            err = predicate(request)
            if err is None:
                continue
            try:
                self.remove_request(info)
                pruned += 1
                self._log.debugf("Pruned request: %s; predicate error: %s", info, err)
            except PoolError:
                pass
        if pruned:
            self._log.debugf("Pruned %d requests", pruned)

    # ------------------------------------------------------------------ remove

    def remove_requests(self, infos) -> int:
        """Bulk removal of a delivered batch; returns the not-pooled count.

        The hot post-delivery path: every replica removes every request of
        every decision (RequestBatch x n calls per decision cluster-wide),
        and on followers most are misses — per-request PoolError raising
        alone costs real wall time at n=64 x batch=500.  Misses still pass
        through the recently-deleted dedup map, exactly like
        :meth:`remove_request`."""
        missing = 0
        removed = 0
        for info in infos:
            self._in_flight.discard(info)
            item = self._items.pop(info, None)
            if item is None:
                self._move_to_del(info)
                missing += 1
                continue
            removed += 1
            # no timer to cancel: the wheel entry goes stale with the item
            self._size_bytes -= len(item.request)
            self._move_to_del(info)
            if self._metrics:
                try:
                    # a faulty embedder-supplied metrics provider must not
                    # abort the batch mid-way: the remainder would stay
                    # pooled with live forward timers and no waiter wakeup
                    self._metrics.latency_of_requests.observe(
                        self._scheduler.now() - item.addition_time
                    )
                except Exception:
                    pass
        if removed and self._metrics:
            try:
                # same guard as the per-item observe above: removal fully
                # succeeded by now, so a faulty metrics provider must not
                # escape to the controller's catch-all and log a spurious
                # "pool removal failed" warning
                self._metrics.count_of_requests.set(len(self._items))
            except Exception:
                pass
        self._note_drained(removed)
        self._release_space()
        return missing

    def remove_request(self, info: RequestInfo) -> None:
        self._in_flight.discard(info)
        item = self._items.pop(info, None)
        if item is None:
            self._move_to_del(info)
            raise PoolError(f"request {info} is not in the pool at remove time")
        self._size_bytes -= len(item.request)
        self._move_to_del(info)
        if self._metrics:
            try:
                # same guard as remove_requests: removal already succeeded,
                # so a faulty metrics provider must not escape (prune()
                # catches only PoolError around this call)
                self._metrics.count_of_requests.set(len(self._items))
                self._metrics.latency_of_requests.observe(
                    self._scheduler.now() - item.addition_time
                )
            except Exception:
                pass
        self._note_drained(1)
        self._release_space()

    def seed_processed(self, infos) -> None:
        """Pre-arm the dedup memory with ALREADY-COMMITTED request ids
        (snapshot install / reshard handoff, ISSUE 17): a node seeded
        from a donor snapshot never saw those requests delivered, but a
        client resubmitting one must get ReqAlreadyProcessedError, not a
        second delivery.  Bounded by the same eviction as the delivery
        path."""
        for info in infos:
            self._move_to_del(info)

    def _move_to_del(self, info: RequestInfo) -> None:
        if info in self._del_map:
            return
        self._del_map[info] = None
        # bounded dedup memory (requestpool.go:418-437)
        if len(self._del_map) > 2 * DEFAULT_SIZE_OF_DEL_ELEMENTS:
            for _ in range(len(self._del_map) - DEFAULT_SIZE_OF_DEL_ELEMENTS):
                self._del_map.popitem(last=False)

    def _release_space(self) -> None:
        # wake as many parked submitters as there is capacity (the bulk
        # removal path frees hundreds of slots in one call; waking just one
        # would strand the rest until their submit_timeout).  Overwaking is
        # harmless: submit() re-checks capacity in a while loop.
        capacity = (self._opts.queue_size - len(self._items)
                    - self._reserved_slots)
        while self._space_waiters and capacity > 0:
            fut = self._space_waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                self._reserved_slots += 1
                capacity -= 1

    # ------------------------------------------------------------------ timers
    #
    # Lazy timer wheel (round 18).  The reference arms one timer per
    # request per chain stage; at open-loop rates the schedule/cancel
    # pairs for timers that never fire (requests commit long before
    # their forward timeout) were a top profile line of the whole
    # cluster.  Here an armed item carries (deadline, stage, gen) and is
    # appended to a per-stage FIFO deque; ONE scheduler timer is armed
    # at the earliest outstanding deadline.  Removal just drops the item
    # — its queue entry goes stale (item gone, or gen mismatch after a
    # re-arm) and is skipped when the wheel next fires, so the commit
    # path pays a deque append on submit and nothing on removal.
    # Per-stage queues are near-monotone (uniform timeouts mean FIFO
    # order == deadline order); an adaptive forward_timeout_fn can
    # invert entries, which only DELAYS an interior entry until the
    # queue head's deadline — bounded by the derivation swing, harmless
    # for what is a liveness nudge backed by leader-side dedup.

    def _arm(self, info: RequestInfo, item: _Item, stage: int,
             delay: float) -> None:
        self._gen += 1
        item.gen = self._gen
        item.stage = stage
        item.deadline = self._scheduler.now() + delay
        self._timer_qs[stage].append((item.deadline, info, item.gen))
        if item.deadline < self._wheel_deadline:
            self._arm_wheel(item.deadline)

    def _arm_wheel(self, deadline: float) -> None:
        if self._wheel_handle is not None:
            self._wheel_handle.cancel()
        self._wheel_deadline = deadline
        self._wheel_handle = self._scheduler.schedule(
            max(deadline - self._scheduler.now(), 0.0), self._wheel_fire
        )

    def _cancel_wheel(self) -> None:
        if self._wheel_handle is not None:
            self._wheel_handle.cancel()
            self._wheel_handle = None
        self._wheel_deadline = float("inf")

    def _wheel_fire(self) -> None:
        self._wheel_handle = None
        self._wheel_deadline = float("inf")
        now = self._scheduler.now()
        for stage, q in enumerate(self._timer_qs):
            while q:
                deadline, info, gen = q[0]
                item = self._items.get(info)
                if item is None or item.gen != gen:
                    q.popleft()  # stale: removed, or re-armed elsewhere
                    continue
                if deadline > now:
                    break
                q.popleft()
                # a dispatch handler may stop/close the pool mid-fire
                # (complain -> view change); due entries behind it are
                # dropped exactly as stop_timers would have cancelled them
                if self._closed or self._stopped:
                    continue
                self._dispatch(stage, info, item)
        if self._closed or self._stopped:
            return
        # re-arm at the earliest still-armed entry (stale prefixes were
        # drained above; a dispatch may have appended fresh entries)
        nxt = float("inf")
        for q in self._timer_qs:
            while q:
                deadline, info, gen = q[0]
                item = self._items.get(info)
                if item is None or item.gen != gen:
                    q.popleft()
                    continue
                if deadline < nxt:
                    nxt = deadline
                break
        if nxt < float("inf"):
            self._arm_wheel(nxt)

    def _dispatch(self, stage: int, info: RequestInfo, item: _Item) -> None:
        """Fire one chain leg for one item — the re-arm happens BEFORE the
        handler runs, matching the reference's AfterFunc ordering."""
        request = item.request
        if stage == _STAGE_FWD:
            self._arm(info, item, _STAGE_COMPLAIN, self._opts.complain_timeout)
            if self._metrics:
                self._metrics.count_of_leader_forward_requests.add(1)
            self._th.on_request_timeout(request, info)
        elif stage == _STAGE_COMPLAIN:
            self._arm(info, item, _STAGE_AUTOREMOVE,
                      self._opts.auto_remove_timeout)
            if self._metrics:
                self._metrics.count_of_complain_timeout.add(1)
            self._th.on_leader_fwd_request_timeout(request, info)
        elif stage == _STAGE_AUTOREMOVE:
            self._on_auto_remove_to(info)
        else:  # _STAGE_FLIP: the flip-time BONUS forward (round 15).
            # Push the stalled request to the new leader immediately, then
            # re-arm the ORDINARY forward->complain chain behind it on its
            # original schedule.  The early forward is purely additive —
            # if it lands, leader-side dedup absorbs the ordinary forward
            # that follows; if it is lost on the wire or refused by a peer
            # that has not flipped to the new view yet, the unchanged
            # chain retries it instead of stranding it until the complain
            # stage.  An accelerated chain was the first design and
            # livelocked the lossy-network gate both ways: early complains
            # re-triggered view changes, and a dropped one-shot forward
            # stalled the drain.
            remaining = max(
                self._forward_timeout() - FORWARD_TIMEOUT_FLOOR, 0.0
            )
            self._arm(info, item, _STAGE_FWD, remaining)
            self._th.on_request_timeout(request, info)

    def _on_auto_remove_to(self, info: RequestInfo) -> None:
        try:
            self.remove_request(info)
        except PoolError as e:
            self._log.errorf("Removal of request %s failed; error: %s", info, e)
            return
        if self._metrics:
            self._metrics.count_of_deleted_requests.add(1)
        self._th.on_auto_remove_timeout(info)

    # ------------------------------------------------------------------ epochs

    def change_options(self, timeout_handler: RequestTimeoutHandler, options: PoolOptions) -> None:
        """Swap the timeout handler and timeouts across a reconfig
        (requestpool.go:146-180); queue size is kept."""
        options.queue_size = self._opts.queue_size
        self._opts = options
        self._th = timeout_handler
        self._log.debugf("Changed pool timeouts")

    def stop_timers(self) -> None:
        """Freeze all request timers during a view change
        (requestpool.go:456-470)."""
        self._stopped = True
        for q in self._timer_qs:
            q.clear()
        self._cancel_wheel()
        self._log.debugf("Stopped all timers: size=%d", len(self._items))

    def restart_timers(self, *, flip: bool = False) -> None:
        """Restart all request timers as forward timeouts
        (requestpool.go:472-490).

        ``flip=True`` (a completed view change restarting the timers):
        the oldest ``flip_drain_limit`` requests arm at
        FORWARD_TIMEOUT_FLOOR instead — the stalled backlog reaches the
        NEW leader within a tick and its first proposals batch it into
        deep windows, instead of every pooled request waiting out a full
        forward timeout while the new view idles (round 16: propose_wait
        was 98% of forced-VC request time).  Leader-side dedup absorbs
        any duplicate this forwards; requests past the limit keep the
        ordinary chain."""
        self._stopped = False
        for q in self._timer_qs:
            q.clear()  # every item is re-armed fresh below
        self._cancel_wheel()
        fwd = self._forward_timeout()
        fast = self._opts.flip_drain_limit if flip else 0
        for k, (info, item) in enumerate(self._items.items()):
            if k < fast:
                self._arm(info, item, _STAGE_FLIP, FORWARD_TIMEOUT_FLOOR)
            else:
                self._arm(info, item, _STAGE_FWD, fwd)
        if fast and self._items:
            self.flip_drains += min(fast, len(self._items))
        self._log.debugf("Restarted all timers: size=%d", len(self._items))

    def _forward_timeout(self) -> float:
        """The effective forward timeout for the next timer arm: the
        RTT-derived value from ``forward_timeout_fn`` clamped into
        [FORWARD_TIMEOUT_FLOOR, configured constant]; the constant alone
        when no provider is wired, it has no measurement yet, or it
        fails (telemetry must never wedge request timers)."""
        fn = self._opts.forward_timeout_fn
        ceiling = self._opts.forward_timeout
        if fn is None:
            return ceiling
        try:
            derived = fn()
        except Exception:  # noqa: BLE001 — derivation is advisory
            return ceiling
        if derived is None or derived <= 0:
            return ceiling
        return min(max(derived, FORWARD_TIMEOUT_FLOOR), ceiling)

    def close(self) -> None:
        self._closed = True
        self._cancel_wheel()
        for q in self._timer_qs:
            q.clear()
        for info in list(self._items.keys()):
            item = self._items.pop(info)
            self._size_bytes -= len(item.request)
            self._move_to_del(info)
        for fut in self._space_waiters:
            if not fut.done():
                fut.set_exception(PoolClosedError("pool closed"))
        self._space_waiters.clear()
