"""ProposalMaker: builds a fresh View per view-start, restoring from the WAL
exactly once (re-design of /root/reference/internal/bft/util.go:250-331)."""

from __future__ import annotations

from typing import Optional

from ..api import Logger, MembershipNotifier, Signer, Verifier
from ..metrics import BlacklistMetrics, ViewMetrics
from ..types import Checkpoint
from .pipeline import WindowedView
from .view import View, ViewSequence, ViewSequencesHolder


class ProposalMaker:
    def __init__(
        self,
        *,
        decisions_per_leader: int,
        n: int,
        nodes_list: list[int],
        self_id: int,
        decider,
        failure_detector,
        synchronizer,
        logger: Logger,
        comm,
        verifier: Verifier,
        signer: Signer,
        membership_notifier: Optional[MembershipNotifier],
        state,
        in_msg_q_size: int,
        view_sequences: ViewSequencesHolder,
        checkpoint: Checkpoint,
        metrics_view: Optional[ViewMetrics] = None,
        metrics_blacklist: Optional[BlacklistMetrics] = None,
        pipeline_depth: int = 1,
        backpressure: bool = False,
        recorder=None,
    ):
        self.decisions_per_leader = decisions_per_leader
        self.n = n
        self.nodes_list = nodes_list
        self.self_id = self_id
        self.decider = decider
        self.failure_detector = failure_detector
        self.synchronizer = synchronizer
        self.logger = logger
        self.comm = comm
        self.verifier = verifier
        self.signer = signer
        self.membership_notifier = membership_notifier
        self.state = state
        self.in_msg_q_size = in_msg_q_size
        self.view_sequences = view_sequences
        self.checkpoint = checkpoint
        self.metrics_view = metrics_view
        self.metrics_blacklist = metrics_blacklist
        self.pipeline_depth = pipeline_depth
        self.backpressure = backpressure
        self.recorder = recorder
        self._restored_from_wal = False

    def new_proposer(
        self,
        leader: int,
        proposal_sequence: int,
        view_num: int,
        decisions_in_view: int,
        quorum_size: int,
    ) -> tuple[View, int]:
        """util.go:273-329 — returns (view, initial_phase)."""
        if self.pipeline_depth > 1:
            return self._new_windowed_proposer(
                leader, proposal_sequence, view_num, decisions_in_view, quorum_size
            )
        view = View(
            retrieve_checkpoint=self.checkpoint.get,
            decisions_per_leader=self.decisions_per_leader,
            n=self.n,
            nodes_list=self.nodes_list,
            leader_id=leader,
            self_id=self.self_id,
            quorum=quorum_size,
            number=view_num,
            decider=self.decider,
            failure_detector=self.failure_detector,
            synchronizer=self.synchronizer,
            logger=self.logger,
            comm=self.comm,
            verifier=self.verifier,
            signer=self.signer,
            membership_notifier=self.membership_notifier,
            proposal_sequence=proposal_sequence,
            decisions_in_view=decisions_in_view,
            state=self.state,
            in_msg_q_size=self.in_msg_q_size,
            view_sequences=self.view_sequences,
            metrics_view=self.metrics_view,
            metrics_blacklist=self.metrics_blacklist,
            backpressure=self.backpressure,
            recorder=self.recorder,
        )
        self._restore_once_and_publish(view, proposal_sequence)
        if proposal_sequence > view.proposal_sequence:
            view.proposal_sequence = proposal_sequence
            view.decisions_in_view = decisions_in_view
        if view_num > view.number:
            view.number = view_num
            view.decisions_in_view = decisions_in_view
        self._publish_metrics(view)
        return view, view.phase

    def _restore_once_and_publish(self, view, proposal_sequence: int) -> None:
        view.view_sequences.store(
            ViewSequence(view_active=True, proposal_seq=proposal_sequence)
        )
        if not self._restored_from_wal:
            self._restored_from_wal = True
            self.state.restore(view)

    def _publish_metrics(self, view) -> None:
        if self.metrics_view:
            self.metrics_view.view_number.set(view.number)
            self.metrics_view.leader_id.set(view.leader_id)
            self.metrics_view.proposal_sequence.set(view.proposal_sequence)
            self.metrics_view.decisions_in_view.set(view.decisions_in_view)
            self.metrics_view.phase.set(view.phase)

    def _new_windowed_proposer(
        self,
        leader: int,
        proposal_sequence: int,
        view_num: int,
        decisions_in_view: int,
        quorum_size: int,
    ) -> tuple[WindowedView, int]:
        """Pipelined mode: build a WindowedView (pipeline_depth sequences in
        flight, up to 2x that under the launch shadow; with window-granular
        rotation, ``decisions_per_leader`` arrives pre-multiplied by the
        window depth — Configuration.effective_decisions_per_leader).  The
        same restore-exactly-once contract as the single-slot path
        (util.go:305-311).  The decider is the Controller; its
        ``on_window_capacity`` re-arms the leader token when the view's
        launch-shadow gate (or a WAL drain) re-opens propose capacity
        without a delivery — without the seam the leader would idle until
        the next delivery even though the window has room."""
        view = WindowedView(
            retrieve_checkpoint=self.checkpoint.get,
            decisions_per_leader=self.decisions_per_leader,
            membership_notifier=self.membership_notifier,
            metrics_blacklist=self.metrics_blacklist,
            n=self.n,
            nodes_list=self.nodes_list,
            leader_id=leader,
            self_id=self.self_id,
            quorum=quorum_size,
            number=view_num,
            decider=self.decider,
            failure_detector=self.failure_detector,
            synchronizer=self.synchronizer,
            logger=self.logger,
            comm=self.comm,
            verifier=self.verifier,
            signer=self.signer,
            proposal_sequence=proposal_sequence,
            decisions_in_view=decisions_in_view,
            state=self.state,
            view_sequences=self.view_sequences,
            window=self.pipeline_depth,
            in_flight=getattr(self.state, "in_flight", None),
            metrics_view=self.metrics_view,
            capacity_cb=getattr(self.decider, "on_window_capacity", None),
            recorder=self.recorder,
        )
        self._restore_once_and_publish(view, proposal_sequence)
        self._publish_metrics(view)
        return view, view.phase
