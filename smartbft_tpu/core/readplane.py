"""The read/serving plane's pure core (ISSUE 19).

Castro–Liskov's read-only optimization: a read executes at replicas
against COMMITTED state with no ordering — no pool, no proposer, no
verify launch.  What makes that safe is entirely client-side judgement
over the stamps replicas attach, and this module holds that judgement
as pure functions so every embedder (socket control channel, in-process
shard front door, chaos oracle, property tests) applies bit-identical
rules:

* :func:`quorum_read_decide` — the ``f+1`` match rule.  ``f+1``
  bit-identical ``(found, value, height, state_digest)`` stamps contain
  at least one honest replica, and an honest replica only stamps
  committed state — so the value is committed.  Replies that contradict
  the winning stamp are returned as OUTLIERS with a reason: a donor at
  the same height with a different digest/value is provably
  inconsistent with a committed stamp; a donor behind the winner past
  the caller's lag bound served stale state.  Both are observed-only
  evidence (``stale_read``) for the MisbehaviorTable — read replies are
  unsigned, so they must never feed the provable shun score.
* :func:`follower_read_accept` — the single-replica fast path's
  staleness bound.  The client chooses ``max_lag_decisions`` and
  rejects any reply whose anchor (the live height, or the snapshot
  anchor-certificate height for a read-at-base) is older than its known
  frontier by more than the bound.  Freshness is bounded in DECISIONS,
  not wall time: the logical clock owns the tests.
* :class:`TokenBucket` — the per-replica read gate.  Reads bypass the
  write path's admission gate entirely (they must never queue behind
  writes), so they get their own bucket: a read storm drains this
  bucket and sheds READS with a retry-after hint while the write path
  never sees it.

Everything here is synchronous, lock-free and deterministic — callers
own their locking and supply the clock.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence


def read_stamp(reply) -> tuple:
    """The equality key of the ``f+1`` match rule.  Anything exposing
    ``found``/``value``/``height``/``state_digest`` (the wire
    ``ReadResponse``, the in-process reply) stamps identically."""
    return (
        bool(getattr(reply, "found", False)),
        bytes(getattr(reply, "value", b"") or b""),
        int(getattr(reply, "height", 0)),
        bytes(getattr(reply, "state_digest", b"") or b""),
    )


@dataclass(frozen=True)
class QuorumReadResult:
    """Outcome of one quorum-read fan-out: the winning reply (None when
    no stamp reached ``need`` matches), and the contradicting donors as
    ``(sender, reason)`` pairs for observed-only attribution."""

    winner: object = None
    matches: int = 0
    outliers: tuple = ()


def quorum_read_decide(replies: Sequence[tuple[int, object]], need: int,
                       *, max_lag_decisions: int = 0) -> QuorumReadResult:
    """Apply the ``f+1`` match rule to ``(sender, reply)`` pairs.

    ``need`` is how many bit-identical stamps prove commitment (f+1 —
    the caller derives f from its membership).  Shed replies never
    match and are never outliers: a shed is the gate working, not a
    donor lying.  When several stamps reach ``need`` (only possible
    while commits land mid-fan-out), the HIGHEST height wins — every
    qualifying stamp is committed, so freshest is strictly better.
    """
    groups: dict[tuple, list[int]] = {}
    usable: list[tuple[int, object]] = []
    for sender, reply in replies:
        if reply is None or getattr(reply, "shed", False):
            continue
        usable.append((sender, reply))
        groups.setdefault(read_stamp(reply), []).append(sender)
    winners = [(stamp, senders) for stamp, senders in groups.items()
               if len(senders) >= need]
    if not winners:
        return QuorumReadResult(winner=None, matches=0, outliers=())
    win_stamp, win_senders = max(winners, key=lambda sw: sw[0][2])
    winner = next(r for s, r in usable
                  if s in win_senders and read_stamp(r) == win_stamp)
    win_height = win_stamp[2]
    outliers: list[tuple[int, str]] = []
    for sender, reply in usable:
        stamp = read_stamp(reply)
        if stamp == win_stamp:
            continue
        if stamp[2] == win_height:
            # same height, different value/digest: inconsistent with a
            # committed stamp — a tampered or forked read reply
            outliers.append((sender, "digest_mismatch"))
        elif stamp[2] < win_height - max_lag_decisions:
            outliers.append((sender, "stale_beyond_bound"))
        # a reply within the lag bound (or AHEAD of the winner) is an
        # honest replica at a different frontier — never attributed
    return QuorumReadResult(winner=winner, matches=len(win_senders),
                            outliers=tuple(outliers))


def follower_read_accept(reply, frontier_seq: int,
                         max_lag_decisions: int) -> bool:
    """The follower-read staleness rule: accept a single-replica reply
    iff its anchor is no more than ``max_lag_decisions`` behind the
    client's known frontier.  The anchor is the snapshot certificate
    height for a read-at-base, the live height otherwise; a shed reply
    is never accepted.  A reply AHEAD of the client's frontier is
    always fresh (the client's frontier knowledge is the stale side)."""
    if reply is None or getattr(reply, "shed", False):
        return False
    if getattr(reply, "at_base", False):
        anchor = int(getattr(reply, "anchor_height", 0))
    else:
        anchor = int(getattr(reply, "height", 0))
    return frontier_seq - anchor <= max_lag_decisions


def session_retry_after_ms(height: int, min_height: int,
                           commit_gap_s: Optional[float],
                           *, floor_ms: int = 10,
                           cap_ms: int = 5000) -> int:
    """Retry-after hint for a read-your-write miss (ISSUE 20 satellite).

    A follower asked to serve at ``min_height`` (the session token a
    write ack carried) while still at ``height`` estimates when it will
    have caught up: the decision gap times the replica's measured commit
    inter-arrival EWMA (``commit_gap_s``; None/0 when idle — then the
    floor applies, since catch-up may be one wire-sync away).  Clamped
    to ``[floor_ms, cap_ms]`` so a huge gap never tells a client to go
    away for minutes.  Pure — the shed-reply retry-after discipline
    (Pool drain rate, TokenBucket) applied to session reads."""
    gap = max(0, int(min_height) - int(height))
    if gap == 0:
        return 0
    est_s = gap * (commit_gap_s or 0.0)
    return max(floor_ms, min(cap_ms, int(est_s * 1000)))


class TokenBucket:
    """The per-replica read gate: ``rate`` tokens/second refill up to
    ``burst``.  ``allow()`` spends one token or refuses; ``retry_after``
    is the drain-rate-derived hint the shed reply carries (the FT_REJECT
    contract).  The clock is injected so logical-clock tests drive it
    deterministically; rate <= 0 disables the gate (always allow)."""

    __slots__ = ("rate", "burst", "_clock", "_tokens", "_last", "sheds",
                 "allowed")

    def __init__(self, rate: float, burst: int,
                 clock: Optional[Callable[[], float]] = None):
        import time

        self.rate = float(rate)
        self.burst = max(1, int(burst))
        self._clock = clock if clock is not None else time.monotonic
        self._tokens = float(self.burst)
        self._last = self._clock()
        self.sheds = 0
        self.allowed = 0

    def _refill(self) -> None:
        now = self._clock()
        elapsed = now - self._last
        if elapsed > 0:
            self._tokens = min(self.burst, self._tokens + elapsed * self.rate)
            self._last = now

    def allow(self) -> bool:
        if self.rate <= 0:
            self.allowed += 1
            return True
        self._refill()
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            self.allowed += 1
            return True
        self.sheds += 1
        return False

    def retry_after(self) -> float:
        """Seconds until one token exists (0 when a token is available
        or the gate is disabled)."""
        if self.rate <= 0:
            return 0.0
        self._refill()
        deficit = 1.0 - self._tokens
        return max(0.0, deficit / self.rate)

    def occupancy(self) -> tuple[int, int]:
        """(tokens spent of the burst window, burst) — the shed reply's
        occupancy/high_water snapshot, mirroring the pool gate's."""
        self._refill()
        return self.burst - int(self._tokens), self.burst


@dataclass
class ReadStats:
    """Serving-side read-plane counters, embedded per replica and
    exported as the ``read`` stats block (control cmd=stats, ShardSet
    stats_block, the bench ``read`` row's per-replica half)."""

    served_live: int = 0
    served_base: int = 0
    not_found: int = 0
    sheds: int = 0
    base_refused: int = 0
    watch_notifications: int = 0
    watch_dropped: int = 0
    #: lag (serving height minus reply anchor) observed per served read;
    #: live reads serve at the frontier so this meters the at_base path
    lag_sum: int = 0
    lag_max: int = 0
    served_total: int = field(init=False, default=0)

    def note_served(self, *, at_base: bool, found: bool, lag: int = 0) -> None:
        self.served_total += 1
        if at_base:
            self.served_base += 1
        else:
            self.served_live += 1
        if not found:
            self.not_found += 1
        if lag > 0:
            self.lag_sum += lag
            if lag > self.lag_max:
                self.lag_max = lag

    def snapshot(self) -> dict:
        served = self.served_total
        return {
            "served": served,
            "served_live": self.served_live,
            "served_base": self.served_base,
            "not_found": self.not_found,
            "sheds": self.sheds,
            "base_refused": self.base_refused,
            "watch_notifications": self.watch_notifications,
            "watch_dropped": self.watch_dropped,
            "lag_mean": round(self.lag_sum / served, 3) if served else 0.0,
            "lag_max": self.lag_max,
        }
