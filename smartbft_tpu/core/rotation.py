"""Shared leader-rotation machinery: blacklist metadata + chain verification.

Extracted from the single-slot View (view.go:553-716,896-1062 re-design) so
the pipelined WindowedView can run the SAME deterministic blacklist update
and prev-commit-certificate verification at window boundaries that the
single-slot path runs per decision.  Both views hold one
:class:`RotationState` per view instance; the state is pure protocol logic
plus the f+1-aux-witness "blacklisting supported" latch (view.go:1064-1088).

One deliberate robustness divergence: commit signatures minted by the
view-change in-flight commit machinery carry EMPTY auxiliary data (the
special PREPARED view signs with no prepare witnesses,
viewchanger.go:1186-1306).  The reference's blacklist update would choke
decoding PreparesFrom from them; here :func:`decode_prepares_from` maps
empty/undecodable aux to an empty witness list — deterministically, on
leader and follower alike, so metadata byte-equality is preserved.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Optional

from ..api import Logger, MembershipNotifier, Verifier
from ..codec import decode
from ..messages import PreparesFrom, Signature, ViewMetadata
from ..metrics import BlacklistMetrics
from ..types import commit_signatures_digest
from .util import compute_blacklist_update, compute_quorum


def decode_prepares_from(aux: bytes) -> PreparesFrom:
    """Tolerant PreparesFrom decode: empty/undecodable aux (in-flight-view
    certificates) counts as zero witnesses instead of crashing the
    deterministic blacklist recomputation."""
    if not aux:
        return PreparesFrom(ids=[])
    try:
        return decode(PreparesFrom, aux)
    except Exception:
        return PreparesFrom(ids=[])


class RotationState:
    """Rotation-mode proposal metadata construction (leader) and
    re-verification (follower) for one view instance."""

    def __init__(
        self,
        *,
        self_id: int,
        n: int,
        nodes_list: list[int],
        leader_id: int,
        get_view_number,
        decisions_per_leader: int,
        verifier: Verifier,
        retrieve_checkpoint,
        membership_notifier: Optional[MembershipNotifier],
        logger: Logger,
        metrics_blacklist: Optional[BlacklistMetrics] = None,
    ):
        self.self_id = self_id
        self.n = n
        self.nodes_list = nodes_list
        self.leader_id = leader_id
        #: callable, NOT a frozen int: WAL restore can raise the owning
        #: view's number after construction (state.py _recover_*, pipeline
        #: restore_window adopt the records' view), and the deterministic
        #: blacklist recomputation must use the LIVE number or a restored
        #: follower diverges from the leader's metadata.view_id
        self.get_view_number = get_view_number
        self.decisions_per_leader = decisions_per_leader
        self.verifier = verifier
        self.retrieve_checkpoint = retrieve_checkpoint
        self.membership_notifier = membership_notifier
        self.logger = logger
        self.metrics_blacklist = metrics_blacklist
        self._blacklist_supported = False

    # ------------------------------------------------------------------ follower

    async def verify_prev_commit_signatures(
        self, prev_commit_signatures: list[Signature], curr_verification_seq: int
    ) -> Optional[dict[int, PreparesFrom]]:
        """view.go:609-647 — batched (one quorum-sized batch)."""
        from .view import verify_sigs_batch  # local import: avoid cycle

        prev_prop_raw, _ = self.retrieve_checkpoint()
        if prev_prop_raw.verification_sequence != curr_verification_seq:
            self.logger.infof(
                "Skipping verifying prev commit signatures due to verification "
                "sequence advancing from %d to %d",
                prev_prop_raw.verification_sequence, curr_verification_seq,
            )
            return None

        if not prev_commit_signatures:
            return {}

        results = await verify_sigs_batch(
            self.verifier, prev_commit_signatures, prev_prop_raw, self.logger
        )
        prepare_acks: dict[int, PreparesFrom] = {}
        for sig, aux in zip(prev_commit_signatures, results):
            if aux is None:
                raise ValueError(f"failed verifying consenter signature of {sig.signer}")
            prepare_acks[sig.signer] = decode_prepares_from(aux)
        return prepare_acks

    def verify_blacklist(
        self,
        prev_commit_signatures: list[Signature],
        curr_verification_seq: int,
        pending_blacklist: list[int],
        prepare_acks: Optional[dict[int, PreparesFrom]],
    ) -> None:
        """view.go:649-716 — recompute the deterministic blacklist update and
        require equality with the leader's."""
        if self.decisions_per_leader == 0:
            if pending_blacklist:
                raise ValueError(
                    f"rotation is inactive but blacklist is not empty: {pending_blacklist}"
                )
            return

        prev_prop_raw, my_last_commit_sigs = self.retrieve_checkpoint()
        prev_md = (
            decode(ViewMetadata, prev_prop_raw.metadata)
            if prev_prop_raw.metadata
            else ViewMetadata()
        )

        if prev_prop_raw.verification_sequence != curr_verification_seq:
            if list(prev_md.black_list) != pending_blacklist:
                raise ValueError(
                    f"blacklist changed ({prev_md.black_list} --> {pending_blacklist}) "
                    "during reconfiguration"
                )
            self.logger.infof(
                "Skipping verifying prev commits due to verification sequence advancing"
            )
            return

        if self.membership_notifier is not None and self.membership_notifier.membership_change():
            if list(prev_md.black_list) != pending_blacklist:
                raise ValueError(
                    f"blacklist changed ({prev_md.black_list} --> {pending_blacklist}) "
                    "during membership change"
                )
            self.logger.infof("Skipping verifying prev commits due to membership change")
            return

        _, f = compute_quorum(self.n)

        if self.blacklisting_supported(f, my_last_commit_sigs) and len(
            prev_commit_signatures
        ) < len(my_last_commit_sigs):
            raise ValueError(
                f"only {len(prev_commit_signatures)} out of {len(my_last_commit_sigs)} "
                "required previous commits is included in pre-prepare"
            )

        expected = compute_blacklist_update(
            current_leader=self.leader_id,
            leader_rotation=self.decisions_per_leader > 0,
            prev_md=prev_md,
            n=self.n,
            nodes=self.nodes_list,
            curr_view=self.get_view_number(),
            prepares_from=prepare_acks or {},
            f=f,
            decisions_per_leader=self.decisions_per_leader,
            logger=self.logger,
            metrics=self.metrics_blacklist,
        )
        if pending_blacklist != expected:
            raise ValueError(
                f"proposed blacklist {pending_blacklist} differs from expected "
                f"{expected} blacklist"
            )

    def verify_prev_commit_digest(
        self, prev_commit_signatures: list[Signature], md: ViewMetadata
    ) -> None:
        """view.go:694-698 — the metadata must bind the carried certificate."""
        prev_commit_digest = commit_signatures_digest(prev_commit_signatures)
        if prev_commit_digest != md.prev_commit_signature_digest and self.decisions_per_leader > 0:
            raise ValueError(
                "prev commit signatures received from leader mismatches the metadata digest"
            )

    def blacklisting_supported(self, f: int, my_last_commit_sigs: list[Signature]) -> bool:
        """view.go:1064-1088 — f+1 witnesses of aux data activate blacklisting."""
        if self._blacklist_supported:
            return True
        count = 0
        for sig in my_last_commit_sigs:
            aux = self.verifier.auxiliary_data(sig.msg)
            if aux:
                count += 1
        supported = count > f
        self._blacklist_supported = self._blacklist_supported or supported
        return supported

    # ------------------------------------------------------------------ leader

    def build_leader_metadata(self, metadata: ViewMetadata) -> ViewMetadata:
        """The full rotation-leader metadata flow (view.go:896-948): seed
        the previous blacklist from the checkpoint, apply the deterministic
        update, bind the certificate digest.  Shared by the single-slot
        View (every decision) and the WindowedView (window-first only)."""
        verification_seq = self.verifier.verification_sequence()
        prev_prop, prev_sigs = self.retrieve_checkpoint()
        prev_md = (
            decode(ViewMetadata, prev_prop.metadata)
            if prev_prop.metadata
            else ViewMetadata()
        )
        metadata = replace(metadata, black_list=list(prev_md.black_list))
        metadata = self.metadata_with_updated_blacklist(
            metadata, verification_seq, prev_prop, prev_sigs
        )
        return self.bind_commit_signatures(metadata, prev_sigs)

    def metadata_with_updated_blacklist(
        self, metadata: ViewMetadata, verification_seq: int, prev_prop, prev_sigs
    ) -> ViewMetadata:
        membership_change = (
            self.membership_notifier.membership_change()
            if self.membership_notifier is not None
            else False
        )
        if verification_seq == prev_prop.verification_sequence and not membership_change:
            return self._update_blacklist_metadata(metadata, prev_sigs, prev_prop.metadata)
        if verification_seq != prev_prop.verification_sequence:
            self.logger.infof(
                "Skipping updating blacklist due to verification sequence changing from %d to %d",
                prev_prop.verification_sequence, verification_seq,
            )
        if membership_change:
            self.logger.infof("Skipping updating blacklist due to membership change")
        return metadata

    def _update_blacklist_metadata(
        self, metadata: ViewMetadata, prev_sigs, prev_metadata: bytes
    ) -> ViewMetadata:
        """view.go:1022-1062."""
        if self.decisions_per_leader == 0:
            return replace(metadata, black_list=[])
        prepares_from: dict[int, PreparesFrom] = {}
        for sig in prev_sigs:
            aux = self.verifier.auxiliary_data(sig.msg)
            prepares_from[sig.signer] = decode_prepares_from(aux)
        prev_md = decode(ViewMetadata, prev_metadata) if prev_metadata else ViewMetadata()
        _, f = compute_quorum(self.n)
        black_list = compute_blacklist_update(
            current_leader=self.leader_id,
            leader_rotation=self.decisions_per_leader > 0,
            prev_md=prev_md,
            n=self.n,
            nodes=self.nodes_list,
            curr_view=metadata.view_id,
            prepares_from=prepares_from,
            f=f,
            decisions_per_leader=self.decisions_per_leader,
            logger=self.logger,
            metrics=self.metrics_blacklist,
        )
        return replace(metadata, black_list=black_list)

    def bind_commit_signatures(self, metadata: ViewMetadata, prev_sigs) -> ViewMetadata:
        """view.go:979-998."""
        if self.decisions_per_leader == 0:
            return metadata
        return replace(
            metadata, prev_commit_signature_digest=commit_signatures_digest(prev_sigs)
        )
