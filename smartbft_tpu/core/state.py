"""Protocol-state persistence over the WAL.

Re-design of /root/reference/internal/bft/state.go.  ``PersistedState.save``
appends a SavedMessage record at each phase transition (truncating on new
proposals — the previous decision is then stable); ``restore`` rebuilds the
View's phase, in-flight proposal, and last broadcast from the final one or
two WAL entries after a crash (state.go:115-247).
"""

from __future__ import annotations

from typing import Optional

from ..api import Logger, WriteAheadLog
from ..codec import decode
from ..messages import (
    Commit,
    CommitRecord,
    NewViewRecord,
    Prepare,
    ProposedRecord,
    Signature,
    ViewChange,
    ViewChangeRecord,
    ViewMetadata,
    marshal,
    unmarshal,
)
from ..types import ViewAndSeq
from .util import InFlightData

# Phase constants (view.go:22-31)
COMMITTED = 0
PROPOSED = 1
PREPARED = 2
ABORT = 3

PHASE_NAMES = {COMMITTED: "COMMITTED", PROPOSED: "PROPOSED", PREPARED: "PREPARED", ABORT: "ABORT"}


class StateRecorder:
    """In-memory State double for unit tests (state.go:18-29)."""

    def __init__(self) -> None:
        self.saved_messages: list = []

    def save(self, msg, truncate: Optional[bool] = None) -> None:
        self.saved_messages.append(msg)

    async def save_durable(self, msg, truncate: Optional[bool] = None) -> None:
        self.save(msg)

    def save_nowait(self, msg, truncate: Optional[bool] = None):
        self.save(msg)
        return None

    def restore(self, view) -> None:
        raise RuntimeError("should not be used")


class PersistedState:
    def __init__(
        self,
        in_flight: InFlightData,
        entries: list[bytes],
        logger: Logger,
        wal: WriteAheadLog,
        group_commit: bool = True,
    ):
        """``group_commit``: let :meth:`save_durable` ride the WAL's
        append_async path (batched fsync waves, awaited durability).  ON in
        production — fsyncs stop blocking the event loop.  Deterministic
        logical-clock tests turn it OFF (Configuration.wal_group_commit /
        fast_config): a save would otherwise span real executor round-trips
        during which the test harness advances the logical clock, firing
        timers the protocol never earned — the same determinism argument
        that keeps the sync-verifier fallback inline (view.py)."""
        self.in_flight = in_flight
        self.entries = entries
        self.logger = logger
        self.wal = wal
        self.group_commit = group_commit

    def save(self, msg, truncate: Optional[bool] = None) -> None:
        """Append a SavedMessage; by default only ProposedRecord truncates
        (state.go:38-59): a new proposal implies the previous decision is a
        stable checkpoint.  The pipelined window overrides ``truncate`` —
        a ProposedRecord for seq s+k lands while s is still undelivered, so
        there truncation is only safe when the window is otherwise empty."""
        data = self._record_and_marshal(msg)
        if truncate is None:
            truncate = isinstance(msg, ProposedRecord)
        self.wal.append(data, truncate_to=truncate)

    async def save_durable(self, msg, truncate: Optional[bool] = None) -> None:
        """Like :meth:`save`, but rides the WAL's group-commit path when it
        has one: the append happens immediately, the fsync lands in a wave
        shared with every other WAL on the loop, and this coroutine resumes
        once the record is durable.  Callers hold their dependent broadcast
        until then — the same WAL-first ordering the sync path gives."""
        fut = self.save_nowait(msg, truncate=truncate)
        if fut is not None:
            await fut

    def save_nowait(self, msg, truncate: Optional[bool] = None):
        """Write the record NOW; return its durability future, or None when
        the write was synchronously durable (blocking-save configuration).

        The pipelined window stages several slots' records back to back and
        awaits ONE shared fsync wave for all of them — sequentially awaiting
        :meth:`save_durable` per slot costs a wave round-trip each."""
        data = self._record_and_marshal(msg)
        if truncate is None:
            truncate = isinstance(msg, ProposedRecord)
        append_async = (
            getattr(self.wal, "append_async", None) if self.group_commit else None
        )
        if append_async is None:
            self.wal.append(data, truncate_to=truncate)
            return None
        return append_async(data, truncate_to=truncate)

    def _record_and_marshal(self, msg) -> bytes:
        if isinstance(msg, ProposedRecord):
            self._store_proposal(msg)
        elif isinstance(msg, CommitRecord):
            self._store_prepared(msg.commit)
        return marshal(msg)

    def _store_proposal(self, proposed: ProposedRecord) -> None:
        self.in_flight.store_proposal(proposed.pre_prepare.proposal)

    def _store_prepared(self, commit: Commit) -> None:
        self.in_flight.store_prepares(commit.view, commit.seq)

    def _last_entry(self):
        if not self.entries:
            return None
        try:
            return unmarshal(self.entries[-1])
        except Exception as e:
            self.logger.errorf("Failed unmarshaling last entry from WAL: %s", e)
            raise

    def load_new_view_if_applicable(self) -> Optional[ViewAndSeq]:
        """If the last WAL entry is a NewView record, adopt its view/seq
        (state.go:77-95)."""
        last = self._last_entry()
        if isinstance(last, NewViewRecord):
            md = last.metadata
            self.logger.infof("last entry in WAL is a newView record")
            return ViewAndSeq(view=md.view_id, seq=md.latest_sequence)
        return None

    def load_view_change_if_applicable(self) -> Optional[ViewChange]:
        """If the last WAL entry is a ViewChange, resume it (state.go:97-113)."""
        last = self._last_entry()
        if isinstance(last, ViewChangeRecord):
            self.logger.infof("last entry in WAL is a viewChange message")
            return last.view_change
        return None

    def restore(self, view) -> None:
        """Rebuild View runtime state from the last WAL entries
        (state.go:115-247).  A WindowedView (pipeline_depth > 1) restores
        its whole slot ladder from the suffix instead of just the tail."""
        view.phase = COMMITTED
        if not self.entries:
            self.logger.infof("Nothing to restore")
            return
        self.logger.infof("WAL contains %d entries", len(self.entries))
        restore_window = getattr(view, "restore_window", None)
        if restore_window is not None:
            records = []
            for raw in self.entries:
                try:
                    records.append(unmarshal(raw))
                except Exception as e:
                    self.logger.errorf("Failed unmarshaling WAL entry: %s", e)
                    raise
            restore_window(records)
            return
        last = self._last_entry()
        if isinstance(last, ProposedRecord):
            self._recover_proposed(last, view)
        elif isinstance(last, CommitRecord):
            self._recover_prepared(last, view)
        elif isinstance(last, (NewViewRecord, ViewChangeRecord)):
            self.logger.infof("last entry in WAL is a %s", type(last).__name__)
        else:
            raise ValueError(f"unrecognized record: {last!r}")

    def _recover_proposed(self, rec: ProposedRecord, view) -> None:
        """Crash after saving the pre-prepare: re-enter PROPOSED and
        re-broadcast our prepare (state.go:155-182)."""
        pp = rec.pre_prepare
        view.in_flight_proposal = pp.proposal
        self.in_flight.store_proposal(pp.proposal)
        view.last_broadcast_sent = rec.prepare
        view.phase = PROPOSED
        view.number = pp.view
        view.proposal_sequence = pp.seq
        md = decode(ViewMetadata, pp.proposal.metadata)
        view.decisions_in_view = md.decisions_in_view
        self.logger.infof("Restored proposal with sequence %d", pp.seq)

    def _recover_prepared(self, rec: CommitRecord, view) -> None:
        """Crash after saving our commit: the matching pre-prepare must be
        the second-to-last entry; re-enter PREPARED and re-broadcast the
        commit (state.go:184-247)."""
        if len(self.entries) < 2:
            raise ValueError(
                "last message is a commit, but expected to also have a matching pre-prepare"
            )
        prev = unmarshal(self.entries[-2])
        if not isinstance(prev, ProposedRecord) or prev.pre_prepare is None:
            raise ValueError(
                f"expected second last message to be a pre-prepare, got {type(prev).__name__}"
            )
        pp = prev.pre_prepare
        if view.proposal_sequence < pp.seq:
            raise ValueError(
                f"last proposal sequence persisted into WAL is {pp.seq} which is greater "
                f"than last committed sequence {view.proposal_sequence}"
            )
        if view.proposal_sequence > pp.seq:
            self.logger.infof(
                "Last proposal with sequence %d has been safely committed",
                view.proposal_sequence,
            )
            return
        commit = rec.commit
        view.in_flight_proposal = pp.proposal
        self.in_flight.store_proposal(pp.proposal)
        self.in_flight.store_prepares(commit.view, commit.seq)
        view.last_broadcast_sent = commit
        view.phase = PREPARED
        view.number = pp.view
        view.proposal_sequence = pp.seq
        md = decode(ViewMetadata, pp.proposal.metadata)
        view.decisions_in_view = md.decisions_in_view
        sig = commit.signature
        view.my_proposal_sig = Signature(signer=sig.signer, value=sig.value, msg=sig.msg)
        self.logger.infof("Restored proposal with sequence %d", pp.seq)
