"""State collector: aggregate StateTransferResponse votes during sync.

Re-design of /root/reference/internal/bft/statecollector.go:18-147.  The
Controller broadcasts a StateTransferRequest and awaits >f identical
{view, seq} responses or the collect timeout.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional

from ..api import Logger
from ..messages import Message, StateTransferResponse
from ..types import ViewAndSeq
from ..utils.clock import Scheduler
from .util import VoteSet, compute_quorum

#: hard lower bound of a DERIVED collect timeout (seconds): a state
#: sweep needs at least one full round trip plus peer dispatch
COLLECT_TIMEOUT_FLOOR = 0.05


class StateCollector:
    def __init__(
        self,
        self_id: int,
        n: int,
        logger: Logger,
        collect_timeout: float,
        scheduler: Scheduler,
        collect_timeout_fn: Optional[Callable[[], Optional[float]]] = None,
    ):
        """``collect_timeout_fn`` (ISSUE 15): optional live provider of a
        DERIVED collect timeout (the consensus facade wires an RTT-based
        one when adaptive detection is armed), clamped into
        [COLLECT_TIMEOUT_FLOOR, configured constant].  The state-fetch
        leg of a failover then gives up on missing peers at network
        scale instead of always burning the constant — the same
        ceiling/fallback contract as every other derived timer."""
        self.self_id = self_id
        self.n = n
        self._log = logger
        self._collect_timeout = collect_timeout
        self._collect_timeout_fn = collect_timeout_fn
        self._scheduler = scheduler
        self._quorum, self._f = compute_quorum(n)
        self._responses = VoteSet(
            lambda _s, m: isinstance(m, StateTransferResponse)
        )
        self._pending: list[tuple[int, Message]] = []
        self._wakeup: Optional[asyncio.Future] = None
        self._stopped = False

    def start(self) -> None:
        self._quorum, self._f = compute_quorum(self.n)
        self._stopped = False

    def stop(self) -> None:
        self._stopped = True
        if self._wakeup is not None and not self._wakeup.done():
            self._wakeup.set_result("stop")

    def handle_message(self, sender: int, msg: Message) -> None:
        if self._stopped or not isinstance(msg, StateTransferResponse):
            return
        if len(self._pending) >= self.n:
            return  # bounded inbox, drop on overflow (statecollector.go:61-64)
        self._pending.append((sender, msg))
        if self._wakeup is not None and not self._wakeup.done():
            self._wakeup.set_result("msg")

    def clear_collected(self) -> None:
        self._pending.clear()

    def effective_timeout(self) -> float:
        """The next collect arm's timeout: derived when a provider is
        wired and measuring, the configured constant otherwise."""
        fn = self._collect_timeout_fn
        ceiling = self._collect_timeout
        if fn is None:
            return ceiling
        try:
            derived = fn()
        except Exception:  # noqa: BLE001 — derivation is advisory
            return ceiling
        if derived is None or derived <= 0:
            return ceiling
        return min(max(derived, COLLECT_TIMEOUT_FLOOR), ceiling)

    async def collect_state_responses(self) -> Optional[ViewAndSeq]:
        """Await >f identical {view,seq} votes or timeout
        (statecollector.go:77-129)."""
        self._responses.clear()
        timer = self._scheduler.schedule(self.effective_timeout(), self._on_timeout)
        self._log.debugf("Node %d started collecting state responses", self.self_id)
        try:
            while True:
                while self._pending:
                    sender, msg = self._pending.pop(0)
                    self._responses.register_vote(sender, msg)
                result = self._collected_enough_equal_votes()
                if result is not None:
                    self._log.infof(
                        "Node %d collected a valid state: view - %d and seq - %d",
                        self.self_id, result.view, result.seq,
                    )
                    return result
                if self._stopped:
                    return None
                self._wakeup = asyncio.get_running_loop().create_future()
                reason = await self._wakeup
                self._wakeup = None
                if reason == "timeout":
                    self._log.infof("Node %d reached the state collector timeout", self.self_id)
                    return None
                if reason == "stop":
                    return None
        finally:
            timer.cancel()
            self._wakeup = None

    def _on_timeout(self) -> None:
        if self._wakeup is not None and not self._wakeup.done():
            self._wakeup.set_result("timeout")

    def _collected_enough_equal_votes(self) -> Optional[ViewAndSeq]:
        if len(self._responses.voted) <= self._f:
            return None
        counts: dict[ViewAndSeq, int] = {}
        for vote in self._responses.votes:
            resp: StateTransferResponse = vote.msg
            vs = ViewAndSeq(view=resp.view_num, seq=resp.sequence)
            counts[vs] = counts.get(vs, 0) + 1
        for vs, count in counts.items():
            if count > self._f:
                return vs
        return None
