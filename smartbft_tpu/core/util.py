"""Core protocol utilities: leader election, quorum, votes, blacklist.

Re-design of /root/reference/internal/bft/util.go.  The reference's
channel-backed ``voteSet`` (util.go:107-136) becomes a plain event-driven
accumulator — the asyncio core is single-owner per component, so no
channel machinery is needed.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api import Logger
from ..messages import Message, PreparesFrom, ViewMetadata
from ..metrics import BlacklistMetrics
from ..types import Decision


def compute_quorum(n: int) -> tuple[int, int]:
    """Return (Q, f) for cluster size n (util.go:176-180).

    f = ⌊(n−1)/3⌋;  Q = ⌈(n+f+1)/2⌉ — any two Q-subsets intersect in ≥ f+1.
    """
    f = (n - 1) // 3
    q = int(math.ceil((n + f + 1) / 2.0))
    return q, f


def get_leader_id(
    view: int,
    n: int,
    nodes: list[int],
    leader_rotation: bool,
    decisions_in_view: int,
    decisions_per_leader: int,
    blacklist: list[int],
) -> int:
    """Deterministic leader for (view, decisions_in_view) (util.go:72-100).

    Static mode: nodes[view % n].  Rotation: offset the view by completed
    leader terms and skip blacklisted nodes.

    ``decisions_per_leader`` is always in DECISIONS here.  Window-granular
    rotation (pipelined mode) pre-multiplies the configured per-window
    count by the window depth (Configuration.effective_decisions_per_leader)
    before it reaches any caller of this function, so a term spans whole
    windows and every replica — controller, view changer, blacklist
    recomputation — derives the same election from the same arithmetic.
    """
    if not leader_rotation:
        return nodes[view % n]
    blacklisted = set(blacklist)
    for i in range(len(nodes)):
        index = view + (decisions_in_view // decisions_per_leader) + i
        node = nodes[index % n]
        if node not in blacklisted:
            return node
    raise RuntimeError(f"all {len(nodes)} nodes are blacklisted")


@dataclass
class Vote:
    msg: Message
    sender: int


def iter_bits(mask: int):
    """Indices of the set bits of ``mask``, lowest first (pure int ops)."""
    while mask:
        low = mask & -mask
        yield low.bit_length() - 1
        mask ^= low


class SignerIndex:
    """Dense signer-id -> bit-index mapping, shared by every vote set and
    slot of a cluster.  Node ids are small ints, so the lookup is one list
    index — no hashing on the vote hot path."""

    __slots__ = ("ids", "_tbl")

    def __init__(self, ids: list[int]):
        self.ids = list(ids)
        size = (max(self.ids) + 1) if self.ids else 0
        self._tbl = [-1] * size
        for i, nid in enumerate(self.ids):
            self._tbl[nid] = i

    def index_of(self, nid: int) -> int:
        """Bit index of ``nid``, or -1 for an unknown signer."""
        if 0 <= nid < len(self._tbl):
            return self._tbl[nid]
        return -1

    def __len__(self) -> int:
        return len(self.ids)


class _VotedView:
    """len/in/iter view over a VoteSet's signer bitmask (API compat with
    the old ``voted: set[int]`` field)."""

    __slots__ = ("_vs",)

    def __init__(self, vs: "VoteSet"):
        self._vs = vs

    def __len__(self) -> int:
        return self._vs.mask.bit_count()

    def __contains__(self, voter: int) -> bool:
        idx = self._vs._index_of(voter)
        return idx >= 0 and bool(self._vs.mask >> idx & 1)

    def __iter__(self):
        vs = self._vs
        for idx in iter_bits(vs.mask):
            yield vs.signer_id(idx)


class VoteSet:
    """Dedup'd per-sender vote accumulator (util.go:102-136, event-driven).

    Bitmask representation: ``mask`` holds one bit per signer, payloads
    live in a per-signer array, so registration and the quorum test are
    integer ops (bit set + popcount) instead of set hashing and per-vote
    object allocation — the vote path runs ~12k times per decision at
    n=64, which made the old set+list representation a top-2 item of the
    protocol-plane profile (PERF.md).

    Two index modes:

    * ``signers=`` (hot paths — View / WindowedView slots): a shared
      :class:`SignerIndex` preallocates the payload array and maps ids by
      list lookup.  Payload order is signer-index order.
    * dynamic (cold paths — ViewChanger, StateCollector, doubles): indices
      are assigned first-seen, preserving the old arrival-order iteration
      exactly.

    Compat surface: ``voted`` (len/in/iter view over the mask) and
    ``votes`` (a lazily built list of :class:`Vote`) keep the cold
    consumers and existing tests working unchanged.
    """

    __slots__ = ("_valid_vote", "_signers", "_dyn_ids", "_dyn_idx",
                 "mask", "payloads")

    def __init__(self, valid_vote: Callable[[int, Message], bool],
                 signers: Optional[SignerIndex] = None):
        self._valid_vote = valid_vote
        self._signers = signers
        self._dyn_ids: Optional[list[int]] = None if signers is not None else []
        self._dyn_idx: Optional[dict[int, int]] = None if signers is not None else {}
        self.mask = 0
        self.payloads: list[Optional[Message]] = (
            [None] * len(signers) if signers is not None else []
        )

    # -- index plumbing ----------------------------------------------------

    def _index_of(self, voter: int) -> int:
        if self._signers is not None:
            return self._signers.index_of(voter)
        idx = self._dyn_idx.get(voter)
        return -1 if idx is None else idx

    def signer_id(self, idx: int) -> int:
        if self._signers is not None:
            return self._signers.ids[idx]
        return self._dyn_ids[idx]

    # -- core --------------------------------------------------------------

    def clear(self) -> None:
        self.mask = 0
        if self._signers is not None:
            for i in range(len(self.payloads)):
                self.payloads[i] = None
        else:
            self._dyn_ids.clear()
            self._dyn_idx.clear()
            self.payloads.clear()

    def register_vote(self, voter: int, msg: Message) -> Optional[Message]:
        """Returns the registered message, or None if invalid/duplicate."""
        if not self._valid_vote(voter, msg):
            return None
        if self._signers is not None:
            idx = self._signers.index_of(voter)
            if idx < 0:
                return None  # not a member
        else:
            idx = self._dyn_idx.get(voter)
            if idx is None:
                idx = len(self._dyn_ids)
                self._dyn_idx[voter] = idx
                self._dyn_ids.append(voter)
                self.payloads.append(None)
        bit = 1 << idx
        if self.mask & bit:
            return None  # double vote
        self.mask |= bit
        self.payloads[idx] = msg
        return msg

    def __len__(self) -> int:
        return self.mask.bit_count()

    def items(self):
        """(sender, msg) pairs of the registered votes."""
        for idx in iter_bits(self.mask):
            yield self.signer_id(idx), self.payloads[idx]

    # -- compat views ------------------------------------------------------

    @property
    def voted(self) -> _VotedView:
        return _VotedView(self)

    @property
    def votes(self) -> list[Vote]:
        return [Vote(msg=m, sender=s) for s, m in self.items()]


class NextViews:
    """Latest next-view announced per sender (util.go:138-156)."""

    def __init__(self) -> None:
        self._n: dict[int, int] = {}

    def clear(self) -> None:
        self._n.clear()

    def register_next(self, next_view: int, sender: int) -> None:
        if next_view <= self._n.get(sender, 0):
            return
        self._n[sender] = next_view

    def send_recv(self, next_view: int, sender: int) -> bool:
        return self._n.get(sender) == next_view


class InFlightData:
    """The proposal currently being agreed on + its prepared flag
    (util.go:184-247).  Read by the ViewChanger when building ViewData.

    Pipelined-window extension (pipeline_depth > 1): a seq-keyed WINDOW of
    in-flight proposals.  When the window is non-empty the single-slot
    accessors report the LOWEST rung, so every single-slot consumer (the
    ViewChanger's rung-0 ViewData field, the controller's stale-in-flight
    pruning) keeps working; :meth:`ladder` exposes the full ordered window
    for the multi-in-flight view change."""

    def __init__(self) -> None:
        self._proposal = None
        self._prepared = False
        self._window: dict[int, list] = {}  # seq -> [proposal, prepared]
        #: bumped on every mutation — cheap change detection for derived
        #: caches (the ViewChanger's hot-standby ViewData keys on it
        #: together with Checkpoint.version, ISSUE 15)
        self.version = 0
        #: single-subscriber mutation hook (the ViewChanger's event-driven
        #: hot-standby prebuild)
        self.on_mutate = None

    def _bump(self) -> None:
        self.version += 1
        cb = self.on_mutate
        if cb is not None:
            cb()

    def in_flight_proposal(self):
        if self._window:
            return self._window[min(self._window)][0]
        return self._proposal

    def is_in_flight_prepared(self) -> bool:
        if self._window:
            return self._window[min(self._window)][1]
        return self._prepared

    def store_proposal(self, proposal) -> None:
        self._proposal = proposal
        self._prepared = False
        self._bump()

    def store_prepares(self, view: int, seq: int) -> None:
        if self._proposal is None:
            if self._window:
                # pipelined mode after a crash restore: the WindowedView
                # tracks prepared-ness per rung via store_prepares_at; the
                # legacy singular slot may legitimately be empty here
                return
            raise RuntimeError("stored prepares but proposal is not initialized")
        self._prepared = True
        self._bump()

    def clear(self) -> None:
        self._proposal = None
        self._prepared = False
        self._window.clear()
        self._bump()

    # -- windowed API (pipeline_depth > 1) ---------------------------------

    def store_proposal_at(self, seq: int, proposal) -> None:
        self._window[seq] = [proposal, False]
        self._bump()

    def store_prepares_at(self, seq: int) -> None:
        slot = self._window.get(seq)
        if slot is None:
            raise RuntimeError(
                f"stored prepares at seq {seq} but its proposal is not initialized"
            )
        slot[1] = True
        self._bump()

    def clear_below(self, seq: int) -> None:
        """Drop window rungs for delivered sequences (< ``seq``).

        When this empties the window, a provably-stale legacy singular slot
        (PersistedState writes it on every windowed save) is cleared too —
        otherwise in_flight_proposal() would fall back to a long-delivered
        proposal and poison this node's next ViewData."""
        stale = [s for s in self._window if s < seq]
        for s in stale:
            del self._window[s]
        if stale:
            self._bump()
        if not self._window and self._proposal is not None \
                and getattr(self._proposal, "metadata", b""):
            from ..codec import decode
            from ..messages import ViewMetadata

            md = decode(ViewMetadata, self._proposal.metadata)
            if md.latest_sequence < seq:
                self._proposal = None
                self._prepared = False
                self._bump()

    def prune_synced(self, synced_seq: int) -> None:
        """A sync advanced the checkpoint to ``synced_seq``: drop what it
        covers.  Windowed mode keeps rungs ABOVE the synced sequence — they
        are still genuinely in flight and must stay reportable in ViewData
        (the ladder's quorum-intersection argument needs every broadcast
        commit remembered); single-slot mode clears the lone proposal,
        matching the reference (controller.go:682-705)."""
        if self._window:
            self.clear_below(synced_seq + 1)
        else:
            self.clear()

    def ladder(self) -> list[tuple[int, object, bool]]:
        """Ordered (seq, proposal, prepared) rungs of the window."""
        return [(s, *self._window[s]) for s in sorted(self._window)]


def compute_blacklist_update(
    *,
    current_leader: int,
    leader_rotation: bool,
    prev_md: ViewMetadata,
    n: int,
    nodes: list[int],
    curr_view: int,
    prepares_from: dict[int, PreparesFrom],
    f: int,
    decisions_per_leader: int,
    logger: Logger,
    metrics: Optional[BlacklistMetrics] = None,
) -> list[int]:
    """Deterministic blacklist update, recomputed independently by every
    replica at proposal time and re-verified by followers (util.go:415-495).

    After a view change: blacklist every leader of the skipped views.  Within
    a view: prune nodes attested alive by > f prepare-acknowledgement
    witnesses.  Cap the list at f by dropping from the front.
    """
    new_blacklist = list(prev_md.black_list)
    view_before = prev_md.view_id

    if view_before != curr_view:
        # A view change happened: blacklist the leaders of skipped views.
        # Offset matches the reference: past the first proposal, the previous
        # leader's ID was computed with decisions_in_view+1 (util.go:437-443).
        offset = 0 if prev_md.latest_sequence == 0 else 1
        for prev_view in range(view_before, curr_view):
            leader_id = get_leader_id(
                prev_view, n, nodes, leader_rotation,
                prev_md.decisions_in_view + offset, decisions_per_leader,
                list(prev_md.black_list),
            )
            if leader_id == current_leader:
                logger.debugf("Skipping blacklisting current node (%d)", leader_id)
                continue
            new_blacklist.append(leader_id)
            logger.infof("Blacklisting %d", leader_id)
    else:
        new_blacklist = prune_blacklist(new_blacklist, prepares_from, f, nodes, logger)

    while len(new_blacklist) > f:
        logger.infof(
            "Removing %d from %d sized blacklist due to size constraint",
            new_blacklist[0], len(new_blacklist),
        )
        new_blacklist = new_blacklist[1:]

    if len(prev_md.black_list) != len(new_blacklist):
        logger.infof("Blacklist changed: %s --> %s", prev_md.black_list, new_blacklist)

    if metrics is not None:
        in_bl = set(new_blacklist)
        for node in nodes:
            metrics.nodes_in_black_list.with_labels(str(node)).set(1.0 if node in in_bl else 0.0)
        metrics.count_black_list.set(len(new_blacklist))

    return new_blacklist


def prune_blacklist(
    prev_blacklist: list[int],
    prepares_from: dict[int, PreparesFrom],
    f: int,
    nodes: list[int],
    logger: Logger,
) -> list[int]:
    """Remove blacklisted nodes attested alive by > f witnesses, and nodes
    that left the membership (util.go:502-541)."""
    if not prev_blacklist:
        return prev_blacklist
    current = set(nodes)
    acks: dict[int, int] = {}
    for sender, got in prepares_from.items():
        for prepare_sender in got.ids:
            acks[prepare_sender] = acks.get(prepare_sender, 0) + 1
    out = []
    for node in prev_blacklist:
        if node not in current:
            logger.infof("Node %d no longer exists, removing it from the blacklist", node)
            continue
        if acks.get(node, 0) > f:
            logger.infof(
                "Node %d was observed sending a prepare by %d nodes, removing it from blacklist",
                node, acks[node],
            )
            continue
        out.append(node)
    return out


def msg_type_name(msg: Message) -> str:
    return type(msg).__name__


def view_number_of(msg: Message) -> Optional[int]:
    """The view a message refers to, for routing (util.go:338-413 analogue)."""
    for attr in ("view", "next_view", "view_num"):
        if hasattr(msg, attr):
            return getattr(msg, attr)
    return None
