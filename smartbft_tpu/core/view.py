"""The View: one (view-number, leader) instance of the three-phase protocol.

Re-design of /root/reference/internal/bft/view.go:68-1088.  The reference
runs a goroutine that drains an inbox channel and then blocks inside
phase-specific selects; here the same control flow is an asyncio task that
pumps one inbox and awaits phase predicates.  Three deliberate divergences,
all TPU-motivated:

1. **Batched commit verification** — the reference spawns a goroutine per
   commit vote calling ``VerifyConsenterSig`` (view.go:537-541); here commit
   votes accumulate between event-loop turns and are flushed through
   ``Verifier.verify_consenter_sigs_batch`` in one call, which the TPU
   verifier maps to a single vmap'd kernel launch.  Under load the batch
   grows automatically: while one batch is in flight on the device, newly
   arriving votes queue up for the next flush.
2. **Batched prev-commit-signature verification** in proposal validation
   (view.go:606-647) — a quorum-sized batch per pre-prepare.
3. Vote sets / pre-prepare slots are plain data, not channels — the view
   task is the single owner (SURVEY §2.4).

Pipelining is preserved: messages for sequence s+1 land in ``next_*`` sets
and are swapped in at ``_start_next_seq`` (view.go:107-113,860-894).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, replace
from typing import Optional, Sequence

from ..api import Logger, MembershipNotifier, Signer, Verifier
from ..codec import decode, encode
from ..messages import (
    Commit,
    CommitRecord,
    Message,
    PreparesFrom,
    PrePrepare,
    Prepare,
    Proposal,
    ProposedRecord,
    Signature,
    ViewMetadata,
)
from ..metrics import BlacklistMetrics, ViewMetrics
from ..types import VerifyPlaneDown, proposal_digest
from ..metrics import PROTOCOL_PLANE, current_plane
from .rotation import RotationState
from .state import ABORT, COMMITTED, PREPARED, PROPOSED
from .util import SignerIndex, VoteSet, compute_quorum, iter_bits
from ..utils.tasks import create_logged_task

_MAX_U64 = 2**64 - 1


def view_number_of_msg(msg: Message) -> int:
    """util.go:31-45 — view of a pre-prepare/prepare/commit, else MaxUint64."""
    if isinstance(msg, (PrePrepare, Prepare, Commit)):
        return msg.view
    return _MAX_U64


def proposal_sequence_of_msg(msg: Message) -> int:
    if isinstance(msg, (PrePrepare, Prepare, Commit)):
        return msg.seq
    return _MAX_U64


class ViewAborted(Exception):
    pass


@dataclass(frozen=True)
class ViewSequence:
    """view.go's ViewSequence (util.go:333-336)."""

    view_active: bool = False
    proposal_seq: int = 0


class ViewSequencesHolder:
    """Shared mutable slot replacing the reference's atomic.Value."""

    def __init__(self) -> None:
        self._v: Optional[ViewSequence] = None

    def store(self, vs: ViewSequence) -> None:
        self._v = vs

    def load(self) -> Optional[ViewSequence]:
        return self._v


@dataclass(frozen=True)
class _ProposalInfo:
    digest: str
    view: int
    seq: int


_ABORT = object()  # inbox sentinel

#: one loud warning per process when a sync-only verifier measurably
#: stalls the event loop (module-level: shared by View and ViewChanger)
_warned_slow_sync_verifier = False


async def verify_sigs_batch(verifier, sigs, proposal, logger=None) -> list:
    """Batched consenter-signature verification, async path preferred.

    Sync-only verifiers run inline, ON the event loop.  Deliberate: every
    CryptoProvider exposes the async coalescer path (engine on a worker
    thread), so the inline branch serves injected test verifiers with
    trivial crypto — and threading it (asyncio.to_thread) makes the
    deterministic logical-clock tests racy: timers advance while the
    thread runs, firing spurious heartbeat/view-change timeouts.  A
    production embedder with a slow sync-only verifier hears about it
    loudly (once per process) when the inline call measurably stalls the
    loop every component shares.
    """
    global _warned_slow_sync_verifier
    batch_async = getattr(verifier, "verify_consenter_sigs_batch_async", None)
    if batch_async is not None:
        return await batch_async(sigs, proposal)
    t0 = time.monotonic()
    out = verifier.verify_consenter_sigs_batch(sigs, proposal)
    elapsed = time.monotonic() - t0
    if elapsed > 0.05 and not _warned_slow_sync_verifier:
        _warned_slow_sync_verifier = True
        if logger is None:
            from ..utils.logging import StdLogger

            logger = StdLogger("smartbft.view")
        logger.warnf(
            "Sync-only verifier blocked the event loop for %.0f ms "
            "verifying %d signatures; EVERY consensus component stalls "
            "during such calls — implement verify_consenter_sigs_batch_async "
            "(see smartbft_tpu.crypto.provider.CryptoProvider) to run "
            "verification off-loop", 1e3 * elapsed, len(sigs),
        )
    return out


class View:
    """One protocol instance.  Constructed by ProposalMaker, owned by the
    Controller; communicates upward through Decider/FailureDetector/Sync."""

    def __init__(
        self,
        *,
        self_id: int,
        n: int,
        nodes_list: list[int],
        leader_id: int,
        quorum: int,
        number: int,
        decider,
        failure_detector,
        synchronizer,
        logger: Logger,
        comm,
        verifier: Verifier,
        signer: Signer,
        membership_notifier: Optional[MembershipNotifier],
        proposal_sequence: int,
        decisions_in_view: int,
        state,
        retrieve_checkpoint,
        decisions_per_leader: int,
        view_sequences: ViewSequencesHolder,
        metrics_view: Optional[ViewMetrics] = None,
        metrics_blacklist: Optional[BlacklistMetrics] = None,
        in_msg_q_size: int = 200,
        backpressure: bool = False,
        recorder=None,
    ):
        self.self_id = self_id
        self.n = n
        self.nodes_list = nodes_list
        self.leader_id = leader_id
        self.quorum = quorum
        self.number = number
        self.decider = decider
        self.failure_detector = failure_detector
        self.synchronizer = synchronizer
        self.logger = logger
        self.comm = comm
        self.verifier = verifier
        self.signer = signer
        self.membership_notifier = membership_notifier
        self.proposal_sequence = proposal_sequence
        self.decisions_in_view = decisions_in_view
        self.state = state
        self.retrieve_checkpoint = retrieve_checkpoint
        self.decisions_per_leader = decisions_per_leader
        self.view_sequences = view_sequences
        self.metrics = metrics_view
        self.metrics_blacklist = metrics_blacklist
        self.in_msg_q_size = in_msg_q_size
        # flight recorder (obs.TraceRecorder; nop singleton when tracing
        # is off): quorum-completion + WAL-persist marks for the per-
        # request critical-path decomposition (obs.critpath)
        from ..obs.recorder import NOP_RECORDER

        self.recorder = recorder if recorder is not None else NOP_RECORDER

        self.phase = COMMITTED
        # runtime
        self.my_proposal_sig: Optional[Signature] = None
        self.in_flight_proposal: Optional[Proposal] = None
        self.in_flight_requests: list = []
        # batch-processing latency starts at pre-prepare receipt; views that
        # skip processProposal (WAL restore, the in-flight commit view spun
        # up at Phase=PREPARED) must still have a start point
        self._begin_pre_prepare = self._now()
        self.last_broadcast_sent: Optional[Message] = None
        self._curr_prepare_sent: Optional[Prepare] = None
        self._curr_commit_sent: Optional[Commit] = None
        self._prev_prepare_sent: Optional[Prepare] = None
        self._prev_commit_sent: Optional[Commit] = None
        self._last_voted_proposal_by_id: dict[int, Commit] = {}
        # shared rotation machinery (blacklist metadata + chain checks);
        # also used by the pipelined WindowedView at window boundaries
        self._rotation = RotationState(
            self_id=self_id,
            n=n,
            nodes_list=nodes_list,
            leader_id=leader_id,
            get_view_number=lambda: self.number,
            decisions_per_leader=decisions_per_leader,
            verifier=verifier,
            retrieve_checkpoint=retrieve_checkpoint,
            membership_notifier=membership_notifier,
            logger=logger,
            metrics_blacklist=metrics_blacklist,
        )

        self.backpressure = backpressure
        # backpressure mode uses the queue's own bound so senders can block
        # on put(); drop mode keeps the unbounded queue + explicit check
        self._inbox: asyncio.Queue = asyncio.Queue(
            maxsize=in_msg_q_size if backpressure else 0
        )
        self._dropped_msgs = 0  # overflow counter for the bounded inbox
        self._aborted = False
        # the per-shard accounting plane captured at intake: _drain_inbox
        # runs in the view's OWN task (whose context predates any transport
        # dispatch), so the drain must use the plane the transport installed
        # when it fed the inbox, not current_plane() at drain time
        self._vote_plane = None
        self._task: Optional[asyncio.Task] = None
        # 1-slot pre-prepare stashes (view.go:105-111)
        self._pre_prepare: Optional[PrePrepare] = None
        self._next_pre_prepare: Optional[PrePrepare] = None
        #: shared id->bit mapping: one per view, reused by all 4 vote sets
        self._signer_index = SignerIndex(nodes_list)
        self._setup_votes()

    # ------------------------------------------------------------------ votes

    def _setup_votes(self) -> None:
        def accept_prepares(_sender: int, m: Message) -> bool:
            return isinstance(m, Prepare)

        def accept_commits(sender: int, m: Message) -> bool:
            if not isinstance(m, Commit) or m.signature is None:
                return False
            return m.signature.signer == sender  # view.go:160-171

        idx = self._signer_index
        self.prepares = VoteSet(accept_prepares, idx)
        self.next_prepares = VoteSet(accept_prepares, idx)
        self.commits = VoteSet(accept_commits, idx)
        self.next_commits = VoteSet(accept_commits, idx)

    # ------------------------------------------------------------------ life

    def start(self) -> None:
        self._task = create_logged_task(
            self._run(), name=f"view-{self.self_id}-{self.number}",
            logger=self.logger,
        )

    def stopped(self) -> bool:
        return self._aborted

    def _stop(self) -> None:
        if not self._aborted:
            self._aborted = True
            try:
                self._inbox.put_nowait(_ABORT)
            except asyncio.QueueFull:
                pass  # a full (backpressure) inbox wakes the loop anyway;
                # every dequeue re-checks self._aborted

    async def abort(self) -> None:
        """Force the view to end and wait for its task (view.go:1000-1010)."""
        self._stop()
        if self._task is not None:
            try:
                await self._task
            except asyncio.CancelledError:
                # Swallow ONLY the view task's own cancellation.  If the
                # CALLER is the one being cancelled (shutdown reaping a
                # controller parked here during a view change), eating the
                # error leaves that task permanently in 'cancelling' —
                # asyncio delivers the cancel once — and the event loop
                # can never close (the bug showed as a 0%-CPU hang in
                # asyncio.run's _cancel_all_tasks).
                cur = asyncio.current_task()
                # Task.cancelling is 3.11+; on 3.10 a finished view task
                # means the cancellation was the view's own — swallow it
                cancelling = getattr(cur, "cancelling", None)
                if not self._task.done() or (
                    cancelling is not None and cancelling()
                ):
                    raise

    def get_leader_id(self) -> int:
        return self.leader_id

    def handle_message(self, sender: int, msg: Message) -> None:
        """Sync intake: drop on overflow (the default policy).

        Bounded inbox (consensus.go:337 IncomingMessageBufferSize; the
        reference's View drains a buffered channel, view.go:274): drop on
        overflow so a Byzantine flooder cannot grow memory without limit."""
        if self._aborted:
            return
        self._note_intake_plane()
        if self._inbox.qsize() >= self.in_msg_q_size:
            self._dropped_msgs += 1
            if self._dropped_msgs == 1 or self._dropped_msgs % 1000 == 0:
                self.logger.warnf(
                    "View %d inbox full (%d), dropped %d messages from %d",
                    self.number, self.in_msg_q_size, self._dropped_msgs, sender,
                )
            return
        self._inbox.put_nowait((sender, msg))

    async def handle_message_async(self, sender: int, msg: Message) -> None:
        """Async intake: with ``backpressure`` on, a full inbox BLOCKS the
        sending task until the view drains — the reference's full-channel
        semantics (view.go:190).  Without backpressure, same as the sync
        path."""
        if not self.backpressure:
            self.handle_message(sender, msg)
            return
        if self._aborted:
            return
        self._note_intake_plane()
        await self._inbox.put((sender, msg))

    def ingest_batch(self, items) -> None:
        """Wave-batched intake: enqueue a whole wave of (sender, msg) pairs
        in one call.  The run task's pending ``get()`` wakes once for the
        wave instead of once per message; ``_drain_inbox`` then registers
        the rest without further awaits."""
        for sender, msg in items:
            self.handle_message(sender, msg)

    def _note_intake_plane(self) -> None:
        """Latch the transport's per-shard plane the first time one feeds
        this inbox.  A view belongs to exactly one group, so the capture is
        stable; loopback/self-deliveries (default-plane contexts) never
        overwrite it."""
        if self._vote_plane is None:
            p = current_plane()
            if p is not PROTOCOL_PLANE:
                self._vote_plane = p

    async def ingest_batch_async(self, items) -> None:
        """Backpressure-aware wave intake (blocks per message on a full
        inbox, like handle_message_async)."""
        if not self.backpressure:
            self.ingest_batch(items)
            return
        for sender, msg in items:
            await self.handle_message_async(sender, msg)

    # ------------------------------------------------------------------ loop

    async def _run(self) -> None:
        try:
            while True:
                if self.phase == COMMITTED:
                    await self._process_proposal()
                elif self.phase == PROPOSED:
                    self.comm.broadcast_consensus(self.last_broadcast_sent)
                    await self._process_prepares()
                elif self.phase == PREPARED:
                    self.comm.broadcast_consensus(self.last_broadcast_sent)
                    await self._prepared()
                elif self.phase == ABORT:
                    return
                if self.metrics:
                    self.metrics.phase.set(self.phase)
        except ViewAborted:
            pass
        except Exception as e:  # pragma: no cover - defensive
            self.logger.errorf("View %d crashed: %r", self.number, e)
            raise
        finally:
            # release EVERY sender blocked in handle_message_async's put()
            # on the (bounded) inbox of a view that is going away: each
            # drain pass frees at most qsize putters, and a freed putter
            # immediately re-fills the slot — so drain repeatedly, yielding
            # between passes, until a pass finds nothing (more concurrent
            # senders than the bound is the norm at large n)
            while True:
                drained = False
                while True:
                    try:
                        self._inbox.get_nowait()
                        drained = True
                    except asyncio.QueueEmpty:
                        break
                if not drained:
                    break
                await asyncio.sleep(0)
            self.view_sequences.store(
                ViewSequence(view_active=False, proposal_seq=self.proposal_sequence)
            )

    async def _next_event(self) -> None:
        """Await and process exactly one inbound message (or abort)."""
        item = await self._inbox.get()
        if item is _ABORT or self._aborted:
            raise ViewAborted()
        sender, msg = item
        self._process_msg(sender, msg)

    def _drain_inbox(self) -> None:
        """Process everything already queued without awaiting — lets votes
        coalesce ahead of a batched verify."""
        t0 = time.perf_counter()
        drained = False
        try:
            while True:
                try:
                    item = self._inbox.get_nowait()
                except asyncio.QueueEmpty:
                    return
                if item is _ABORT or self._aborted:
                    raise ViewAborted()
                drained = True
                sender, msg = item
                self._process_msg(sender, msg)
        finally:
            if drained:
                plane = self._vote_plane
                if plane is None:
                    plane = current_plane()
                plane.vote_reg_us += (time.perf_counter() - t0) * 1e6

    # ------------------------------------------------------------------ routing

    def _process_msg(self, sender: int, m: Message) -> None:
        """view.go:194-261 — route one message into slots/vote-sets."""
        if self._aborted:
            return
        msg_view = view_number_of_msg(m)
        msg_seq = proposal_sequence_of_msg(m)

        if msg_view != self.number:
            if sender != self.leader_id:
                self._discover_if_sync_needed(sender, m)
                return
            self.failure_detector.complain(self.number, False)
            if msg_view > self.number:
                self.synchronizer.sync()
            self._stop()
            return

        if msg_seq == self.proposal_sequence - 1 and self.proposal_sequence > 0:
            self._handle_prev_seq_message(msg_seq, sender, m)
            return

        if msg_seq != self.proposal_sequence and msg_seq != self.proposal_sequence + 1:
            self.logger.warnf(
                "%d got message from %d with sequence %d but our sequence is %d",
                self.self_id, sender, msg_seq, self.proposal_sequence,
            )
            self._discover_if_sync_needed(sender, m)
            return

        for_next = msg_seq == self.proposal_sequence + 1

        if isinstance(m, PrePrepare):
            self._process_pre_prepare(m, for_next, sender)
            return

        if sender == self.self_id:
            return  # ignore own votes (view.go:238-241)

        if isinstance(m, Prepare):
            (self.next_prepares if for_next else self.prepares).register_vote(sender, m)
            return

        if isinstance(m, Commit):
            (self.next_commits if for_next else self.commits).register_vote(sender, m)
            return

    def _process_pre_prepare(self, pp: PrePrepare, for_next: bool, sender: int) -> None:
        """view.go:301-324 — stash into the 1-slot (current or next)."""
        if pp.proposal is None:
            self.logger.warnf("%d got pre-prepare from %d with empty proposal", self.self_id, sender)
            return
        if sender != self.leader_id:
            self.logger.warnf(
                "%d got pre-prepare from %d but the leader is %d",
                self.self_id, sender, self.leader_id,
            )
            return
        if for_next:
            if self._next_pre_prepare is None:
                self._next_pre_prepare = pp
            else:
                self.logger.warnf("Got a pre-prepare for next sequence without processing previous one, dropping message")
        else:
            if self._pre_prepare is None:
                self._pre_prepare = pp
            else:
                self.logger.warnf("Got a pre-prepare for current sequence without processing previous one, dropping message")

    # ------------------------------------------------------------------ phases

    async def _process_proposal(self) -> None:
        """COMMITTED -> PROPOSED (view.go:351-427)."""
        self._prev_prepare_sent = self._curr_prepare_sent
        self._prev_commit_sent = self._curr_commit_sent
        self._curr_prepare_sent = None
        self._curr_commit_sent = None
        self.in_flight_proposal = None
        self.in_flight_requests = []
        self.last_broadcast_sent = None

        while self._pre_prepare is None:
            await self._next_event()
        pp = self._pre_prepare
        self._pre_prepare = None
        proposal = pp.proposal
        prev_commits = list(pp.prev_commit_signatures)

        try:
            requests = await self._verify_proposal(proposal, prev_commits)
        except VerifyPlaneDown as e:
            # the verify PLANE is down (retries + fallback exhausted), not
            # the proposal: don't blame the leader — escalate to sync and
            # let restore/catch-up re-validate once the plane recovers
            self.logger.errorf(
                "Verify plane down validating proposal at seq %d: %s; "
                "aborting view and syncing", self.proposal_sequence, e,
            )
            self.synchronizer.sync()
            self._stop()
            raise ViewAborted() from e
        except Exception as e:
            self.logger.warnf(
                "%d received bad proposal from %d: %s", self.self_id, self.leader_id, e
            )
            self.failure_detector.complain(self.number, False)
            self.synchronizer.sync()
            self._stop()
            raise ViewAborted() from e

        if self.metrics:
            self.metrics.count_txs_in_batch.set(len(requests))
        self._begin_pre_prepare = self._now()

        seq = self.proposal_sequence
        prepare = Prepare(view=self.number, seq=seq, digest=proposal_digest(proposal))

        # Record the pre-prepare before sending our prepare (WAL-first).
        # Awaiting durability (group-commit fsync wave) instead of blocking
        # lets every other component make progress while the disk syncs.
        await self._save_state(ProposedRecord(pre_prepare=pp, prepare=prepare))
        self.last_broadcast_sent = prepare
        self._curr_prepare_sent = replace(prepare, assist=True)
        self.in_flight_proposal = proposal
        self.in_flight_requests = requests

        # The leader broadcasts the pre-prepare only after persisting it
        # (view.go:421-423): WAL-first ordering.
        if self.self_id == self.leader_id:
            self.comm.broadcast_consensus(pp)

        self.logger.infof("Processed proposal with seq %d", seq)
        self.phase = PROPOSED

    async def _process_prepares(self) -> None:
        """PROPOSED -> PREPARED (view.go:441-517)."""
        proposal = self.in_flight_proposal
        expected_digest = proposal_digest(proposal)
        voter_ids: list[int] = []
        taken_mask = 0

        def sweep() -> None:
            # incremental mask sweep: only bits not seen before — popcount
            # + bit iteration, no per-vote objects or hashing
            nonlocal taken_mask
            new = self.prepares.mask & ~taken_mask
            taken_mask |= new
            for idx in iter_bits(new):
                prepare: Prepare = self.prepares.payloads[idx]
                if prepare.digest != expected_digest:
                    self.logger.warnf(
                        "Got wrong digest at processPrepares for prepare with seq %d",
                        prepare.seq,
                    )
                    continue
                voter_ids.append(self.prepares.signer_id(idx))

        while len(voter_ids) < self.quorum - 1:
            sweep()
            if len(voter_ids) >= self.quorum - 1:
                break
            await self._next_event()

        rec = self.recorder
        if rec.enabled:
            # the voter whose prepare COMPLETED the quorum — "the slowest
            # f+1-th voter", the critical-path table's named straggler.
            # Granularity is the INGEST WAVE: votes landing in one
            # coalesced wave are observationally simultaneous here, and
            # ties within the completing wave resolve in signer-index
            # order (the mask sweep's iteration order)
            rec.record(
                "quorum.prepare", view=self.number,
                seq=self.proposal_sequence,
                # quorum == 1 (n == 1) needs no peer votes: there is no
                # completing voter to name (and [-1] on the empty list
                # would crash the view — tracing must never break it)
                extra={"slowest_voter": voter_ids[self.quorum - 2]
                       if self.quorum >= 2
                       and len(voter_ids) >= self.quorum - 1 else -1,
                       "voters": len(voter_ids)},
            )

        # sweep prepares that are already queued/registered into the witness
        # list before signing: PreparesFrom is the liveness evidence behind
        # blacklist redemption (util.go:502-541), and crediting only the
        # FIRST quorum-1 voters lets a slow-but-alive replica lose the
        # witness race on every decision and never get redeemed
        # (the vote set dedupes per sender, so one more pass of the same
        # collection loop suffices)
        self._drain_inbox()
        sweep()

        self.logger.infof(
            "%d collected %d prepares from %s", self.self_id, len(voter_ids), voter_ids
        )

        prp_from = encode(PreparesFrom(ids=voter_ids))
        self.my_proposal_sig = self.signer.sign_proposal(proposal, prp_from)

        seq = self.proposal_sequence
        commit = Commit(
            view=self.number,
            seq=seq,
            digest=expected_digest,
            signature=Signature(
                signer=self.my_proposal_sig.signer,
                value=self.my_proposal_sig.value,
                msg=self.my_proposal_sig.msg,
            ),
        )
        # Save our commit before broadcasting it (group-commit durability).
        await self._save_state(CommitRecord(commit=commit))
        if rec.enabled:
            rec.record("wal.persist", view=self.number, seq=seq)
        self._curr_commit_sent = replace(commit, assist=True)
        self.last_broadcast_sent = commit
        self.logger.infof("Processed prepares for proposal with seq %d", seq)
        self.phase = PREPARED

    async def _prepared(self) -> None:
        """PREPARED -> COMMITTED via quorum of verified commits
        (view.go:326-349,519-551)."""
        proposal = self.in_flight_proposal
        signatures = await self._process_commits(proposal)

        seq = self.proposal_sequence
        rec = self.recorder
        if rec.enabled:
            rec.record(
                "quorum.commit", view=self.number, seq=seq,
                extra={"slowest_voter": signatures[-1].signer
                       if signatures else -1},
            )
        self.logger.infof("%d processed commits for proposal with seq %d", self.self_id, seq)
        if self.metrics:
            self.metrics.count_batch_all.add(1)
            self.metrics.count_txs_all.add(len(self.in_flight_requests))
            size = len(proposal.metadata) + len(proposal.header) + len(proposal.payload)
            for s in signatures:
                size += len(s.value) + len(s.msg)
            self.metrics.size_of_batch.add(size)
            self.metrics.latency_batch_processing.observe(self._now() - self._begin_pre_prepare)

        await self._decide(proposal, signatures, self.in_flight_requests)
        self.phase = COMMITTED

    async def _process_commits(self, proposal: Proposal) -> list[Signature]:
        """Collect Q-1 valid commit signatures, verifying in batches.

        Flush policy: hold the batch until enough candidates are pending to
        possibly complete the quorum.  Eager flushing launched a partial
        wave (the first few arrivals) and then a second launch for the
        rest; on accelerators where a launch costs ~100 ms of fixed
        latency, one quorum-sized launch per decision halves the verify
        latency on the critical path.  Liveness is unchanged: with too few
        candidates we block on the next event exactly as before."""
        expected_digest = proposal_digest(proposal)
        valid: list[Signature] = []
        seen: set[int] = set()
        pending: list[Signature] = []
        taken_mask = 0

        while len(valid) < self.quorum - 1:
            # gather every pending, digest-matching vote not yet verified
            # (incremental mask sweep — integer ops, no vote objects)
            new = self.commits.mask & ~taken_mask
            taken_mask |= new
            for idx in iter_bits(new):
                commit: Commit = self.commits.payloads[idx]
                if commit.digest != expected_digest:
                    self.logger.warnf("Got wrong digest at processCommits for seq %d", commit.seq)
                    continue
                sig = commit.signature
                if sig.signer in seen:
                    continue
                pending.append(sig)
            if pending and len(valid) + len(pending) >= self.quorum - 1:
                try:
                    results = await self._verify_consenter_sigs_batch(
                        pending, proposal
                    )
                except VerifyPlaneDown as e:
                    # the device plane exhausted its deadline+retry budget
                    # AND the host fallback: escalate to sync instead of
                    # letting the exception kill the view task (which would
                    # stall this replica permanently).  No complaint — the
                    # engine being down is not the leader's fault.
                    self.logger.errorf(
                        "Verify plane down collecting commits for seq %d: "
                        "%s; aborting view and syncing",
                        self.proposal_sequence, e,
                    )
                    self.synchronizer.sync()
                    self._stop()
                    raise ViewAborted() from e
                for sig, aux in zip(pending, results):
                    if aux is None:
                        self.logger.warnf("Couldn't verify %d's signature", sig.signer)
                        continue
                    if sig.signer in seen:
                        continue
                    # stop at EXACTLY quorum-1, like the reference's vote
                    # collector (view.go:326-349): a batched flush can
                    # validate extras, but admitting them would make
                    # certificate sizes vary per replica — and the
                    # prev-commit count check (view.go:694, ours :698)
                    # rejects any later pre-prepare carrying fewer commits
                    # than the verifier's own stored certificate
                    if len(valid) >= self.quorum - 1:
                        break
                    seen.add(sig.signer)
                    valid.append(sig)
                pending = []
                # more votes may have queued while verifying — drain w/o await
                self._drain_inbox()
                continue
            if len(valid) >= self.quorum - 1:
                break
            await self._next_event()

        self.logger.infof(
            "%d collected %d commits from %s",
            self.self_id, len(valid), sorted(s.signer for s in valid),
        )
        return valid

    async def _save_state(self, msg) -> None:
        """Persist a SavedMessage, awaiting durability.

        Prefers the state's ``save_durable`` (group-commit: append now,
        fsync in a shared wave — the WAL-first guarantee is intact because
        the caller broadcasts only after this resumes).  Falls back to the
        blocking ``save`` for injected test doubles.  A view abort that
        lands during the await is re-raised here so no post-abort
        broadcast goes out."""
        save_durable = getattr(self.state, "save_durable", None)
        if save_durable is not None:
            await save_durable(msg)
        else:
            self.state.save(msg)
        if self._aborted:
            raise ViewAborted()

    async def _verify_consenter_sigs_batch(
        self, sigs: Sequence[Signature], proposal: Proposal
    ) -> list:
        return await verify_sigs_batch(self.verifier, sigs, proposal, self.logger)

    async def _decide(self, proposal, signatures, requests) -> None:
        """view.go:851-858: prepare next sequence, then hand the decision to
        the Controller and wait for delivery.

        Deliberate divergence from the reference: the ViewSequence is stored
        AFTER ``_start_next_seq`` (the reference stores the just-decided
        sequence, view.go:853).  Every consumer treats ProposalSeq as "the
        sequence this view is working on" — the proposer stores the next
        expected sequence at view start, and the sync path checks
        ``response.seq == latest_seq + 1`` (controller.go:651) — so storing
        the just-decided value made the two sources ambiguous: a replica
        stuck one sequence behind an idle cluster reads the leader's
        heartbeat seq as equal to its own and never syncs (the heartbeat
        one-behind rescue, heartbeatmonitor.go:231-247, can then never
        fire).  Storing the next expected sequence on both paths makes the
        comparison sound."""
        self.logger.infof("Deciding on seq %d", self.proposal_sequence)
        self._start_next_seq()
        self.view_sequences.store(
            ViewSequence(view_active=True, proposal_seq=self.proposal_sequence)
        )
        signatures = list(signatures) + [self.my_proposal_sig]
        await self.decider.decide(proposal, signatures, requests)

    def _start_next_seq(self) -> None:
        """Pipeline swap: next-* become current (view.go:860-894)."""
        prev_seq = self.proposal_sequence
        self.proposal_sequence += 1
        self.decisions_in_view += 1
        if self.metrics:
            self.metrics.proposal_sequence.set(self.proposal_sequence)
            self.metrics.decisions_in_view.set(self.decisions_in_view)
        self.logger.infof("Sequence: %d-->%d", prev_seq, self.proposal_sequence)

        self._pre_prepare = self._next_pre_prepare
        self._next_pre_prepare = None

        self.prepares, self.next_prepares = self.next_prepares, self.prepares
        self.next_prepares.clear()

        self.commits, self.next_commits = self.next_commits, self.commits
        self.next_commits.clear()

    # ------------------------------------------------------------------ verify

    async def _verify_proposal(
        self, proposal: Proposal, prev_commits: list[Signature]
    ) -> list:
        """view.go:553-607 — structural, metadata, verification-sequence,
        prev-commit-signature, and blacklist checks."""
        requests = self.verifier.verify_proposal(proposal)

        md = decode(ViewMetadata, proposal.metadata)

        if md.view_id != self.number:
            raise ValueError(f"invalid view number: expected {self.number} got {md.view_id}")
        if md.latest_sequence != self.proposal_sequence:
            raise ValueError(
                f"invalid proposal sequence: expected {self.proposal_sequence} got {md.latest_sequence}"
            )
        if md.decisions_in_view != self.decisions_in_view:
            raise ValueError(
                f"invalid decisions in view: expected {self.decisions_in_view} got {md.decisions_in_view}"
            )
        expected_seq = self.verifier.verification_sequence()
        if proposal.verification_sequence != expected_seq:
            raise ValueError(
                f"verification sequence mismatch: expected {expected_seq} got {proposal.verification_sequence}"
            )

        prepare_acks = await self._rotation.verify_prev_commit_signatures(
            prev_commits, expected_seq
        )
        self._rotation.verify_blacklist(
            prev_commits, expected_seq, list(md.black_list), prepare_acks
        )
        self._rotation.verify_prev_commit_digest(prev_commits, md)

        return requests

    # ------------------------------------------------------------------ assists

    def _handle_prev_seq_message(self, msg_seq: int, sender: int, m: Message) -> None:
        """Resend our previous prepare/commit to a lagging replica
        (view.go:718-756)."""
        if isinstance(m, PrePrepare):
            self.logger.warnf(
                "Got pre-prepare for sequence %d but we're in sequence %d",
                msg_seq, self.proposal_sequence,
            )
            return
        if isinstance(m, Prepare):
            if m.assist:
                return
            if self._prev_prepare_sent is not None:
                self.comm.send_consensus(sender, self._prev_prepare_sent)
        elif isinstance(m, Commit):
            if m.assist:
                return
            if self._prev_commit_sent is not None:
                self.comm.send_consensus(sender, self._prev_commit_sent)

    def _discover_if_sync_needed(self, sender: int, m: Message) -> None:
        """f+1 matching future commit votes trigger a sync (view.go:758-818)."""
        if not isinstance(m, Commit):
            return
        _, f = compute_quorum(self.n)
        threshold = f + 1
        self._last_voted_proposal_by_id[sender] = m
        if len(self._last_voted_proposal_by_id) < threshold:
            return
        counts: dict[_ProposalInfo, int] = {}
        for vote in self._last_voted_proposal_by_id.values():
            info = _ProposalInfo(digest=vote.digest, view=vote.view, seq=vote.seq)
            counts[info] = counts.get(info, 0) + 1
        for info, count in counts.items():
            if count < threshold:
                continue
            if info.view < self.number:
                continue
            if info.seq <= self.proposal_sequence and info.view == self.number:
                continue
            self.logger.warnf(
                "Seen %d votes for digest %s in view %d, sequence %d but I am in view %d and seq %d",
                count, info.digest, info.view, info.seq, self.number, self.proposal_sequence,
            )
            self._stop()
            self.synchronizer.sync()
            return

    # ------------------------------------------------------------------ leader

    def get_metadata(self) -> bytes:
        """Build the next proposal's ViewMetadata incl. blacklist update and
        prev-commit-signature digest (view.go:896-948)."""
        metadata = ViewMetadata(
            view_id=self.number,
            latest_sequence=self.proposal_sequence,
            decisions_in_view=self.decisions_in_view,
        )
        return encode(self._rotation.build_leader_metadata(metadata))

    def propose(self, proposal: Proposal) -> None:
        """Leader: wrap as pre-prepare and self-deliver first so the WAL
        records it before the broadcast (view.go:951-977)."""
        prev_sigs: list[Signature] = []
        if self.decisions_per_leader > 0:
            _, prev_sigs = self.retrieve_checkpoint()
        pp = PrePrepare(
            view=self.number,
            seq=self.proposal_sequence,
            proposal=proposal,
            prev_commit_signatures=list(prev_sigs),
        )
        self.handle_message(self.leader_id, pp)
        self.logger.debugf(
            "Proposing proposal sequence %d in view %d", self.proposal_sequence, self.number
        )

    # ------------------------------------------------------------------ misc

    def _now(self) -> float:
        return time.monotonic()
