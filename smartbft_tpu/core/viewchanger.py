"""The view-change sub-protocol: ViewChange -> ViewData -> NewView.

Re-design of /root/reference/internal/bft/viewchanger.go:52-1363 — the most
intricate component of the protocol.  Structure:

- Nodes broadcast ``ViewChange{next_view}``; at f+1 (SpeedUpViewChange) or
  quorum-1 they join, persist a ViewChange record, abort the current view,
  and send signed ``ViewData`` (checkpoint + in-flight proposal) to the new
  leader (viewchanger.go:364-456).
- The new leader validates each ViewData — including delivering a last
  decision it is one behind on (checkLastDecision ladder, :501-666) — and at
  quorum runs ``check_in_flight`` (the agreed-in-flight decision rule,
  :813-908) before broadcasting ``NewView``.
- Every node validates the NewView's quorum of ViewData (:931-1095), commits
  an agreed in-flight proposal by spinning up a special View with itself as
  leader pre-seeded in PREPARED (:1186-1306), persists a NewView record, and
  informs the Controller.

Quorum signature checks on last decisions (``validate_last_decision``,
:681-727) are batched through the Verifier — the second TPU batching target
after commit processing.

Timing (resend interval, view-change timeout with exponential backoff) is
tick-driven from the shared Scheduler.  Ticks are delivered as events to the
main loop, except during the in-flight wait where a live tick callback
drives the timeout — mirroring the reference's two select sites.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..api import Logger, Signer, Verifier
from ..codec import decode, encode
from ..messages import (
    Commit,
    Message,
    NewView,
    NewViewRecord,
    Proposal,
    Signature,
    SignedViewData,
    ViewChange,
    ViewChangeRecord,
    ViewData,
    ViewMetadata,
)
from ..metrics import BlacklistMetrics, ViewChangeMetrics, ViewMetrics
from ..types import Checkpoint, VerifyPlaneDown, blacklist_of, proposal_digest
from .pool import remove_delivered_requests
from .state import PREPARED
from .util import InFlightData, NextViews, VoteSet, compute_quorum, get_leader_id
from .view import View, ViewSequencesHolder, verify_sigs_batch
from ..utils.tasks import create_logged_task


def validate_in_flight(in_flight_proposal: Optional[Proposal], last_sequence: int) -> None:
    """viewchanger.go:788-806 — raises if invalid."""
    if in_flight_proposal is None:
        return
    if not in_flight_proposal.metadata:
        raise ValueError("in flight proposal metadata is nil")
    md = decode(ViewMetadata, in_flight_proposal.metadata)
    if md.latest_sequence != last_sequence + 1:
        raise ValueError(
            f"the in flight proposal sequence is {md.latest_sequence} while the last "
            f"decision sequence is {last_sequence}"
        )


def validate_in_flight_ladder(vd: ViewData, last_sequence: int) -> None:
    """Ladder extension of :func:`validate_in_flight` (pipelined window):
    rung 0 sits at last_sequence+1 and every ``in_flight_more[i]`` must be
    the consecutive rung above it.  Raises if invalid."""
    validate_in_flight(vd.in_flight_proposal, last_sequence)
    # wire invariant FIRST: flag count == rung count always, even when the
    # rung list is empty — otherwise a ViewData with orphan prepared flags
    # (empty in_flight_more, non-empty in_flight_more_prepared) passes
    # validation and the invariant is only accidentally upheld downstream
    if len(vd.in_flight_more_prepared) != len(vd.in_flight_more):
        raise ValueError("in flight ladder prepared flags do not match rung count")
    if not vd.in_flight_more:
        return
    if vd.in_flight_proposal is None:
        raise ValueError("in flight ladder extension without a first rung")
    for i, prop in enumerate(vd.in_flight_more):
        if not prop.metadata:
            raise ValueError("in flight proposal metadata is nil")
        md = decode(ViewMetadata, prop.metadata)
        if md.latest_sequence != last_sequence + 2 + i:
            raise ValueError(
                f"in flight ladder rung {i + 1} has sequence {md.latest_sequence}, "
                f"expected {last_sequence + 2 + i}"
            )


async def validate_last_decision(
    vd: ViewData, quorum: int, n: int, verifier: Verifier
) -> int:
    """viewchanger.go:681-727 — verify a quorum of consenter signatures on
    the last decision (batched); returns its sequence.  Raises if invalid."""
    if vd.last_decision is None:
        raise ValueError("the last decision is not set")
    if not vd.last_decision.metadata:
        return 0  # genesis proposal: nothing to validate
    md = decode(ViewMetadata, vd.last_decision.metadata)
    if md.view_id >= vd.next_view:
        raise ValueError(
            f"last decision view {md.view_id} is greater or equal to requested next view {vd.next_view}"
        )
    num_sigs = len(vd.last_decision_signatures)
    if num_sigs < quorum:
        raise ValueError(f"there are only {num_sigs} last decision signatures")
    seen: set[int] = set()
    unique_sigs = []
    for sig in vd.last_decision_signatures:
        if sig.signer in seen:
            continue
        seen.add(sig.signer)
        unique_sigs.append(sig)
    # shared dispatch incl. the loop-stall warning for slow sync verifiers
    results = await verify_sigs_batch(verifier, unique_sigs, vd.last_decision)
    valid = sum(1 for r in results if r is not None)
    if any(r is None for r in results):
        raise ValueError("last decision signature is invalid")
    if valid < quorum:
        raise ValueError(f"there are only {valid} valid last decision signatures")
    return md.latest_sequence


def max_last_decision_sequence(messages: list[ViewData]) -> int:
    """viewchanger.go:910-929."""
    mx = 0
    for vd in messages:
        if vd.last_decision is None:
            raise ValueError("The last decision is not set")
        if not vd.last_decision.metadata:
            continue
        md = decode(ViewMetadata, vd.last_decision.metadata)
        mx = max(mx, md.latest_sequence)
    return mx


def _in_flight_rungs(vd: ViewData) -> dict[int, tuple[Proposal, bool]]:
    """seq -> (proposal, prepared) for every in-flight rung a ViewData
    carries: the reference-shaped singular field plus the pipelined-window
    extension (``in_flight_more``).  Raises on nil-metadata rungs, like the
    reference's check (viewchanger.go:837-841)."""
    rungs: dict[int, tuple[Proposal, bool]] = {}
    if vd.in_flight_proposal is not None:
        if not vd.in_flight_proposal.metadata:
            raise ValueError("view data message has in flight proposal with nil metadata")
        md = decode(ViewMetadata, vd.in_flight_proposal.metadata)
        rungs[md.latest_sequence] = (vd.in_flight_proposal, vd.in_flight_prepared)
    for i, prop in enumerate(vd.in_flight_more):
        if not prop.metadata:
            raise ValueError("view data message has in flight proposal with nil metadata")
        md = decode(ViewMetadata, prop.metadata)
        prepared = (
            vd.in_flight_more_prepared[i] if i < len(vd.in_flight_more_prepared) else False
        )
        rungs[md.latest_sequence] = (prop, prepared)
    return rungs


def _check_rung(
    entries: list[Optional[tuple[Proposal, bool]]], f: int, quorum: int
) -> tuple[Optional[Proposal], int]:
    """One rung of the agreed-in-flight decision rule: the A/B counters of
    viewchanger.go:813-908 over per-ViewData entries at ONE sequence.

    ``entries[i]`` is (proposal, prepared) if ViewData i carries an
    in-flight rung at the sequence under examination, else None (covers
    no-in-flight, wrong-sequence, and absent rungs — all of which the
    reference counts identically).  Returns (chosen_proposal_or_None,
    no_in_flight_count)."""
    possible: list[dict] = []
    no_in_flight_count = 0
    for e in entries:
        if e is None or not e[1]:
            no_in_flight_count += 1
        if e is not None and e[1] and not any(p["proposal"] == e[0] for p in possible):
            possible.append({"proposal": e[0], "preprepared": 0, "no_argument": 0})
    for e in entries:
        for p in possible:
            if e is None:
                p["no_argument"] += 1
            elif e[0] == p["proposal"]:
                p["no_argument"] += 1
                p["preprepared"] += 1
    for p in possible:
        if p["preprepared"] < f + 1:
            continue  # condition A2 fails
        if p["no_argument"] < quorum:
            continue  # condition A1 fails
        return p["proposal"], no_in_flight_count
    return None, no_in_flight_count


def check_in_flight(
    messages: list[ViewData], f: int, quorum: int, n: int, verifier: Verifier
) -> tuple[bool, bool, Optional[Proposal]]:
    """The agreed-in-flight-proposal decision rule (viewchanger.go:813-908).

    Returns (ok, no_in_flight, proposal):
      condition A — some prepared proposal at the expected sequence has >=f+1
        pre-prepare witnesses (A2) and >=quorum no-argument votes (A1);
      condition B — >=quorum of messages support that nothing is in flight.
    """
    expected_sequence = max_last_decision_sequence(messages) + 1
    entries = [_in_flight_rungs(vd).get(expected_sequence) for vd in messages]
    chosen, no_in_flight_count = _check_rung(entries, f, quorum)
    if chosen is not None:
        return True, False, chosen
    if no_in_flight_count >= quorum:
        return True, True, None
    return False, False, None


def check_in_flight_ladder(
    messages: list[ViewData], f: int, quorum: int, n: int, verifier: Verifier
) -> tuple[bool, list[Proposal]]:
    """Multi-in-flight generalization of :func:`check_in_flight` for the
    pipelined window (pipeline_depth > 1; no reference counterpart).

    Applies the A/B rule rung by rung starting at max-last-decision+1:
    every rung where condition A holds contributes an agreed proposal that
    MUST be committed before the new view starts (a commit quorum may have
    existed for it); the first rung where condition B holds terminates the
    ladder (quorum intersection: nothing at or above it can have gathered
    a commit quorum, because commit broadcasts are in-order within the
    window — see core/pipeline.py).  A rung satisfying neither fails the
    whole check, exactly as the single-slot rule does.

    Returns (ok, agreed_proposals_in_sequence_order).  With no ladder
    extensions present this reduces exactly to check_in_flight: one rung,
    then B on the empty rung above it.
    """
    expected_sequence = max_last_decision_sequence(messages) + 1
    all_rungs = [_in_flight_rungs(vd) for vd in messages]
    agreed: list[Proposal] = []
    # the ladder is bounded by the highest rung any ViewData carries
    highest = max((max(r) for r in all_rungs if r), default=0)
    while expected_sequence <= highest + 1:
        entries = [rungs.get(expected_sequence) for rungs in all_rungs]
        chosen, no_in_flight_count = _check_rung(entries, f, quorum)
        if chosen is not None:
            agreed.append(chosen)
            expected_sequence += 1
            continue
        if no_in_flight_count >= quorum:
            return True, agreed
        return False, []
    return True, agreed


class _InFlightDecider:
    """Decider/FailureDetector/Synchronizer facade handed to the special
    in-flight View (viewchanger.go:1308-1345)."""

    def __init__(self, vc: "ViewChanger"):
        self.vc = vc

    async def decide(self, proposal, signatures, requests) -> None:
        vc = self.vc
        if vc._in_flight_view is not None:
            vc._in_flight_view._stop()
        vc.logger.debugf("Delivering to app from in-flight Decide the last decision proposal")
        reconfig = await vc.application.deliver(proposal, signatures)
        if reconfig.in_latest_decision:
            vc.close()
        remove_delivered_requests(vc.requests_timer, requests, vc.logger)
        vc.pruner.maybe_prune_revoked_requests()
        if vc._in_flight_decide is not None and not vc._in_flight_decide.done():
            vc._in_flight_decide.set_result(True)

    def complain(self, view_num: int, stop_view: bool) -> None:
        self.vc.logger.panicf(
            "Node %d has complained while in the view for the in flight proposal",
            self.vc.self_id,
        )

    def sync(self) -> None:
        vc = self.vc
        vc.logger.debugf(
            "Node %d is calling sync because the in flight proposal view has asked to sync",
            vc.self_id,
        )
        vc.synchronizer.sync()
        if vc._in_flight_sync is not None and not vc._in_flight_sync.done():
            vc._in_flight_sync.set_result(True)


class ViewChanger:
    #: how long a fresh run loop waits for a cancelled prior loop to
    #: actually finish before escalating (clear vote state + force sync);
    #: tests tighten it
    STRAGGLER_WAIT: float = 5.0

    #: scheduler-seconds of state quiet before a mutation-driven standby
    #: rebuild fires.  Short enough to land well inside the detection
    #: floor (a complaint is at least DETECTION_FLOOR=50ms of silence
    #: away), long enough that a window of back-to-back commits costs one
    #: timer reschedule per mutation instead of one ViewData sign each
    STANDBY_REBUILD_DEBOUNCE: float = 0.02

    def __init__(
        self,
        *,
        self_id: int,
        n: int,
        nodes_list: list[int],
        leader_rotation: bool,
        decisions_per_leader: int,
        speed_up_view_change: bool,
        logger: Logger,
        signer: Signer,
        verifier: Verifier,
        checkpoint: Checkpoint,
        in_flight: InFlightData,
        state,
        resend_timeout: float,
        view_change_timeout: float,
        in_msg_q_size: int,
        backpressure: bool = False,
        metrics_view_change: Optional[ViewChangeMetrics] = None,
        metrics_blacklist: Optional[BlacklistMetrics] = None,
        metrics_view: Optional[ViewMetrics] = None,
        vc_phases=None,
        recorder=None,
        scheduler=None,
    ):
        self.self_id = self_id
        self.n = n
        self.nodes_list = nodes_list
        self.leader_rotation = leader_rotation
        self.decisions_per_leader = decisions_per_leader
        self.speed_up_view_change = speed_up_view_change
        self.logger = logger
        self.signer = signer
        self.verifier = verifier
        self.checkpoint = checkpoint
        self.in_flight = in_flight
        self.state = state
        self.resend_timeout = resend_timeout
        self.view_change_timeout = view_change_timeout
        self.in_msg_q_size = in_msg_q_size
        self.backpressure = backpressure
        self._space_event = asyncio.Event()
        self.metrics = metrics_view_change
        self.metrics_blacklist = metrics_blacklist
        self.metrics_view = metrics_view
        #: optional obs.ViewChangePhaseTracker — marks the complain →
        #: depose → ViewData → new-view pipeline's transition points so
        #: the flight recorder can decompose failover time (ISSUE 12);
        #: None = no decomposition (unit tests constructing a bare
        #: ViewChanger pay nothing)
        self.vc_phases = vc_phases
        from ..obs.recorder import NOP_RECORDER

        self.recorder = recorder if recorder is not None else NOP_RECORDER

        # wired later by the Consensus facade (consensus.go:445-450,466-470)
        self.comm = None  # Controller (broadcast + send)
        self.synchronizer = None  # Controller (sync trigger)
        self.application = None  # MutuallyExclusiveDeliver
        self.controller = None  # ViewController: view_changed / abort_view
        self.requests_timer = None  # Pool
        self.pruner = None  # Controller
        self.view_sequences: Optional[ViewSequencesHolder] = None

        self.quorum = 0
        self.f = 0
        self.curr_view = 0
        self.real_view = 0
        self.next_view = 0
        self._events: asyncio.Queue = asyncio.Queue()
        self._queued_msgs = 0  # network messages in-queue (bounded; internal events are not)
        self._dropped_msgs = 0
        # start barrier (consensus.go:507-511 waitForEachOther): the run loop
        # holds off processing until the Controller finished starting, so a
        # message racing a start/reconfig cannot hit a half-wired ViewChanger.
        self.controller_started_event: Optional[asyncio.Event] = None
        self._stopped = False
        self._task: Optional[asyncio.Task] = None
        self._prior_tasks: set[asyncio.Task] = set()
        self._restore_on_start = False

        self.view_change_msgs = VoteSet(lambda _s, m: isinstance(m, ViewChange))
        self.view_data_msgs = VoteSet(lambda _s, m: isinstance(m, SignedViewData))
        self.nvs = NextViews()

        self._last_tick = 0.0
        self._last_resend = 0.0
        self._start_view_change_time = 0.0
        self._check_timeout = False
        self._back_off_factor = 1
        self._committed_during_view_change: Optional[ViewMetadata] = None
        self._pending_changes = 0

        # hot-standby ViewData (ISSUE 15): when THIS node is the
        # deterministic next leader, the tick loop pre-builds (and signs)
        # its ViewData from the live checkpoint/ladder state, keyed on
        # (next_view, checkpoint.version, in_flight.version) so any
        # protocol progress invalidates the cache.  On complaint quorum
        # _prepare_view_data_msg then returns the cached message instead
        # of reconstructing + re-signing state under the depose — the new
        # leader registers its own vote immediately and starts collecting
        # the quorum one round trip sooner.
        self._standby_msg: Optional[SignedViewData] = None
        self._standby_key: Optional[tuple] = None
        self.standby_prebuilds = 0
        self.standby_hits = 0
        # Event-driven prebuild (ISSUE 15 residual b): checkpoint/ladder
        # mutations notify _note_state_mutation, which debounces on the
        # shared scheduler (mutation bursts — every commit bumps both
        # versions several times — collapse to ONE rebuild, fired only
        # once the state goes quiet) and enqueues a "standby" event.  The
        # tick-time prebuild stays as the no-scheduler fallback and
        # belt-and-braces refresh; the event path is what closes the
        # cache-hit gap, because the moment mutations STOP (leader dead,
        # cluster idle) is exactly when the next complaint finds the
        # cache key still matching.
        self.scheduler = scheduler
        self._standby_timer = None
        self._standby_event_queued = False
        self.standby_event_rebuilds = 0

        self._in_flight_view: Optional[View] = None
        self._in_flight_decide: Optional[asyncio.Future] = None
        self._in_flight_sync: Optional[asyncio.Future] = None
        self._in_flight_tick_cb = None

    # ------------------------------------------------------------------ life

    def start(self, start_view_number: int) -> None:
        """viewchanger.go:119-159."""
        self.quorum, self.f = compute_quorum(self.n)
        self.curr_view = start_view_number
        self.real_view = self.curr_view
        self.next_view = self.curr_view
        self._set_view_metrics()
        self.nvs.clear()
        self.view_change_msgs.clear()
        self.view_data_msgs.clear()
        self._back_off_factor = 1
        self._stopped = False
        # reuse safety: a prior life's run loop may still be winding down if
        # the caller close()d without awaiting stop() — cancel it so two
        # loops never compete on one queue, then drain its backlog (a stale
        # ("stop",) sentinel would kill the fresh run loop on its first turn)
        if self._task is not None and not self._task.done():
            self._task.cancel()
            self._prior_tasks.add(self._task)
        # transitive: a prior life may ITSELF still be waiting on an even
        # older cancelled loop — a rapid double restart must not let the
        # oldest loop interleave with the newest (wait on ALL live priors)
        self._prior_tasks = {t for t in self._prior_tasks if not t.done()}
        while not self._events.empty():
            self._events.get_nowait()
        self._queued_msgs = 0
        self._pending_changes = 0
        self._standby_event_queued = False
        # event-driven standby prebuild: subscribe to checkpoint/ladder
        # mutations (single-subscriber seam; this ViewChanger owns it)
        if self.checkpoint is not None:
            self.checkpoint.on_mutate = self._note_state_mutation
        if self.in_flight is not None:
            self.in_flight.on_mutate = self._note_state_mutation
        self._task = create_logged_task(
            self._run(frozenset(self._prior_tasks)),
            name=f"viewchanger-{self.self_id}", logger=self.logger,
        )

    def _set_view_metrics(self) -> None:
        if self.metrics:
            self.metrics.current_view.set(self.curr_view)
            self.metrics.real_view.set(self.real_view)
            self.metrics.next_view.set(self.next_view)

    def close(self) -> None:
        if not self._stopped:
            self._stopped = True
            if self._standby_timer is not None:
                self._standby_timer.cancel()
                self._standby_timer = None
            if self.controller_started_event is not None:
                self.controller_started_event.set()  # release the start barrier
            self._space_event.set()  # release blocked async senders
            self._events.put_nowait(("stop",))
            for fut in (self._in_flight_decide, self._in_flight_sync):
                if fut is not None and not fut.done():
                    fut.set_result(False)

    async def stop(self) -> None:
        self.close()
        if self._task is not None:
            await self._task
            self._task = None

    # ------------------------------------------------------------------ inputs

    def handle_message(self, sender: int, m: Message) -> None:
        if self._stopped:
            return
        # Bounded message intake (consensus.go:406 IncomingMessageBufferSize):
        # only network messages count toward the bound — internal control
        # events (change/inform/tick/stop) must never be dropped.
        if self._queued_msgs >= self.in_msg_q_size:
            self._dropped_msgs += 1
            if self._dropped_msgs == 1 or self._dropped_msgs % 1000 == 0:
                self.logger.warnf(
                    "ViewChanger inbox full (%d), dropped %d messages from %d",
                    self.in_msg_q_size, self._dropped_msgs, sender,
                )
            return
        self._queued_msgs += 1
        self._events.put_nowait(("msg", sender, m))

    async def handle_message_async(self, sender: int, m: Message) -> None:
        """Async intake: with ``backpressure`` on, a full intake BLOCKS the
        sending task until the run loop drains below the bound — the
        reference's full-channel semantics (viewchanger.go:206)."""
        if not self.backpressure:
            self.handle_message(sender, m)
            return
        while not self._stopped and self._queued_msgs >= self.in_msg_q_size:
            self._space_event.clear()
            await self._space_event.wait()
        if self._stopped:
            return
        self._queued_msgs += 1
        self._events.put_nowait(("msg", sender, m))

    def handle_view_message(self, sender: int, m: Message) -> None:
        """Pass view messages to the in-flight view (viewchanger.go:1347-1356)."""
        view = self._in_flight_view
        if view is not None:
            self.logger.debugf("Node %d is passing a message to the in flight view", self.self_id)
            view.handle_message(sender, m)

    def start_view_change(self, view: int, stop_view: bool) -> None:
        """External trigger (viewchanger.go:356-361); 2-slot like the
        reference's buffered channel."""
        if self._stopped or self._pending_changes >= 2:
            return
        self._pending_changes += 1
        self._events.put_nowait(("change", view, stop_view))

    def inform_new_view(self, view: int) -> None:
        if self._stopped:
            return
        self._events.put_nowait(("inform", view))

    def restore_trigger(self) -> None:
        """Restore a persisted ViewChange on startup (consensus.go:487-494)."""
        self._events.put_nowait(("restore",))

    def tick(self, now: float) -> None:
        """Driven by the shared scheduler Ticker."""
        if self._stopped:
            return
        if self._in_flight_tick_cb is not None:
            self._in_flight_tick_cb(now)
            return
        self._events.put_nowait(("tick", now))

    # ------------------------------------------------------------------ loop

    async def _run(self, prior_tasks: frozenset = frozenset()) -> None:
        if prior_tasks:
            # prior lives' cancelled loops may be suspended mid-_process_msg
            # (not at the queue.get); let their cancellations land before
            # this loop touches shared ViewChanger state, so two loops never
            # interleave.  asyncio.wait never propagates the tasks' outcomes.
            # Bounded: an embedder callback that swallows cancellation must
            # not brick the ViewChanger forever — after the timeout, escalate
            # SAFELY: discard the shared view-change bookkeeping a straggler
            # may still be mutating (vote sets rebuild from peer resends —
            # the resend timer re-broadcasts every resend_timeout) and force
            # a sync so this node re-derives its position from the cluster
            # instead of from potentially interleaved state.
            _, stragglers = await asyncio.wait(prior_tasks, timeout=self.STRAGGLER_WAIT)
            if stragglers:
                self.logger.errorf(
                    "ViewChanger %d: %d prior run loop(s) ignored cancellation "
                    "for %.1fs; clearing view-change vote state and forcing a "
                    "sync instead of sharing it with a live straggler",
                    self.self_id, len(stragglers), self.STRAGGLER_WAIT,
                )
                self.view_change_msgs.clear()
                self.view_data_msgs.clear()
                self.nvs.clear()
                self._check_timeout = False
                if self.synchronizer is not None:
                    self.synchronizer.sync()
        if self.controller_started_event is not None:
            await self.controller_started_event.wait()  # viewchanger.go:156
        while True:
            evt = await self._events.get()
            kind = evt[0]
            # close() may have released the start barrier with a message
            # backlog still queued ahead of the stop sentinel — never process
            # it against a half-started controller
            if kind == "stop" or self._stopped:
                return
            try:
                if kind == "msg":
                    self._queued_msgs -= 1
                    self._space_event.set()  # wake blocked async senders
                    await self._process_msg(evt[1], evt[2])
                elif kind == "change":
                    self._pending_changes -= 1
                    self._start_view_change(evt[1], evt[2])
                elif kind == "tick":
                    self._last_tick = evt[1]
                    if self.vc_phases is not None:
                        self.vc_phases.note_tick()  # live in-VC gauge
                    self._check_if_resend_view_change(evt[1])
                    self._check_if_timeout(evt[1])
                    self._maybe_prebuild_standby()
                elif kind == "standby":
                    self._standby_event_queued = False
                    before = self.standby_prebuilds
                    self._maybe_prebuild_standby()
                    if self.standby_prebuilds != before:
                        self.standby_event_rebuilds += 1
                elif kind == "inform":
                    self._inform_new_view(evt[1])
                elif kind == "restore":
                    await self._process_view_change_msg(restore=True)
            except Exception as e:
                self.logger.errorf("ViewChanger %d event %s failed: %r", self.self_id, kind, e)
                raise

    # ------------------------------------------------------------------ timing

    def get_leader(self) -> int:
        return get_leader_id(
            self.curr_view, self.n, self.nodes_list, self.leader_rotation,
            0, self.decisions_per_leader, self._blacklist(),
        )

    def _blacklist(self) -> list[int]:
        prop, _ = self.checkpoint.get()
        return blacklist_of(prop)

    # -- hot-standby ViewData (ISSUE 15) -----------------------------------

    def _note_state_mutation(self) -> None:
        """Checkpoint / in-flight ladder mutation hook (loop-synchronous:
        every mutation site runs on the shared event loop).  Debounced —
        the rebuild fires only once the state stays quiet for
        STANDBY_REBUILD_DEBOUNCE, so a burst of per-commit version bumps
        costs timer reschedules, not ViewData signatures."""
        if self._stopped:
            return
        if self.scheduler is not None:
            if self._standby_timer is not None:
                self._standby_timer.cancel()
            self._standby_timer = self.scheduler.schedule(
                self.STANDBY_REBUILD_DEBOUNCE, self._fire_standby_rebuild
            )
        else:
            # no scheduler wired (bare unit-test construction): rebuild
            # eagerly on the next loop turn
            self._fire_standby_rebuild()

    def _fire_standby_rebuild(self) -> None:
        self._standby_timer = None
        if self._stopped or self._standby_event_queued:
            return
        self._standby_event_queued = True  # 1-slot: coalesce until processed
        self._events.put_nowait(("standby",))

    def _standby_state_key(self, next_view: int) -> tuple:
        """Everything a ViewData is built from, as cheap version counters:
        the checkpoint (last decision + signatures) and the in-flight
        ladder.  Any commit, prepare, sync prune, or window move bumps
        one of them and invalidates the cache."""
        return (
            next_view,
            self.checkpoint.version,
            getattr(self.in_flight, "version", -1),
        )

    def _maybe_prebuild_standby(self) -> None:
        """Tick hook (off the commit hot path): when this node would lead
        view curr_view+1, keep a signed ViewData for it pre-built from
        the LIVE state.  Non-next-leaders drop the cache — it would never
        be consulted with a matching key."""
        if self._stopped or self.comm is None or self.signer is None:
            return
        try:
            next_leader = get_leader_id(
                self.curr_view + 1, self.n, self.nodes_list,
                self.leader_rotation, 0, self.decisions_per_leader,
                self._blacklist(),
            )
        except Exception:  # noqa: BLE001 — e.g. everyone blacklisted
            return
        if next_leader != self.self_id:
            self._standby_msg = None
            self._standby_key = None
            return
        key = self._standby_state_key(self.curr_view + 1)
        if self._standby_msg is not None and key == self._standby_key:
            return
        self._standby_msg = self._build_view_data_msg(self.curr_view + 1)
        self._standby_key = key
        self.standby_prebuilds += 1
        if self.vc_phases is not None:
            self.vc_phases.note_standby(prebuilt=True)

    def _check_if_resend_view_change(self, now: float) -> None:
        """viewchanger.go:232-252."""
        if self._last_resend + self.resend_timeout > now:
            return
        if self._check_timeout:
            self.comm.broadcast_consensus(ViewChange(next_view=self.next_view))
            if self.metrics:
                self.metrics.count_complaints_sent.add(1)
            self.logger.debugf(
                "Node %d resent a view change message with next view %d",
                self.self_id, self.next_view,
            )
        self._last_resend = now

    def _check_if_timeout(self, now: float) -> bool:
        """viewchanger.go:254-270 — exponential backoff."""
        if not self._check_timeout:
            return False
        if self._start_view_change_time + self.view_change_timeout * self._back_off_factor > now:
            return False
        self.logger.debugf(
            "Node %d got a view change timeout, the current view is %d",
            self.self_id, self.curr_view,
        )
        self._check_timeout = False
        self._back_off_factor += 1
        if self.metrics:
            self.metrics.count_sync_escalations.add(1)
        rec = self.recorder
        if rec.enabled:
            rec.record("vc.timeout_sync", view=self.curr_view)
        if self.vc_phases is not None:
            # the round is being recycled (sync + restart): close it as
            # abandoned so its marks don't read as an in-progress VC
            self.vc_phases.timeout_escalated()
        self.synchronizer.sync()
        self.start_view_change(self.curr_view, False)
        return True

    # ------------------------------------------------------------------ msgs

    async def _process_msg(self, sender: int, m: Message) -> None:
        """viewchanger.go:272-326."""
        if isinstance(m, ViewChange):
            if self.metrics:
                self.metrics.count_complaints_received.add(1)
            self.nvs.register_next(m.next_view, sender)
            if m.next_view == self.curr_view + 1:
                self.view_change_msgs.register_vote(sender, m)
                await self._process_view_change_msg(restore=False)
                return
            if (
                self.next_view == self.curr_view + 1
                and m.next_view > self.real_view
                and m.next_view < self.curr_view + 1
                and self.nvs.send_recv(m.next_view, sender)
            ):
                # help the lagging nodes
                self.comm.broadcast_consensus(ViewChange(next_view=m.next_view))
                if self.metrics:
                    self.metrics.count_complaints_sent.add(1)
                self.logger.warnf(
                    "Node %d got viewChange from %d with view %d, expected view %d, helping lagging nodes",
                    self.self_id, sender, m.next_view, self.curr_view + 1,
                )
                return
            self.logger.warnf(
                "Node %d got viewChange from %d with view %d, expected view %d",
                self.self_id, sender, m.next_view, self.curr_view + 1,
            )
            return

        if isinstance(m, SignedViewData):
            if not await self._validate_view_data_msg(m, sender):
                return
            self.view_data_msgs.register_vote(sender, m)
            await self._process_view_data_msg()
            return

        if isinstance(m, NewView):
            leader = self.get_leader()
            if sender != leader:
                self.logger.warnf(
                    "Node %d got newView from %d, expected sender to be %d the next leader",
                    self.self_id, sender, leader,
                )
                return
            await self._process_new_view_msg(m)

    def _inform_new_view(self, view: int) -> None:
        """viewchanger.go:335-353."""
        if view < self.curr_view:
            return
        self.logger.debugf("Node %d was informed of a new view %d", self.self_id, view)
        if self.vc_phases is not None:
            # a sync installed the view around the VC pipeline
            self.vc_phases.abandoned_by_sync(view)
        self.curr_view = view
        self.real_view = view
        self.next_view = view
        self._set_view_metrics()
        self.nvs.clear()
        self.view_change_msgs.clear()
        self.view_data_msgs.clear()
        self._check_timeout = False
        self._back_off_factor = 1
        # a sync installed a new view around the VC pipeline — still a
        # flip: fast-forward the stalled backlog to the new leader
        self.requests_timer.restart_timers(flip=True)

    def _start_view_change(self, view: int, stop_view: bool) -> None:
        """viewchanger.go:364-391."""
        if view < self.curr_view:
            return
        if self.next_view == self.curr_view + 1:
            self.logger.debugf(
                "Node %d has already started view change with last view %d",
                self.self_id, self.curr_view,
            )
            self._check_timeout = True
            return
        self.next_view = self.curr_view + 1
        if self.metrics:
            self.metrics.next_view.set(self.next_view)
            self.metrics.count_complaints_sent.add(1)
        if self.vc_phases is not None:
            self.vc_phases.armed(self.next_view)
        self.requests_timer.stop_timers()
        self.comm.broadcast_consensus(ViewChange(next_view=self.next_view))
        self.logger.debugf(
            "Node %d started view change, last view is %d", self.self_id, self.curr_view
        )
        if stop_view:
            self.controller.abort_view(self.curr_view)
        self._start_view_change_time = self._last_tick
        self._check_timeout = True

    async def _process_view_change_msg(self, restore: bool) -> None:
        """viewchanger.go:393-431."""
        if (len(self.view_change_msgs.voted) == self.f + 1 and self.speed_up_view_change) or restore:
            self.logger.debugf(
                "Node %d is joining view change, last view is %d", self.self_id, self.curr_view
            )
            self._start_view_change(self.curr_view, True)
        if len(self.view_change_msgs.voted) < self.quorum - 1 and not restore:
            return
        if not self.speed_up_view_change:
            self.logger.debugf(
                "Node %d is joining view change (quorum), last view is %d",
                self.self_id, self.curr_view,
            )
            self._start_view_change(self.curr_view, True)
        if not restore:
            self.state.save(ViewChangeRecord(view_change=ViewChange(next_view=self.curr_view)))
        self.controller.abort_view(self.curr_view)
        self.curr_view = self.next_view
        if self.metrics:
            self.metrics.current_view.set(self.curr_view)
        if self.vc_phases is not None:
            # complaint quorum reached: this node committed to next view
            self.vc_phases.joined(self.curr_view)
        self.view_change_msgs.clear()
        self.view_data_msgs.clear()
        msg = self._prepare_view_data_msg()
        leader = self.get_leader()
        if leader == self.self_id:
            self.view_data_msgs.register_vote(self.self_id, msg)
        else:
            self.comm.send_consensus(leader, msg)
        if self.vc_phases is not None:
            self.vc_phases.viewdata_sent(self.curr_view)
        self.logger.debugf(
            "Node %d sent view data msg, with next view %d, to the new leader %d",
            self.self_id, self.curr_view, leader,
        )

    def _prepare_view_data_msg(self) -> SignedViewData:
        """viewchanger.go:433-456, fronted by the hot-standby cache: a
        pre-built message whose state key still matches the live
        checkpoint/ladder is returned as-is (the one-round-trip failover
        path); anything else is built fresh."""
        key = self._standby_state_key(self.curr_view)
        if self._standby_msg is not None and key == self._standby_key:
            self.standby_hits += 1
            if self.vc_phases is not None:
                self.vc_phases.note_standby(hit=True)
            return self._standby_msg
        return self._build_view_data_msg(self.curr_view)

    def _build_view_data_msg(self, next_view: int) -> SignedViewData:
        """The pipelined window adds the in-flight LADDER (every
        undelivered consecutive rung above the checkpoint)."""
        last_decision, last_decision_signatures = self.checkpoint.get()
        in_flight = self._get_in_flight(last_decision)
        prepared = self.in_flight.is_in_flight_prepared()
        more: list[Proposal] = []
        more_prepared: list[bool] = []
        ladder = self.in_flight.ladder()
        if ladder:
            last_seq = 0
            if last_decision is not None and last_decision.metadata:
                last_seq = decode(ViewMetadata, last_decision.metadata).latest_sequence
            # consecutive prefix starting right above the checkpoint; stale
            # rungs (<= last_seq, e.g. committed during the view change)
            # are dropped, gaps cut the ladder
            want = last_seq + 1
            rungs: list[tuple[Proposal, bool]] = []
            for seq, prop, prepped in ladder:
                if seq < want:
                    continue
                if seq != want:
                    break
                rungs.append((prop, prepped))
                want += 1
            if rungs:
                in_flight, prepared = rungs[0]
                more = [p for p, _ in rungs[1:]]
                more_prepared = [pr for _, pr in rungs[1:]]
            else:
                in_flight, prepared = None, False
        vd = ViewData(
            next_view=next_view,
            last_decision=last_decision,
            last_decision_signatures=list(last_decision_signatures),
            in_flight_proposal=in_flight,
            in_flight_prepared=prepared,
            in_flight_more=more,
            in_flight_more_prepared=more_prepared,
        )
        vd_bytes = encode(vd)
        sig = self.signer.sign(vd_bytes)
        return SignedViewData(raw_view_data=vd_bytes, signer=self.self_id, signature=sig)

    def _get_in_flight(self, last_decision: Proposal) -> Optional[Proposal]:
        """viewchanger.go:458-499."""
        in_flight = self.in_flight.in_flight_proposal()
        if in_flight is None:
            return None
        if not in_flight.metadata:
            self.logger.panicf("Node %d's in flight proposal metadata is not set", self.self_id)
        in_flight_md = decode(ViewMetadata, in_flight.metadata)
        if last_decision is None:
            self.logger.panicf("%d The given last decision is nil", self.self_id)
        if not last_decision.metadata:
            return in_flight  # first proposal after genesis
        last_md = decode(ViewMetadata, last_decision.metadata)
        if in_flight_md.latest_sequence == last_md.latest_sequence:
            return None  # not an actual in-flight proposal
        if (
            in_flight_md.latest_sequence + 1 == last_md.latest_sequence
            and self._committed_during_view_change is not None
            and self._committed_during_view_change.latest_sequence == last_md.latest_sequence
        ):
            self.logger.infof(
                "Node %d's in flight proposal sequence is %d while already committed decision %d "
                "(committed during the view change)",
                self.self_id, in_flight_md.latest_sequence, last_md.latest_sequence,
            )
            return None
        return in_flight

    # ------------------------------------------------------------------ viewdata (leader)

    async def _validate_view_data_msg(self, svd: SignedViewData, sender: int) -> bool:
        """viewchanger.go:501-533."""
        if self.get_leader() != self.self_id:
            self.logger.warnf(
                "Node %d got viewData from %d, but is not the next leader of view %d",
                self.self_id, sender, self.curr_view,
            )
            return False
        try:
            vd = decode(ViewData, svd.raw_view_data)
        except Exception as e:
            self.logger.errorf(
                "Node %d was unable to decode viewData message from %d: %s",
                self.self_id, sender, e,
            )
            return False
        if vd.next_view != self.curr_view:
            self.logger.warnf(
                "Node %d got viewData from %d with next view %d, but is in view %d",
                self.self_id, sender, vd.next_view, self.curr_view,
            )
            return False
        valid, last_decision_sequence = await self._check_last_decision(svd, sender)
        if not valid:
            self.logger.warnf(
                "Node %d got viewData from %d, but the check of the last decision didn't pass",
                self.self_id, sender,
            )
            return False
        try:
            validate_in_flight_ladder(vd, last_decision_sequence)
        except ValueError as e:
            self.logger.warnf(
                "Node %d got viewData from %d, but the in flight proposal is invalid: %s",
                self.self_id, sender, e,
            )
            return False
        return True

    def _extract_current_sequence(self) -> tuple[int, Proposal]:
        """viewchanger.go:668-679."""
        my_last_decision, _ = self.checkpoint.get()
        if not my_last_decision.metadata:
            return 0, my_last_decision
        md = decode(ViewMetadata, my_last_decision.metadata)
        return md.latest_sequence, my_last_decision

    async def _check_last_decision(
        self, svd: SignedViewData, sender: int
    ) -> tuple[bool, int]:
        """The checkLastDecision ladder (viewchanger.go:535-666)."""
        try:
            vd = decode(ViewData, svd.raw_view_data)
        except Exception:
            return False, 0
        if vd.last_decision is None:
            return False, 0

        my_sequence, my_last_decision = self._extract_current_sequence()

        if not vd.last_decision.metadata:  # genesis proposal
            if my_sequence > 0:
                return False, 0  # we are ahead
            return True, 0

        last_md = decode(ViewMetadata, vd.last_decision.metadata)
        if last_md.view_id >= vd.next_view:
            return False, 0
        if last_md.latest_sequence > my_sequence + 1:
            return False, 0  # future decision; might lack config to validate
        if last_md.latest_sequence < my_sequence:
            return False, 0  # past decision
        if last_md.latest_sequence == my_sequence:
            # same sequence: verify message signature + compare decisions
            if svd.signer != sender:
                return False, 0
            try:
                self.verifier.verify_signature(
                    Signature(signer=svd.signer, value=svd.signature, msg=svd.raw_view_data)
                )
            except Exception as e:
                self.logger.warnf(
                    "Node %d got viewData from %d, but signature is invalid: %s",
                    self.self_id, sender, e,
                )
                return False, 0
            if vd.last_decision != my_last_decision:
                self.logger.warnf(
                    "Node %d got viewData from %d at same sequence but last decisions differ",
                    self.self_id, sender,
                )
                return False, 0
            return True, last_md.latest_sequence

        if last_md.latest_sequence != my_sequence + 1:
            return False, 0

        # We are one behind: validate the decision and deliver it.
        try:
            await validate_last_decision(vd, self.quorum, self.n, self.verifier)
        except (ValueError, VerifyPlaneDown) as e:
            # VerifyPlaneDown: the verify plane is down, not the message —
            # drop it as unvalidatable; the sender's resend timer retries
            self.logger.warnf(
                "Node %d got viewData from %d, but the last decision is invalid: %s",
                self.self_id, sender, e,
            )
            return False, 0

        await self._deliver_decision(vd.last_decision, list(vd.last_decision_signatures))
        md = decode(ViewMetadata, vd.last_decision.metadata)
        self._committed_during_view_change = md

        if self._stopped:  # a reconfig may have stopped us during delivery
            return False, 0

        if svd.signer != sender:
            return False, 0
        try:
            self.verifier.verify_signature(
                Signature(signer=svd.signer, value=svd.signature, msg=svd.raw_view_data)
            )
        except Exception:
            return False, 0
        return True, last_md.latest_sequence

    async def _process_view_data_msg(self) -> None:
        """Leader: quorum of ViewData -> NewView (viewchanger.go:747-785)."""
        if len(self.view_data_msgs.voted) < self.quorum:
            return
        self.logger.debugf("Node %d got a quorum of viewData messages", self.self_id)
        messages = [decode(ViewData, v.msg.raw_view_data) for v in self.view_data_msgs.votes]
        ok, _ = check_in_flight_ladder(messages, self.f, self.quorum, self.n, self.verifier)
        if not ok:
            self.logger.debugf("Node %d checked the in flight and it was invalid", self.self_id)
            return
        if self.vc_phases is not None:
            # new leader: quorum of ViewData validated, NewView going out
            self.vc_phases.viewdata_quorum(self.curr_view)
        my_msg = self._prepare_view_data_msg()  # it might have changed by now
        signed_msgs = [my_msg]
        for vote in self.view_data_msgs.votes:
            if vote.sender == self.self_id:
                continue
            signed_msgs.append(vote.msg)
        nv = NewView(signed_view_data=signed_msgs)
        self.logger.debugf("Node %d is broadcasting a new view msg", self.self_id)
        self.comm.broadcast_consensus(nv)
        await self._process_msg(self.self_id, nv)  # also process at self
        self.view_data_msgs.clear()

    # ------------------------------------------------------------------ newview (all)

    async def _validate_new_view_msg(self, msg: NewView) -> tuple[bool, bool, bool]:
        """viewchanger.go:931-1095 — returns (valid, called_sync, called_deliver)."""
        seen: set[int] = set()
        valid_count = 0
        my_sequence, my_last_decision = self._extract_current_sequence()

        for svd in msg.signed_view_data:
            if svd.signer in seen:
                continue
            seen.add(svd.signer)
            try:
                vd = decode(ViewData, svd.raw_view_data)
            except Exception as e:
                self.logger.errorf("Unable to decode viewData in newView: %s", e)
                return False, False, False
            if vd.next_view != self.curr_view:
                self.logger.warnf(
                    "Node %d processing newView: nextView is %d while currView is %d",
                    self.self_id, vd.next_view, self.curr_view,
                )
                return False, False, False
            if vd.last_decision is None:
                return False, False, False

            if not vd.last_decision.metadata:  # genesis
                if my_sequence > 0:
                    try:
                        validate_in_flight_ladder(vd, 0)
                    except ValueError:
                        return False, False, False
                    valid_count += 1
                    continue
                try:
                    self.verifier.verify_signature(
                        Signature(signer=svd.signer, value=svd.signature, msg=svd.raw_view_data)
                    )
                    validate_in_flight_ladder(vd, 0)
                except Exception:
                    return False, False, False
                valid_count += 1
                continue

            last_md = decode(ViewMetadata, vd.last_decision.metadata)
            if last_md.view_id >= vd.next_view:
                return False, False, False

            if last_md.latest_sequence > my_sequence + 1:
                # future decision — sync
                self.synchronizer.sync()
                return True, True, False

            if last_md.latest_sequence < my_sequence:
                try:
                    validate_in_flight_ladder(vd, last_md.latest_sequence)
                except ValueError:
                    return False, False, False
                valid_count += 1
                continue

            if last_md.latest_sequence == my_sequence:
                try:
                    self.verifier.verify_signature(
                        Signature(signer=svd.signer, value=svd.signature, msg=svd.raw_view_data)
                    )
                except Exception:
                    return False, False, False
                if vd.last_decision != my_last_decision:
                    return False, False, False
                try:
                    validate_in_flight_ladder(vd, last_md.latest_sequence)
                except ValueError:
                    return False, False, False
                valid_count += 1
                continue

            if last_md.latest_sequence != my_sequence + 1:
                return False, False, False

            # one behind — validate, deliver, then verify message sig
            try:
                await validate_last_decision(vd, self.quorum, self.n, self.verifier)
            except (ValueError, VerifyPlaneDown) as e:
                self.logger.warnf("newView last decision invalid: %s", e)
                return False, False, False
            await self._deliver_decision(
                vd.last_decision, list(vd.last_decision_signatures)
            )
            if self._stopped:
                return False, False, False
            try:
                self.verifier.verify_signature(
                    Signature(signer=svd.signer, value=svd.signature, msg=svd.raw_view_data)
                )
                validate_in_flight_ladder(vd, last_md.latest_sequence)
            except Exception:
                return False, False, False
            return True, False, True

        if valid_count < self.quorum:
            self.logger.warnf(
                "Node %d processing newView: only %d valid view data messages (quorum %d)",
                self.self_id, valid_count, self.quorum,
            )
            return False, False, False
        return True, False, False

    async def _process_new_view_msg(self, msg: NewView) -> None:
        """viewchanger.go:1110-1167."""
        valid, called_sync, called_deliver = await self._validate_new_view_msg(msg)
        while called_deliver:
            self.logger.debugf("Node %d processed newView and delivered a proposal", self.self_id)
            valid, called_sync, called_deliver = await self._validate_new_view_msg(msg)
        if not valid:
            self.logger.warnf("Node %d processing newView: message invalid", self.self_id)
            return
        if called_sync:
            return

        messages = [
            decode(ViewData, svd.raw_view_data) for svd in msg.signed_view_data
        ]
        ok, agreed = check_in_flight_ladder(
            messages, self.f, self.quorum, self.n, self.verifier
        )
        if not ok:
            self.logger.debugf("In flight check by node %d did not pass", self.self_id)
            return
        # commit every agreed in-flight proposal, in sequence order: each
        # commit advances the checkpoint, satisfying the next rung's
        # last-decision precondition (single-rung ladders are the
        # reference-shaped case, viewchanger.go:1110-1167)
        for in_flight_proposal in agreed:
            if self._stopped:
                return
            # skip rungs this node already delivered: with pipelining a node
            # can hold commit quorums (and a checkpoint) SEVERAL sequences
            # past the quorum's reported max — the single-slot protocol
            # could only ever be one ahead, which _commit_in_flight_proposal
            # handles; two-plus ahead would hit its sequence panic
            rung_md = decode(ViewMetadata, in_flight_proposal.metadata)
            my_sequence, _ = self._extract_current_sequence()
            if rung_md.latest_sequence <= my_sequence:
                self.logger.debugf(
                    "Node %d already delivered rung %d, skipping its in-flight commit",
                    self.self_id, rung_md.latest_sequence,
                )
                continue
            if not await self._commit_in_flight_proposal(in_flight_proposal):
                self.logger.warnf(
                    "Node %d was unable to commit the in flight proposal, not changing the view",
                    self.self_id,
                )
                return

        my_sequence, _ = self._extract_current_sequence()
        self.state.save(
            NewViewRecord(
                metadata=ViewMetadata(view_id=self.curr_view, latest_sequence=my_sequence)
            )
        )
        if self._stopped:
            return
        self.real_view = self.curr_view
        if self.metrics:
            self.metrics.real_view.set(self.real_view)
        if self.vc_phases is not None:
            # NewView validated + persisted; first_commit starts here
            self.vc_phases.newview_done(self.curr_view)
        self.nvs.clear()
        self.controller.view_changed(self.curr_view, my_sequence + 1)
        # the FLIP: the new view is installed and the pool still holds the
        # backlog that stalled through the depose — fast-forward its
        # forward timers so it reaches the new leader's first deep windows
        # instead of waiting out a full request timeout per window
        self.requests_timer.restart_timers(flip=True)
        self._check_timeout = False
        self._back_off_factor = 1

    async def _deliver_decision(self, proposal: Proposal, signatures: list[Signature]) -> None:
        """viewchanger.go:1169-1184."""
        reconfig = await self.application.deliver(proposal, signatures)
        if reconfig.in_latest_decision:
            self.close()
        remove_delivered_requests(
            self.requests_timer, self.verifier.requests_from_proposal(proposal), self.logger
        )
        self.pruner.maybe_prune_revoked_requests()

    # ------------------------------------------------------------------ in-flight commit

    async def _commit_in_flight_proposal(self, proposal: Optional[Proposal]) -> bool:
        """Spin up a special PREPARED View with self as leader to commit the
        agreed in-flight proposal (viewchanger.go:1186-1306)."""
        my_last_decision, _ = self.checkpoint.get()
        if proposal is None:
            self.logger.panicf("The in flight proposal is nil")
        proposal_md = decode(ViewMetadata, proposal.metadata)

        if my_last_decision.metadata:
            last_md = decode(ViewMetadata, my_last_decision.metadata)
            if last_md.latest_sequence == proposal_md.latest_sequence:
                if my_last_decision != proposal:
                    self.logger.warnf(
                        "Node %d last decision differs from in-flight proposal at same sequence",
                        self.self_id,
                    )
                    return False
                return True  # already decided on it
            if last_md.latest_sequence != proposal_md.latest_sequence - 1:
                self.logger.panicf(
                    "Node %d got in-flight proposal with sequence %d while last decision is %d",
                    self.self_id, proposal_md.latest_sequence, last_md.latest_sequence,
                )

        decider = _InFlightDecider(self)
        view = View(
            retrieve_checkpoint=self.checkpoint.get,
            decisions_per_leader=self.decisions_per_leader,
            self_id=self.self_id,
            n=self.n,
            nodes_list=self.nodes_list,
            number=proposal_md.view_id,
            leader_id=self.self_id,  # so no byzantine leader causes a complain
            quorum=self.quorum,
            decider=decider,
            failure_detector=decider,
            synchronizer=decider,
            logger=self.logger,
            comm=self.comm,
            verifier=self.verifier,
            signer=self.signer,
            membership_notifier=None,
            proposal_sequence=proposal_md.latest_sequence,
            decisions_in_view=0,
            state=self.state,
            in_msg_q_size=self.in_msg_q_size,
            view_sequences=self.view_sequences,
            metrics_view=self.metrics_view,
            metrics_blacklist=self.metrics_blacklist,
        )
        view.phase = PREPARED
        view.in_flight_proposal = proposal
        # The normal path populates in_flight_requests at proposal verify
        # time (view._process_pre_prepare); this special view skips that
        # phase, so without this the decide() hand-off prunes NOTHING from
        # the request pool on ANY node — the deposed leader keeps the
        # committed batch pooled and forwards it to the new leader (within
        # one flip-drain tick since ISSUE 15), which re-proposes it at a
        # fresh sequence: measured duplicate delivery under spurious-depose
        # churn at deep overload (mux ShardStreamViolation at 1600/s).
        view.in_flight_requests = self.verifier.requests_from_proposal(proposal)
        view.my_proposal_sig = self.signer.sign_proposal(proposal, b"")
        view.last_broadcast_sent = Commit(
            view=view.number,
            seq=view.proposal_sequence,
            digest=proposal_digest(proposal),
            signature=Signature(
                signer=view.my_proposal_sig.signer,
                value=view.my_proposal_sig.value,
                msg=view.my_proposal_sig.msg,
            ),
        )

        loop = asyncio.get_running_loop()
        self._in_flight_decide = loop.create_future()
        self._in_flight_sync = loop.create_future()
        timeout_fut: asyncio.Future = loop.create_future()

        # wait two ticks before starting (viewchanger.go:1262-1264)
        ticks_before_start = 2
        started = False

        def on_tick(now: float) -> None:
            nonlocal ticks_before_start, started
            self._last_tick = now
            if not started:
                ticks_before_start -= 1
                if ticks_before_start <= 0:
                    started = True
                    self._in_flight_view = view
                    view.start()
                    self.logger.debugf(
                        "Node %d started a view %d for the in flight proposal",
                        self.self_id, view.number,
                    )
                return
            if self._check_if_timeout(now) and not timeout_fut.done():
                timeout_fut.set_result(True)

        self._in_flight_tick_cb = on_tick
        try:
            done, _ = await asyncio.wait(
                [self._in_flight_decide, self._in_flight_sync, timeout_fut],
                return_when=asyncio.FIRST_COMPLETED,
            )
            if self._in_flight_decide.done() and self._in_flight_decide.result():
                self.logger.infof(
                    "In-flight view %d with latest sequence %d has committed a decision",
                    view.number, view.proposal_sequence,
                )
                return True
            if self._in_flight_sync.done():
                self.logger.infof(
                    "In-flight view %d with latest sequence %d has asked to sync",
                    view.number, view.proposal_sequence,
                )
                return False
            self.logger.infof(
                "Timeout expired waiting on in-flight view %d to commit %d",
                view.number, view.proposal_sequence,
            )
            return False
        finally:
            self._in_flight_tick_cb = None
            self._in_flight_decide = None
            self._in_flight_sync = None
            if self._in_flight_view is not None:
                await self._in_flight_view.abort()
                self._in_flight_view = None
