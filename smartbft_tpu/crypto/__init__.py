"""TPU crypto plane: batched signature verification kernels in JAX.

This package is the point of the framework (SURVEY.md §7.6): the reference
verifies one commit signature per goroutine on the CPU
(/root/reference/internal/bft/view.go:537-541); here quorum signature checks
are accumulated and executed as one vmap'd/jit'd kernel launch on the TPU.

Layout:
  bignum.py   -- fixed-width big integers on 16-bit limbs (uint32 storage),
                 Montgomery arithmetic; dtype-safe on TPU (no 64-bit needed).
  p256.py     -- NIST P-256 ECDSA: complete-addition curve ops, batched verify.
  ed25519.py  -- Ed25519 EdDSA: unified twisted-Edwards ops, batched verify.
"""

from . import bignum  # noqa: F401
from . import ed25519  # noqa: F401
