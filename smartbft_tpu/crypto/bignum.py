"""Fixed-width big-integer arithmetic for TPU, on 16-bit limbs.

Design notes (TPU-first):

* A k-bit integer is stored little-endian as ``ceil(k/16)`` limbs of 16 bits
  each, in a ``uint32`` array whose last axis is the limb axis.  All ops are
  natively batched: any leading axes are batch axes, so a (B, n) array is a
  batch of B bignums and every primitive vectorizes on the VPU without
  ``vmap``.
* 16-bit limbs inside 32-bit lanes mean every partial product
  ``a_i * b_j <= (2^16-1)^2`` fits a uint32 lane, and a full schoolbook
  column (<= 2n terms of 16 bits) stays below 2^21 — so multiplication needs
  **no 64-bit arithmetic at all**.  TPUs have no native int64; this layout is
  why the kernels run at full VPU rate instead of through XLA's i64
  emulation.
* The only sequential parts are the carry/borrow chains, expressed as
  ``lax.scan`` along the limb axis (16-32 steps) while the batch dimension
  stays fully vectorized.
* Modular arithmetic is Montgomery-form (separated operand scanning: one
  full product, one low product by N', one full product by N).  The modulus
  is a Python int baked in at trace time via :class:`MontCtx`, so P-256's
  p and n, Ed25519's p and L, and BLS12-381's q all share this engine.

Replaces the host-language bigint the reference leans on implicitly via Go's
``crypto/ecdsa`` (/root/reference/internal/bft/view.go:537-541 is the
per-signature verify fan-out this engine batches).
"""

from __future__ import annotations

import numpy as np


import jax.numpy as jnp
from jax import lax

LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1
DTYPE = jnp.uint32

# Carry-chain scan unrolling (lax.scan unroll=N).  The chains are short
# (~25-50 steps) but appear inside every Montgomery op; for kernels whose
# scan bodies contain many of them (the pairing), unrolling trades while-loop
# count for straightline ops, which XLA often compiles much faster.
import os as _os

UNROLL = int(_os.environ.get("SMARTBFT_BN_UNROLL", "1") or "1")


# ---------------------------------------------------------------------------
# host <-> device conversion
# ---------------------------------------------------------------------------

def to_limbs(x: int, nlimbs: int) -> np.ndarray:
    """Python int -> little-endian 16-bit limb vector (numpy uint32)."""
    if x < 0:
        raise ValueError("negative")
    out = np.zeros(nlimbs, dtype=np.uint32)
    for i in range(nlimbs):
        out[i] = x & LIMB_MASK
        x >>= LIMB_BITS
    if x:
        raise ValueError("overflow: value does not fit in %d limbs" % nlimbs)
    return out


def from_limbs(arr) -> int:
    """Limb vector (1-D) -> Python int.  Host-side only."""
    a = np.asarray(arr, dtype=np.uint64)
    x = 0
    for i in range(a.shape[-1] - 1, -1, -1):
        x = (x << LIMB_BITS) | int(a[i])
    return x


def batch_to_limbs(xs, nlimbs: int) -> np.ndarray:
    """List of Python ints -> (B, nlimbs) uint32."""
    return np.stack([to_limbs(x, nlimbs) for x in xs])


# ---------------------------------------------------------------------------
# carry / borrow chains
#
# Two interchangeable implementations, selected by SMARTBFT_BN_CHAIN:
#   'prefix' (default) — Kogge–Stone carry-lookahead: two local
#     redistribution passes reduce every residual carry to 0/1, then a
#     log2(m)-step (generate, propagate) parallel prefix resolves them.
#     ~12 data-dependent levels instead of m sequential scan steps, and —
#     critically — NO while-loop in the HLO: graphs with hundreds of
#     Montgomery ops compile minutes faster on XLA:CPU (copy-insertion is
#     superlinear in while-op count) and the TPU VPU pipeline stays full.
#   'scan' — the original lax.scan along the limb axis (kept for A/B and
#     as a hedge against Mosaic/XLA regressions).
# ---------------------------------------------------------------------------

CHAIN = _os.environ.get("SMARTBFT_BN_CHAIN", "prefix")


def _shift_up(x, s: int):
    """Limb shift toward higher index along the last axis (zero fill)."""
    pad = [(0, 0)] * (x.ndim - 1) + [(s, 0)]
    return jnp.pad(x, pad)[..., : x.shape[-1]]


def _resolve_prefix(x, m: int):
    """Resolve 0/1 residual carries of ``x`` (values <= 2^16) via
    Kogge–Stone prefix over (generate, propagate); returns (limbs, carry)
    with carry the (...,) carry out of limb m-1."""
    g = x >> LIMB_BITS  # 0/1 by precondition
    b = x & LIMB_MASK
    p = (b == LIMB_MASK).astype(DTYPE)
    G, P = g, p
    s = 1
    while s < m:
        G = G | (P & _shift_up(G, s))
        P = P & _shift_up(P, s)
        s <<= 1
    return (b + _shift_up(G, 1)) & LIMB_MASK, G[..., m - 1]


def carry_propagate(cols, out_len: int):
    """Normalize column sums (< 2^31 each) into 16-bit limbs.

    ``cols``: (..., m) uint32.  Returns (..., out_len) with out_len >= m.
    Any final carry out of limb out_len-1 is DISCARDED: callers either
    bound their inputs so it is zero, or rely on the mod-2^(16*out_len)
    truncation (redc_cols' m-computation does this deliberately).
    """
    m = cols.shape[-1]
    if out_len > m:
        pad = [(0, 0)] * (cols.ndim - 1) + [(0, out_len - m)]
        cols = jnp.pad(cols, pad)
    if CHAIN == "prefix":
        x = cols
        # two local passes: 2^31 -> carries < 2^15 -> values <= 2^16,
        # residual carries in {0, 1}
        for _ in range(2):
            x = (x & LIMB_MASK) + _shift_up(x >> LIMB_BITS, 1)
        limbs, _ = _resolve_prefix(x, out_len)
        return limbs
    x = jnp.moveaxis(cols, -1, 0)  # (out_len, ...)

    def step(c, col):
        t = col + c
        return t >> LIMB_BITS, t & LIMB_MASK

    _, limbs = lax.scan(step, jnp.zeros(x.shape[1:], DTYPE), x, unroll=UNROLL)
    return jnp.moveaxis(limbs, 0, -1)


def sub_borrow(a, b):
    """(a - b) mod 2^(16n) limb-wise; returns (diff, borrow_out).

    borrow_out is (...,) uint32: 1 when a < b.
    """
    if CHAIN == "prefix":
        b = jnp.broadcast_to(b, a.shape)
        n = a.shape[-1]
        # a - b = a + ~b + 1 (two's complement); carry-out <=> a >= b
        x = a + (jnp.uint32(LIMB_MASK) - b)
        x = jnp.concatenate(
            [x[..., :1] + jnp.uint32(1), x[..., 1:]], axis=-1
        )
        # one local pass: values < 2^17 -> <= 2^16, residual carries 0/1.
        # The top limb's local carry leaves the array here — it IS a carry
        # out of limb n-1, so it joins the prefix stage's (at most one of
        # the two can be set: the true carry-out is a single bit).
        hi = x >> LIMB_BITS
        x = (x & LIMB_MASK) + _shift_up(hi, 1)
        diff, carry = _resolve_prefix(x, n)
        return diff, jnp.uint32(1) - (carry | hi[..., n - 1])
    xa = jnp.moveaxis(a, -1, 0)
    xb = jnp.moveaxis(jnp.broadcast_to(b, a.shape), -1, 0)

    def step(borrow, ab):
        ai, bi = ab
        t = ai + jnp.uint32(1 << LIMB_BITS) - bi - borrow
        return jnp.uint32(1) - (t >> LIMB_BITS), t & LIMB_MASK

    borrow, limbs = lax.scan(
        step, jnp.zeros(xa.shape[1:], DTYPE), (xa, xb), unroll=UNROLL
    )
    return jnp.moveaxis(limbs, 0, -1), borrow


def geq(a, b):
    """a >= b as (...,) uint32 0/1."""
    _, borrow = sub_borrow(a, b)
    return jnp.uint32(1) - borrow


def select(mask, a, b):
    """mask ? a : b, broadcasting a (...,) mask over the limb axis."""
    return jnp.where(mask[..., None].astype(bool), a, b)


def is_zero(a):
    """(...,) uint32 1 if the bignum is zero."""
    return (jnp.max(a, axis=-1) == 0).astype(DTYPE)


def eq(a, b):
    """(...,) uint32 1 if equal limb-wise."""
    return jnp.all(a == b, axis=-1).astype(DTYPE)


def bits_msb(a, nbits: int):
    """Bit decomposition, most-significant first: (..., n) -> (..., nbits)."""
    idx = np.arange(nbits - 1, -1, -1)
    limb = idx // LIMB_BITS
    off = idx % LIMB_BITS
    return (a[..., limb] >> jnp.asarray(off, DTYPE)) & jnp.uint32(1)


def grouped(op, pairs):
    """Run independent binary field ops as ONE stacked call.

    The Montgomery ops' sequential carry chains broadcast over leading
    axes, so stacking k independent (a, b) pairs along a new axis shares
    the chains: k ops for the sequential cost of one.  This is the
    level-scheduling primitive behind the fast curve formulas.
    """
    shape = jnp.broadcast_shapes(*(jnp.shape(x) for pr in pairs for x in pr))
    a = jnp.stack([jnp.broadcast_to(x, shape) for x, _ in pairs])
    b = jnp.stack([jnp.broadcast_to(y, shape) for _, y in pairs])
    out = op(a, b)
    return tuple(out[i] for i in range(len(pairs)))


def grouped1(op, items):
    """Unary sibling of :func:`grouped` — k independent one-operand ops
    (squarings, negations) stacked into one call sharing the carry chains."""
    shape = jnp.broadcast_shapes(*(jnp.shape(x) for x in items))
    a = jnp.stack([jnp.broadcast_to(x, shape) for x in items])
    out = op(a)
    return tuple(out[i] for i in range(len(items)))


def digits_msb(a, ndigits: int, width: int = 2):
    """Fixed-width digit decomposition, most-significant digit first.

    (..., n) -> (..., ndigits), each digit in [0, 2**width).
    """
    bits = bits_msb(a, ndigits * width)
    bits = bits.reshape(bits.shape[:-1] + (ndigits, width))
    weights = jnp.asarray([1 << (width - 1 - k) for k in range(width)], DTYPE)
    return jnp.sum(bits * weights, axis=-1, dtype=DTYPE)


def joint_table(point_add, ps, qs):
    """Cross-join table for :func:`shamir_scan_w`: entry len(qs)*i + j is
    ps[i] + qs[j], all combination adds in ONE stacked point_add call."""
    lhs = jnp.stack([p for p in ps for _ in qs], axis=-3)
    rhs = jnp.stack([q for _ in ps for q in qs], axis=-3)
    return point_add(lhs, rhs)


def shamir_scan_w(point_add, table, ident, d1, d2, width: int = 2,
                  point_double=None):
    """Windowed Strauss–Shamir double-scalar mult.

    Per digit: ``width`` doublings + one gather + one addition — for w=2
    that is 3 point ops per 2 bits versus 4 for the bitwise scan, 25%
    fewer sequential point operations.  ``table`` is (..., 4**width, C, n)
    with entry i * 2**width + j holding i*P1 + j*P2; d1/d2 are
    (..., ndigits) MSB-first digits from :func:`digits_msb`.
    ``point_add`` must be complete (identity-safe); ``point_double``, when
    given, must be a complete dedicated doubling (cheaper than the general
    addition — squarings replace cross products).
    """
    dbl = point_double if point_double is not None else (
        lambda p: point_add(p, p))
    xs = (jnp.moveaxis(d1, -1, 0), jnp.moveaxis(d2, -1, 0))
    base = jnp.uint32(1 << width)

    def step(acc, ds):
        i, j = ds
        for _ in range(width):
            acc = dbl(acc)
        idx = (i * base + j).astype(jnp.int32)
        sel = jnp.take_along_axis(
            table, idx[..., None, None, None], axis=-3
        )[..., 0, :, :]
        return point_add(acc, sel), None

    acc, _ = lax.scan(step, ident, xs)
    return acc


def shamir_scan(point_add, table, ident, bits1, bits2):
    """Strauss–Shamir double-scalar-mult scan shared by every curve.

    Per bit: one doubling + one gather from ``table`` (shape (..., 4, C, n),
    entries [ident, P1, P2, P1+P2]) + one addition.  ``bits1``/``bits2`` are
    (..., nbits) MSB-first; points are (..., C, n) for any coordinate count C.
    ``point_add`` must be complete (identity-safe) — no branches are emitted.
    """
    xs = (jnp.moveaxis(bits1, -1, 0), jnp.moveaxis(bits2, -1, 0))

    def step(acc, bits):
        b1, b2 = bits
        acc = point_add(acc, acc)
        idx = (b1 + 2 * b2).astype(DTYPE)
        sel = jnp.take_along_axis(
            table, idx[..., None, None, None].astype(jnp.int32), axis=-3
        )[..., 0, :, :]
        return point_add(acc, sel), None

    acc, _ = lax.scan(step, ident, xs)
    return acc


# ---------------------------------------------------------------------------
# multiplication
# ---------------------------------------------------------------------------

def _put(x, off: int, total: int):
    """Zero-pad ``x`` to ``total`` columns with ``off`` leading zeros.

    The pad+add accumulation primitive (mirrors pallas_ecdsa._pad_rows):
    scatter-free HLO, since XLA:CPU expands ``.at[].add`` scatters into
    slow-to-compile, slow-to-run update loops."""
    pad = [(0, 0)] * (x.ndim - 1) + [(off, total - off - x.shape[-1])]
    return jnp.pad(x, pad)


def mul_columns(a, b):
    """Raw product columns: (..., n) x (..., n) -> (..., 2n) UNNORMALIZED.

    Schoolbook via shift-accumulate, WITHOUT the carry chain — zero
    sequential ops.  Row i of partial products lands in columns [i, i+n);
    each 32-bit product is split into 16-bit halves before accumulation, so
    column sums stay < 2^22; callers may add up to ~2^7 such column arrays
    together before normalizing (uint32 headroom), which is the basis of
    the lazy-reduction tower arithmetic: linear combinations of products
    cost vector adds only, and one carry chain + one Montgomery reduction
    amortizes over the whole combination.
    """
    n = a.shape[-1]
    bshape = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    acc = jnp.zeros(bshape + (2 * n,), DTYPE)
    for i in range(n):
        p = a[..., i : i + 1] * b
        acc = acc + _put(p & LIMB_MASK, i, 2 * n) + _put(
            p >> LIMB_BITS, i + 1, 2 * n
        )
    return acc


def mul_columns_low(a, b):
    """Low-n product columns only: a*b mod 2^(16n), unnormalized.

    The Montgomery m-step (m = T_lo * N' mod R) discards the high half of
    the product; skipping partial products landing at column >= n halves
    this step's lane-mult count."""
    n = a.shape[-1]
    bshape = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    acc = jnp.zeros(bshape + (n,), DTYPE)
    for i in range(n):
        p = a[..., i : i + 1] * b[..., : n - i]  # columns i..n-1
        acc = acc + _put(p & LIMB_MASK, i, n)
        if i + 1 < n:
            acc = acc + _put((p >> LIMB_BITS)[..., : n - i - 1], i + 1, n)
    return acc


def square_columns(a):
    """Raw squaring columns: (..., n) -> (..., 2n) UNNORMALIZED.

    Same contract as :func:`mul_columns` with b = a, but computes only the
    n(n+1)/2 upper-triangle partial products and weights the off-diagonal
    ones by 2 (the halves are doubled *after* the 16-bit split, so nothing
    overflows a uint32 lane) — 136 lane-mults instead of 256 at n = 16.
    Column sums stay < 2^23, well inside :func:`carry_propagate`'s budget,
    and the output is valid input for :meth:`MontCtx.redc_cols`.
    """
    n = a.shape[-1]
    acc = jnp.zeros(a.shape[:-1] + (2 * n,), DTYPE)
    for i in range(n):
        row = a[..., i : i + 1] * a[..., i:]  # j = i..n-1 -> column i+j
        w = np.full(n - i, 2, dtype=np.uint32)
        w[0] = 1  # the diagonal term a_i^2 counts once
        wj = jnp.asarray(w)
        acc = acc + _put((row & LIMB_MASK) * wj, 2 * i, 2 * n) + _put(
            (row >> LIMB_BITS) * wj, 2 * i + 1, 2 * n
        )
    return acc


def mul_full(a, b):
    """Full product: (..., n) x (..., n) -> (..., 2n), normalized limbs."""
    n = a.shape[-1]
    return carry_propagate(mul_columns(a, b), 2 * n + 1)[..., : 2 * n]


def add_raw(a, b, out_len: int):
    """Plain (non-modular) limb addition with carry normalization."""
    m = max(a.shape[-1], b.shape[-1])
    pad_a = [(0, 0)] * (a.ndim - 1) + [(0, m - a.shape[-1])]
    pad_b = [(0, 0)] * (b.ndim - 1) + [(0, m - b.shape[-1])]
    cols = jnp.pad(a, pad_a) + jnp.pad(b, pad_b)
    return carry_propagate(cols, out_len)


# ---------------------------------------------------------------------------
# Montgomery context
# ---------------------------------------------------------------------------

class MontCtx:
    """Montgomery arithmetic mod an odd ``modulus`` over ``nlimbs`` limbs.

    All device methods accept/return (..., nlimbs) uint32 arrays in the
    Montgomery domain unless noted.  Constants are precomputed with Python
    ints at construction and baked into the trace as numpy constants.
    """

    def __init__(self, modulus: int, nlimbs: int):
        if modulus % 2 == 0:
            raise ValueError("modulus must be odd")
        self.modulus = modulus
        self.n = nlimbs
        R = 1 << (LIMB_BITS * nlimbs)
        if modulus >= R:
            raise ValueError("modulus too large for limb count")
        self.R = R
        self.N = to_limbs(modulus, nlimbs)
        self.N_ext = to_limbs(modulus, nlimbs + 1)
        self.R2 = to_limbs((R * R) % modulus, nlimbs)
        self.Nprime = to_limbs((-pow(modulus, -1, R)) % R, nlimbs)
        self.one_mont = to_limbs(R % modulus, nlimbs)  # 1 in Mont domain
        self.zero = to_limbs(0, nlimbs)

    # -- domain conversion --------------------------------------------------

    def to_mont(self, a):
        return self.mul(a, jnp.asarray(self.R2))

    def from_mont(self, a):
        return self.mul(a, jnp.asarray(to_limbs(1, self.n)))

    def encode(self, x: int) -> np.ndarray:
        """Host: Python int -> Montgomery-domain limbs (numpy)."""
        return to_limbs((x * self.R) % self.modulus, self.n)

    def decode(self, arr) -> int:
        """Host: Montgomery-domain limbs -> Python int."""
        return (from_limbs(arr) * pow(self.R, -1, self.modulus)) % self.modulus

    # -- core ops -----------------------------------------------------------

    def mul(self, a, b):
        """Montgomery product a*b*R^-1 mod N — the k=1 case of
        :meth:`redc_cols`: 4 sequential carry chains instead of the naive
        five (three normalized mul_fulls + accumulate + subtract)."""
        return self.redc_cols(mul_columns(a, b))

    def square(self, a):
        """Montgomery square via :func:`square_columns` — ~47% fewer lane
        mults than :meth:`mul`; same 4 sequential carry chains."""
        return self.redc_cols(square_columns(a))

    def redc_cols(self, cols):
        """Montgomery-reduce raw product columns: (..., 2n) -> (..., n) < N.

        ``cols`` is a sum of k column arrays from :func:`mul_columns` over
        operands < N, with k strictly less than R/N — the exact requirement
        is k * N^2 < R * N, i.e. the summed value T < R*N.  (For BLS12-381
        with R = 2^384, R/P is ~9.84, so k <= 9 is safe even though
        floor(R/P) = 9.)
        Output is (T + mN)/R mod N, strictly < N after one conditional
        subtract.  Exactly 4 sequential chains regardless of how many
        outputs are stacked in the leading axes — the whole point.
        """
        n = self.n
        T = carry_propagate(cols, 2 * n + 1)
        m = mul_columns_low(T[..., :n], jnp.asarray(self.Nprime))
        m = carry_propagate(m, n)  # low n limbs: mod R
        s = carry_propagate(
            jnp.pad(T, [(0, 0)] * (T.ndim - 1) + [(0, 1)])
            + jnp.pad(mul_columns(m, jnp.asarray(self.N)),
                      [(0, 0)] * (T.ndim - 1) + [(0, 2)]),
            2 * n + 2,
        )
        r = s[..., n : 2 * n + 1]  # (..., n+1), value < 2N
        d, borrow = sub_borrow(r, jnp.asarray(self.N_ext))
        return select(borrow, r, d)[..., :n]

    def add(self, a, b):
        s = add_raw(a, b, self.n + 1)
        d, borrow = sub_borrow(s, jnp.asarray(self.N_ext))
        return select(borrow, s, d)[..., : self.n]

    def sub(self, a, b):
        d, borrow = sub_borrow(a, b)
        wrapped = add_raw(d, jnp.asarray(self.N), self.n + 1)[..., : self.n]
        return select(borrow, wrapped, d)

    def neg(self, a):
        """-a mod N (a in [0, N))."""
        d, _ = sub_borrow(jnp.broadcast_to(jnp.asarray(self.N), a.shape), a)
        return select(is_zero(a), a, d)

    def dbl(self, a):
        return self.add(a, a)

    def reduce_once(self, a):
        """One conditional subtract: a in [0, 2N) -> a mod N."""
        d, borrow = sub_borrow(a, jnp.asarray(self.N))
        return select(borrow, a, d)

    # -- exponentiation (static exponent) ------------------------------------

    def exp(self, a, e: int, window: int = 4):
        """a^e mod N for a *static* Python-int exponent; a in Mont domain.

        Fixed-window exponentiation as a ``lax.scan`` over the exponent's
        base-2^w digits (MSB first): w cheap squarings + one gather from
        the 2^w-entry power table + one multiply per digit.  Digit 0
        gathers a^0 = 1~ whose Montgomery product is the identity, so the
        body needs no select.  Versus bitwise square-and-multiply this
        trades 256 always-on multiplies for ~64 + a 14-mult table build.
        """
        if e < 0:
            raise ValueError("negative exponent")
        one = jnp.broadcast_to(jnp.asarray(self.one_mont), a.shape)
        if e == 0:
            return one
        if e.bit_length() <= window:  # tiny exponent: straightline
            out = a
            for bit in bin(e)[3:]:
                out = self.square(out)
                if bit == "1":
                    out = self.mul(out, a)
            return out

        # power table a^0 .. a^(2^w - 1), built in log depth with grouped
        # calls: each round squares/multiplies everything derivable so far.
        pows: list = [one, a]
        while len(pows) < (1 << window):
            have = len(pows)
            take = min(have - 1, (1 << window) - have)
            new = grouped(self.mul, [(pows[have - 1], pows[i + 1])
                                     for i in range(take)])
            pows.extend(new)
        table = jnp.stack(pows, axis=-2)  # (..., 2^w, n)

        ndig = (e.bit_length() + window - 1) // window
        digs = np.array(
            [(e >> (window * i)) & ((1 << window) - 1)
             for i in range(ndig - 1, -1, -1)], dtype=np.int32,
        )

        def step(acc, dig):
            for _ in range(window):
                acc = self.square(acc)
            sel = jnp.take(table, dig, axis=-2)  # digit is batch-uniform
            return self.mul(acc, sel), None

        # first digit is nonzero (e > 0): seed with its table entry
        acc0 = jnp.broadcast_to(table[..., int(digs[0]), :], a.shape)
        out, _ = lax.scan(step, acc0, jnp.asarray(digs[1:]))
        return out

    def inv(self, a):
        """a^-1 mod N via Fermat (N must be prime); Mont domain in/out."""
        return self.exp(a, self.modulus - 2)
