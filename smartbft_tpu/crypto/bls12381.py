"""BLS12-381 aggregate signatures with a batched TPU pairing kernel.

The reference library verifies each consenter signature independently on the
CPU (/root/reference/internal/bft/view.go:537-541 — one goroutine per commit
vote).  BLS aggregation collapses an entire Prepare/Commit quorum into ONE
pairing equation — the BASELINE.md "BLS12-381 aggregate (1 pairing/quorum)"
configuration:

    e(agg_sig, -g2) * e(H(m), agg_pk) == 1
    agg_sig = sum sig_i  (G1),  agg_pk = sum pk_i  (G2)

Scheme: "min-sig" — signatures in G1 (96B uncompressed), public keys in G2
(192B uncompressed).  Same-message aggregation only, which is exactly the
quorum shape (every vote signs the same proposal digest).

Design (TPU-first):

* The Fp2/Fp6/Fp12 tower, the Miller loop steps, and the final
  exponentiation are written ONCE, generically over a field "backend".
  The host backend computes with Python ints (reference + signing path);
  the device backend computes with the 16-bit-limb Montgomery engine
  (:mod:`smartbft_tpu.crypto.bignum`), fully batched — so the device kernel
  is the same audited formulas, retraced onto arrays.
* Tower: Fp2 = Fp[u]/(u^2+1), Fp6 = Fp2[v]/(v^3 - (u+1)),
  Fp12 = Fp6[w]/(w^2 - v).  The curve twist E'/Fp2: y^2 = x^3 + 4(u+1) is
  an M-twist; untwisting scales lines by powers of w, and every line is
  normalized by w^3 — a factor in the Fp4 subfield Fp2(w^3), killed by the
  easy part of the final exponentiation.
* Miller loop: projective (Jacobian) G2 arithmetic over Fp2, no inversions;
  line(P) = l00 + (lx * xP) v + (ly * yP) vw.  The -g2 loop's line
  coefficients are all precomputed on the host (g2 is fixed), so per batch
  lane the device runs one variable-Q loop and one table-driven loop fused
  into a single shared Miller accumulator.
* Final exponentiation: easy part (p^6-1)(p^2+1) via conjugation, one
  inversion, and Frobenius; hard part via the BLS12 identity
  (p^4-p^2+1)/r = (x-1)^2 (x+p) (x^2+p^2-1) + 3 — five 64-bit
  exponentiations by |x| instead of one 4600-bit exponentiation.

Host-side checks (on-curve + r-torsion subgroup) run at marshalling time;
the device evaluates the pairing equation itself.
"""

from __future__ import annotations

import functools
import hashlib
import secrets

import numpy as np

# ---------------------------------------------------------------------------
# curve constants
# ---------------------------------------------------------------------------

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R_ORDER = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
X_ABS = 0xD201000000010000  # |x|; the BLS parameter x is -X_ABS
B1 = 4

G1X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

G2X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

H1_COFACTOR = 0x396C8C005555E1568C00AAAB0000AAAB

FP_BYTES = 48
SIG_BYTES = 2 * FP_BYTES       # G1 affine uncompressed: x || y
PUB_BYTES = 4 * FP_BYTES       # G2 affine uncompressed: x0 || x1 || y0 || y1

NLIMBS = 24  # 384 bits of 16-bit limbs holds the 381-bit field


# ---------------------------------------------------------------------------
# field backends
#
# A backend provides Fp arithmetic; the tower above it is backend-generic.
# Elements of the host backend are Python ints; elements of the device
# backend are (..., NLIMBS) uint32 arrays in the Montgomery domain.
# ---------------------------------------------------------------------------

class HostFp:
    """Python-int Fp arithmetic (reference, signing, and precompute path)."""

    def add(self, a, b):
        return (a + b) % P

    def sub(self, a, b):
        return (a - b) % P

    def mul(self, a, b):
        return (a * b) % P

    def sqr(self, a):
        return (a * a) % P

    def neg(self, a):
        return (-a) % P

    def inv(self, a):
        return pow(a, P - 2, P)

    def small(self, k: int, a):
        return (k * a) % P

    def zero(self, like=None):
        return 0

    def one(self, like=None):
        return 1

    def const(self, x: int, like=None):
        return x % P


HOST = HostFp()


# -- Fp2 --------------------------------------------------------------------

def fp2_add(F, a, b):
    return (F.add(a[0], b[0]), F.add(a[1], b[1]))


def fp2_sub(F, a, b):
    return (F.sub(a[0], b[0]), F.sub(a[1], b[1]))


def fp2_neg(F, a):
    return (F.neg(a[0]), F.neg(a[1]))


def fp2_conj(F, a):
    return (a[0], F.neg(a[1]))


def fp2_mul(F, a, b):
    """Karatsuba: 3 Fp mults.  (a0+a1 u)(b0+b1 u), u^2 = -1."""
    t0 = F.mul(a[0], b[0])
    t1 = F.mul(a[1], b[1])
    t2 = F.mul(F.add(a[0], a[1]), F.add(b[0], b[1]))
    return (F.sub(t0, t1), F.sub(t2, F.add(t0, t1)))


def fp2_sqr(F, a):
    """(a0+a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u — 2 Fp mults."""
    t0 = F.mul(F.add(a[0], a[1]), F.sub(a[0], a[1]))
    t1 = F.mul(a[0], a[1])
    return (t0, F.add(t1, t1))


def fp2_small(F, k, a):
    return (F.small(k, a[0]), F.small(k, a[1]))


def fp2_mul_fp(F, a, s):
    """Multiply an Fp2 element by an Fp scalar."""
    return (F.mul(a[0], s), F.mul(a[1], s))


def fp2_mul_xi(F, a):
    """Multiply by xi = 1 + u: (a0 - a1) + (a0 + a1) u."""
    return (F.sub(a[0], a[1]), F.add(a[0], a[1]))


def fp2_inv(F, a):
    d = F.inv(F.add(F.sqr(a[0]), F.sqr(a[1])))
    return (F.mul(a[0], d), F.neg(F.mul(a[1], d)))


def fp2_zero(F, like=None):
    return (F.zero(like), F.zero(like))


def fp2_one(F, like=None):
    return (F.one(like), F.zero(like))


def fp2_const(F, c, like=None):
    return (F.const(c[0], like), F.const(c[1], like))


# -- Fp6 = Fp2[v]/(v^3 - xi) ------------------------------------------------

def fp6_add(F, a, b):
    return tuple(fp2_add(F, x, y) for x, y in zip(a, b))


def fp6_sub(F, a, b):
    return tuple(fp2_sub(F, x, y) for x, y in zip(a, b))


def fp6_neg(F, a):
    return tuple(fp2_neg(F, x) for x in a)


def fp6_mul(F, a, b):
    """Schoolbook with xi-reduction: 6 Fp2 mults via Karatsuba-lite."""
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fp2_mul(F, a0, b0)
    t1 = fp2_mul(F, a1, b1)
    t2 = fp2_mul(F, a2, b2)
    # c0 = t0 + xi*((a1+a2)(b1+b2) - t1 - t2)
    s = fp2_mul(F, fp2_add(F, a1, a2), fp2_add(F, b1, b2))
    c0 = fp2_add(F, t0, fp2_mul_xi(F, fp2_sub(F, fp2_sub(F, s, t1), t2)))
    # c1 = (a0+a1)(b0+b1) - t0 - t1 + xi*t2
    s = fp2_mul(F, fp2_add(F, a0, a1), fp2_add(F, b0, b1))
    c1 = fp2_add(F, fp2_sub(F, fp2_sub(F, s, t0), t1), fp2_mul_xi(F, t2))
    # c2 = (a0+a2)(b0+b2) - t0 - t2 + t1
    s = fp2_mul(F, fp2_add(F, a0, a2), fp2_add(F, b0, b2))
    c2 = fp2_add(F, fp2_sub(F, fp2_sub(F, s, t0), t2), t1)
    return (c0, c1, c2)


def fp6_sqr(F, a):
    return fp6_mul(F, a, a)


def fp6_mul_v(F, a):
    """Multiply by v: (a0, a1, a2) -> (xi*a2, a0, a1)."""
    return (fp2_mul_xi(F, a[2]), a[0], a[1])


def fp6_inv(F, a):
    a0, a1, a2 = a
    c0 = fp2_sub(F, fp2_sqr(F, a0), fp2_mul_xi(F, fp2_mul(F, a1, a2)))
    c1 = fp2_sub(F, fp2_mul_xi(F, fp2_sqr(F, a2)), fp2_mul(F, a0, a1))
    c2 = fp2_sub(F, fp2_sqr(F, a1), fp2_mul(F, a0, a2))
    t = fp2_add(
        F,
        fp2_mul_xi(F, fp2_add(F, fp2_mul(F, a2, c1), fp2_mul(F, a1, c2))),
        fp2_mul(F, a0, c0),
    )
    ti = fp2_inv(F, t)
    return (fp2_mul(F, c0, ti), fp2_mul(F, c1, ti), fp2_mul(F, c2, ti))


def fp6_zero(F, like=None):
    return (fp2_zero(F, like),) * 3


def fp6_one(F, like=None):
    return (fp2_one(F, like), fp2_zero(F, like), fp2_zero(F, like))


# -- Fp12 = Fp6[w]/(w^2 - v) -------------------------------------------------

def fp12_mul(F, a, b):
    """(a0 + a1 w)(b0 + b1 w) = (a0 b0 + v a1 b1) + ((a0+a1)(b0+b1)-a0b0-a1b1) w."""
    t0 = fp6_mul(F, a[0], b[0])
    t1 = fp6_mul(F, a[1], b[1])
    t2 = fp6_mul(F, fp6_add(F, a[0], a[1]), fp6_add(F, b[0], b[1]))
    return (
        fp6_add(F, t0, fp6_mul_v(F, t1)),
        fp6_sub(F, fp6_sub(F, t2, t0), t1),
    )


def fp12_sqr(F, a):
    return fp12_mul(F, a, a)


def fp12_conj(F, a):
    """Conjugation = the p^6 Frobenius: a0 - a1 w.  For elements of the
    cyclotomic subgroup this is also the inverse."""
    return (a[0], fp6_neg(F, a[1]))


def fp12_inv(F, a):
    t = fp6_inv(F, fp6_sub(F, fp6_sqr(F, a[0]), fp6_mul_v(F, fp6_sqr(F, a[1]))))
    return (fp6_mul(F, a[0], t), fp6_neg(F, fp6_mul(F, a[1], t)))


def fp12_one(F, like=None):
    return (fp6_one(F, like), fp6_zero(F, like))


def fp12_eq_one_host(a) -> bool:
    return a == fp12_one(HOST)


# -- Frobenius ---------------------------------------------------------------

def _host_fp2_pow(a, e: int):
    """Fp2 exponentiation with Python ints (constant precompute only)."""
    result = (1, 0)
    base = a
    while e:
        if e & 1:
            result = fp2_mul(HOST, result, base)
        base = fp2_sqr(HOST, base)
        e >>= 1
    return result


#: gamma1 = xi^((p-1)/6), gamma2 = gamma1^2, used by the p-power Frobenius.
_G1F = _host_fp2_pow((1, 1), (P - 1) // 6)
_G2F = fp2_mul(HOST, _G1F, _G1F)
_G4F = fp2_mul(HOST, _G2F, _G2F)  # gamma2^2 = xi^(2(p-1)/3)


def fp12_frob(F, a, g1c, g2c, g4c):
    """The p-power Frobenius.  g1c/g2c/g4c are the backend-encoded gamma
    constants (host ints or device limb constants)."""
    (a0, a1, a2), (b0, b1, b2) = a
    a0 = fp2_conj(F, a0)
    a1 = fp2_mul(F, fp2_conj(F, a1), g2c)
    a2 = fp2_mul(F, fp2_conj(F, a2), g4c)
    b0 = fp2_mul(F, fp2_conj(F, b0), g1c)
    b1 = fp2_mul(F, fp2_conj(F, b1), fp2_mul(F, g1c, g2c))
    b2 = fp2_mul(F, fp2_conj(F, b2), fp2_mul(F, g1c, g4c))
    return ((a0, a1, a2), (b0, b1, b2))


# ---------------------------------------------------------------------------
# G1 / G2 host arithmetic (Python ints, Jacobian coordinates)
# ---------------------------------------------------------------------------

def _jac_dbl(F, pt, fp_sqr, fp_mul, fp_add, fp_sub, fp_small):
    X, Y, Z = pt
    A = fp_sqr(F, X)
    Bv = fp_sqr(F, Y)
    C = fp_sqr(F, Bv)
    D = fp_sub(F, fp_sqr(F, fp_add(F, X, Bv)), fp_add(F, A, C))
    D = fp_add(F, D, D)
    E = fp_add(F, fp_add(F, A, A), A)
    Fv = fp_sqr(F, E)
    X3 = fp_sub(F, Fv, fp_add(F, D, D))
    C8 = fp_small(F, 8, C)
    Y3 = fp_sub(F, fp_mul(F, E, fp_sub(F, D, X3)), C8)
    Z3 = fp_mul(F, fp_add(F, Y, Y), Z)
    return (X3, Y3, Z3)


def _g1_dbl(pt):
    return _jac_dbl(
        HOST, pt,
        lambda F, a: F.sqr(a), lambda F, a, b: F.mul(a, b),
        lambda F, a, b: F.add(a, b), lambda F, a, b: F.sub(a, b),
        lambda F, k, a: F.small(k, a),
    )


def _g2_dbl(pt):
    return _jac_dbl(HOST, pt, fp2_sqr, fp2_mul, fp2_add, fp2_sub, fp2_small)


def _jac_add_generic(F, p1, p2, sqr, mul, add, sub, small, zero_pred):
    """Full Jacobian addition (host only; branches allowed)."""
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    if zero_pred(Z1):
        return p2
    if zero_pred(Z2):
        return p1
    Z1Z1 = sqr(F, Z1)
    Z2Z2 = sqr(F, Z2)
    U1 = mul(F, X1, Z2Z2)
    U2 = mul(F, X2, Z1Z1)
    S1 = mul(F, Y1, mul(F, Z2, Z2Z2))
    S2 = mul(F, Y2, mul(F, Z1, Z1Z1))
    if U1 == U2:
        if S1 == S2:
            return _jac_dbl(F, p1, sqr, mul, add, sub, small)
        return None  # point at infinity
    H = sub(F, U2, U1)
    Rr = sub(F, S2, S1)
    H2 = sqr(F, H)
    H3 = mul(F, H, H2)
    U1H2 = mul(F, U1, H2)
    X3 = sub(F, sub(F, sqr(F, Rr), H3), add(F, U1H2, U1H2))
    Y3 = sub(F, mul(F, Rr, sub(F, U1H2, X3)), mul(F, S1, H3))
    Z3 = mul(F, mul(F, Z1, Z2), H)
    return (X3, Y3, Z3)


def _g1_add(p1, p2):
    r = _jac_add_generic(
        HOST, p1, p2,
        lambda F, a: F.sqr(a), lambda F, a, b: F.mul(a, b),
        lambda F, a, b: F.add(a, b), lambda F, a, b: F.sub(a, b),
        lambda F, k, a: F.small(k, a), lambda z: z == 0,
    )
    return (1, 1, 0) if r is None else r


def _g2_add(p1, p2):
    r = _jac_add_generic(
        HOST, p1, p2, fp2_sqr, fp2_mul, fp2_add, fp2_sub, fp2_small,
        lambda z: z == (0, 0),
    )
    return ((1, 0), (1, 0), (0, 0)) if r is None else r


def _scalar_mult(k: int, pt, dbl, add, inf):
    acc = inf
    q = pt
    while k:
        if k & 1:
            acc = add(acc, q)
        q = dbl(q)
        k >>= 1
    return acc


def _native_bls():
    """The C++ group-arithmetic backend (native/bls381.cc), or None.

    Signing was ~20 ms and aggregation ~63 Python point-adds per quorum
    check in pure ints — the measured reason round 2's BLS cluster row
    could not be deployed.  The native path is ~10x; the Python path
    remains both the fallback and the cross-check oracle."""
    from .. import native

    return native if native.bls_available() else None


def g1_scalar_mult(k: int, affine):
    """k*P, k taken AS GIVEN — no mod-r reduction, because subgroup checks
    multiply by r itself and points may lie outside the r-torsion."""
    nat = _native_bls()
    if nat is not None:
        return nat.bls_g1_mul(k, affine)
    pt = (affine[0], affine[1], 1)
    X, Y, Z = _scalar_mult(k, pt, _g1_dbl, _g1_add, (1, 1, 0))
    return _g1_to_affine((X, Y, Z))


def g2_scalar_mult(k: int, affine):
    nat = _native_bls()
    if nat is not None:
        return nat.bls_g2_mul(k, affine)
    pt = (affine[0], affine[1], (1, 0))
    res = _scalar_mult(k, pt, _g2_dbl, _g2_add, ((1, 0), (1, 0), (0, 0)))
    return _g2_to_affine(res)


def _g1_to_affine(pt):
    X, Y, Z = pt
    if Z == 0:
        return None  # infinity
    zi = pow(Z, P - 2, P)
    zi2 = zi * zi % P
    return (X * zi2 % P, Y * zi2 % P * zi % P)


def _g2_to_affine(pt):
    X, Y, Z = pt
    if Z == (0, 0):
        return None
    zi = fp2_inv(HOST, Z)
    zi2 = fp2_sqr(HOST, zi)
    return (fp2_mul(HOST, X, zi2), fp2_mul(HOST, Y, fp2_mul(HOST, zi2, zi)))


def g1_add_affine(a1, a2):
    """Affine G1 addition (None = infinity)."""
    if a1 is None:
        return a2
    if a2 is None:
        return a1
    return _g1_to_affine(_g1_add((a1[0], a1[1], 1), (a2[0], a2[1], 1)))


def g2_add_affine(a1, a2):
    if a1 is None:
        return a2
    if a2 is None:
        return a1
    return _g2_to_affine(
        _g2_add((a1[0], a1[1], (1, 0)), (a2[0], a2[1], (1, 0)))
    )


def g1_on_curve(pt) -> bool:
    x, y = pt
    return y * y % P == (x * x % P * x + B1) % P


def g2_on_curve(pt) -> bool:
    x, y = pt
    rhs = fp2_add(HOST, fp2_mul(HOST, fp2_sqr(HOST, x), x), fp2_mul_xi(HOST, (B1, 0)))
    return fp2_sqr(HOST, y) == rhs


def g1_in_subgroup(pt) -> bool:
    return g1_scalar_mult(R_ORDER, pt) is None


def g2_in_subgroup(pt) -> bool:
    return g2_scalar_mult(R_ORDER, pt) is None


# ---------------------------------------------------------------------------
# hash to G1 (deterministic try-and-increment + cofactor clearing)
#
# Not RFC 9380 (whose SSWU map would also work); this framework defines its
# own wire format, and try-and-increment is deterministic, uniform enough,
# and runs once per proposal digest on the host — the pairing is the
# device-side cost.
# ---------------------------------------------------------------------------

_SQRT_EXP = (P + 1) // 4  # p = 3 mod 4


@functools.lru_cache(maxsize=4096)
def hash_to_g1(msg: bytes):
    ctr = 0
    while True:
        t = hashlib.sha256(b"smartbft-bls12381-g1" + ctr.to_bytes(4, "big") + msg).digest()
        t2 = hashlib.sha256(b"smartbft-bls12381-g1b" + ctr.to_bytes(4, "big") + msg).digest()
        x = int.from_bytes(t + t2[:16], "big") % P
        rhs = (x * x % P * x + B1) % P
        y = pow(rhs, _SQRT_EXP, P)
        if y * y % P == rhs:
            if (t2[16] & 1) != (y & 1):
                y = P - y
            pt = g1_scalar_mult(H1_COFACTOR, (x, y))
            if pt is not None:
                return pt
        ctr += 1


# ---------------------------------------------------------------------------
# Miller loop (generic over backend) and final exponentiation
# ---------------------------------------------------------------------------

_X_BITS = [(X_ABS >> i) & 1 for i in range(X_ABS.bit_length() - 2, -1, -1)]
_XP1_BITS = [((X_ABS + 1) >> i) & 1 for i in range((X_ABS + 1).bit_length() - 1, -1, -1)]


def _line_to_fp12(F, l00, lx, ly, like=None):
    """Assemble the (scaled) line l00 + lx*v + ly*vw as a full Fp12 element.

    Derivation (module docstring): untwisting scales x by w^-2 and y by
    w^-3; multiplying the affine line by w^3 leaves components at w^0 (Fp2),
    w^2 = v, and w^3 = vw.  The w^3 normalization lies in Fp2(w^3) = Fp4 and
    is erased by the easy final exponentiation.
    """
    z = fp2_zero(F, like)
    return ((l00, lx, z), (z, ly, z))


def _dbl_step(F, T):
    """One Miller doubling: T <- 2T on the twist.

    Returns (T', raw line coeffs (l00, lxc, lyc)); the caller scales
    lxc by xP and lyc by yP.  Line (scaled by the Fp2 factor 2YZ^3,
    erased by the final exp):
      l00 = 3X^3 - 2Y^2,  lxc = -3 X^2 Z^2,  lyc = 2 Y Z^3
    """
    X, Y, Z = T
    X2 = fp2_sqr(F, X)
    Y2 = fp2_sqr(F, Y)
    Z2 = fp2_sqr(F, Z)
    X2_3 = fp2_add(F, fp2_add(F, X2, X2), X2)
    l00 = fp2_sub(F, fp2_mul(F, X2_3, X), fp2_add(F, Y2, Y2))
    lxc = fp2_neg(F, fp2_mul(F, X2_3, Z2))
    YZ = fp2_mul(F, Y, Z)
    lyc = fp2_mul(F, fp2_add(F, YZ, YZ), Z2)
    # dbl-2007-b/l
    C = fp2_sqr(F, Y2)
    D = fp2_sub(F, fp2_sqr(F, fp2_add(F, X, Y2)), fp2_add(F, X2, C))
    D = fp2_add(F, D, D)
    Fv = fp2_sqr(F, X2_3)
    X3 = fp2_sub(F, Fv, fp2_add(F, D, D))
    Y3 = fp2_sub(F, fp2_mul(F, X2_3, fp2_sub(F, D, X3)), fp2_small(F, 8, C))
    Z3 = fp2_add(F, YZ, YZ)
    return (X3, Y3, Z3), (l00, lxc, lyc)


def _add_step(F, T, Q):
    """One Miller mixed addition: T <- T + Q (Q affine).

    With H = xq Z^2 - X, r = yq Z^3 - Y (line scaled by the Fp2 factor HZ):
      l00 = r*xq - HZ*yq,  lxc = -r,  lyc = HZ
    """
    X, Y, Z = T
    xq, yq = Q
    Z2 = fp2_sqr(F, Z)
    Z3c = fp2_mul(F, Z2, Z)
    H = fp2_sub(F, fp2_mul(F, xq, Z2), X)
    Rr = fp2_sub(F, fp2_mul(F, yq, Z3c), Y)
    HZ = fp2_mul(F, H, Z)
    l00 = fp2_sub(F, fp2_mul(F, Rr, xq), fp2_mul(F, HZ, yq))
    lxc = fp2_neg(F, Rr)
    lyc = HZ
    H2 = fp2_sqr(F, H)
    H3 = fp2_mul(F, H2, H)
    UH2 = fp2_mul(F, X, H2)
    X3 = fp2_sub(F, fp2_sub(F, fp2_sqr(F, Rr), H3), fp2_add(F, UH2, UH2))
    Y3 = fp2_sub(F, fp2_mul(F, Rr, fp2_sub(F, UH2, X3)), fp2_mul(F, Y, H3))
    Z3 = HZ
    return (X3, Y3, Z3), (l00, lxc, lyc)


def _scale_line(F, coeffs, xp, yp):
    l00, lxc, lyc = coeffs
    return (l00, fp2_mul_fp(F, lxc, xp), fp2_mul_fp(F, lyc, yp))


def host_miller_loop(p_affine, q_affine):
    """f_{|x|,Q}(P) conjugated (x < 0) — host ints.  P in G1, Q on the twist."""
    F = HOST
    xp, yp = p_affine
    T = (q_affine[0], q_affine[1], (1, 0))
    f = fp12_one(F)
    for bit in _X_BITS:
        f = fp12_sqr(F, f)
        T, coeffs = _dbl_step(F, T)
        f = fp12_mul(F, f, _line_to_fp12(F, *_scale_line(F, coeffs, xp, yp)))
        if bit:
            T, coeffs = _add_step(F, T, q_affine)
            f = fp12_mul(F, f, _line_to_fp12(F, *_scale_line(F, coeffs, xp, yp)))
    return fp12_conj(F, f)  # x < 0


def _cyclo_exp_abs(F, m, bits, g1c, g2c, g4c):
    """m^e for e = |x| or |x|+1 given MSB-first bits; m cyclotomic (host)."""
    acc = m
    for bit in bits[1:]:
        acc = fp12_sqr(F, acc)
        if bit:
            acc = fp12_mul(F, acc, m)
    return acc


def host_final_exp(f):
    """f^(3 (p^12-1)/r): easy part + the BLS12 hard-part identity
    3 (p^4-p^2+1)/r = (x-1)^2 (x+p) (x^2+p^2-1) + 3.

    The extra factor of 3 (coprime to r) yields the CUBE of the optimal ate
    pairing — itself a bilinear, non-degenerate pairing of order r, which is
    all the verification equation needs; skipping the cube root saves work
    (the common trick in production pairing code)."""
    F = HOST
    g1c, g2c, g4c = _G1F, _G2F, _G4F
    # easy: f <- f^(p^6-1), then f <- f^(p^2) * f  => f^((p^6-1)(p^2+1))
    f = fp12_mul(F, fp12_conj(F, f), fp12_inv(F, f))
    f = fp12_mul(F, fp12_frob(F, fp12_frob(F, f, g1c, g2c, g4c), g1c, g2c, g4c), f)
    m = f
    conj = lambda z: fp12_conj(F, z)
    expx = lambda z: conj(_cyclo_exp_abs(F, z, _X_BITS_FULL, g1c, g2c, g4c))
    expxm1 = lambda z: conj(_cyclo_exp_abs(F, z, _XP1_BITS, g1c, g2c, g4c))
    a = expxm1(m)                       # m^(x-1)
    a = expxm1(a)                       # m^((x-1)^2)
    b = expx(a)                         # a^x
    a = fp12_mul(F, b, fp12_frob(F, a, g1c, g2c, g4c))   # a^(x+p)
    c = expx(expx(a))                   # a^(x^2)
    a2 = fp12_frob(F, fp12_frob(F, a, g1c, g2c, g4c), g1c, g2c, g4c)
    a = fp12_mul(F, fp12_mul(F, c, a2), conj(a))         # a^(x^2+p^2-1)
    m3 = fp12_mul(F, fp12_mul(F, m, m), m)
    return fp12_mul(F, a, m3)


_X_BITS_FULL = [(X_ABS >> i) & 1 for i in range(X_ABS.bit_length() - 1, -1, -1)]

NEG_G2 = (G2X, fp2_neg(HOST, G2Y))


def host_pairing_check(pairs) -> bool:
    """prod e(P_i, Q_i) == 1, host ints.  pairs: [(G1 affine, twist affine)]."""
    f = fp12_one(HOST)
    for p_aff, q_aff in pairs:
        f = fp12_mul(HOST, f, host_miller_loop(p_aff, q_aff))
    return fp12_eq_one_host(host_final_exp(f))


# ---------------------------------------------------------------------------
# scheme API (host): keygen / sign / verify / aggregate
# ---------------------------------------------------------------------------

def _fp_to_bytes(x: int) -> bytes:
    return x.to_bytes(FP_BYTES, "big")


def _fp_from_bytes(b: bytes) -> int:
    x = int.from_bytes(b, "big")
    if x >= P:
        raise ValueError("field element out of range")
    return x


def serialize_g1(pt) -> bytes:
    return _fp_to_bytes(pt[0]) + _fp_to_bytes(pt[1])


def deserialize_g1(b: bytes):
    if len(b) != SIG_BYTES:
        raise ValueError("bad G1 encoding length")
    return (_fp_from_bytes(b[:FP_BYTES]), _fp_from_bytes(b[FP_BYTES:]))


def serialize_g2(pt) -> bytes:
    (x0, x1), (y0, y1) = pt
    return b"".join(_fp_to_bytes(v) for v in (x0, x1, y0, y1))


def deserialize_g2(b: bytes):
    if len(b) != PUB_BYTES:
        raise ValueError("bad G2 encoding length")
    v = [_fp_from_bytes(b[i * FP_BYTES:(i + 1) * FP_BYTES]) for i in range(4)]
    return ((v[0], v[1]), (v[2], v[3]))


def keygen(seed: bytes | None = None):
    """Returns (sk_int, pk_bytes).  pk = sk * g2, 192B uncompressed."""
    if seed is None:
        seed = secrets.token_bytes(32)
    sk = (
        int.from_bytes(hashlib.sha512(b"smartbft-bls-keygen" + seed).digest(), "big")
        % (R_ORDER - 1)
    ) + 1
    pk = g2_scalar_mult(sk, (G2X, G2Y))
    return sk, serialize_g2(pk)


def sign(sk: int, msg: bytes) -> bytes:
    """sig = sk * H(msg) in G1; 96B uncompressed.

    H(msg) is cofactor-cleared (r-torsion by construction), so the native
    GLV ladder is sound here — ~halves the doublings of the generic path
    (native/bls381.cc jac_mul_glv)."""
    if sk % R_ORDER == 0:
        # sk*h would be the point at infinity (rc==0 from the native ABI,
        # None from the software ladder) — unserializable and useless as a
        # signature; fail with a diagnosis instead of a TypeError downstream
        raise ValueError("BLS secret key is 0 mod r; refusing to sign")
    h = hash_to_g1(msg)
    nat = _native_bls()
    if nat is not None:
        pt = nat.bls_g1_mul_torsion(sk, h)
    else:
        pt = g1_scalar_mult(sk, h)
    if pt is None:  # h at infinity (negligible-probability hash output)
        raise ValueError("BLS signing produced the point at infinity")
    return serialize_g1(pt)


# Proof of possession: same-message ("fast") aggregate verification is only
# sound against rogue-key attacks (pk_B = b*g2 - pk_A lets B forge an
# aggregate containing a vote A never cast) when every registered public key
# has proven knowledge of its secret key — the PoP scheme of the IETF BLS
# draft.  The domain tag separates PoP messages from every consensus payload.
_POP_TAG = b"smartbft-bls12381-pop:"


def pop_prove(sk: int, pub: bytes) -> bytes:
    """Proof of possession for ``pub``: a signature over its own wire bytes."""
    return sign(sk, _POP_TAG + pub)


def pop_verify(pub: bytes, pop: bytes) -> bool:
    """Check a proof of possession produced by :func:`pop_prove`."""
    return verify_int(pub, _POP_TAG + pub, pop)


def keygen_with_pop(seed: bytes | None = None):
    """(sk, pk, pop) — keygen plus the proof of possession for pk."""
    sk, pk = keygen(seed)
    return sk, pk, pop_prove(sk, pk)


@functools.lru_cache(maxsize=1024)
def _checked_pub(pub: bytes):
    pk = deserialize_g2(pub)
    if not g2_on_curve(pk) or not g2_in_subgroup(pk):
        raise ValueError("public key not in G2")
    return pk


@functools.lru_cache(maxsize=4096)
def _checked_sig(sig: bytes):
    """Decode + on-curve + r-torsion check, memoized by wire bytes.

    The subgroup check is a full scalar-mult by r on the host; the cache
    means a signature relayed across paths (commit vote, ViewData last
    decision, aggregate-failure fallback lanes) pays it once.
    """
    pt = deserialize_g1(sig)
    if not g1_on_curve(pt) or not g1_in_subgroup(pt):
        raise ValueError("signature not in G1")
    return pt


def verify_int(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Single-signature verify, host ints: e(sig,-g2) e(H(m),pk) == 1."""
    try:
        pk = _checked_pub(pub)
        s = _checked_sig(sig)
    except ValueError:
        return False
    return host_pairing_check([(s, NEG_G2), (hash_to_g1(msg), pk)])


def aggregate_sigs(sigs) -> bytes:
    """Sum of G1 signatures (same-message aggregation)."""
    nat = _native_bls()
    if nat is not None:
        acc = nat.bls_g1_sum(deserialize_g1(sig) for sig in sigs)
    else:
        acc = None
        for sig in sigs:
            acc = g1_add_affine(acc, deserialize_g1(sig))
    if acc is None:
        raise ValueError("empty or cancelling aggregate")
    return serialize_g1(acc)


def aggregate_pubs(pubs) -> bytes:
    nat = _native_bls()
    if nat is not None:
        acc = nat.bls_g2_sum(deserialize_g2(pub) for pub in pubs)
    else:
        acc = None
        for pub in pubs:
            acc = g2_add_affine(acc, deserialize_g2(pub))
    if acc is None:
        raise ValueError("empty or cancelling aggregate")
    return serialize_g2(acc)


def aggregate_verify_int(pubs, msg: bytes, sigs) -> bool:
    """Whole-quorum verify with ONE pairing equation (host path)."""
    try:
        pks = [_checked_pub(p) for p in pubs]
        pts = [_checked_sig(s) for s in sigs]
    except ValueError:
        return False
    nat = _native_bls()
    if nat is not None:
        agg_sig = nat.bls_g1_sum(pts)
        agg_pk = nat.bls_g2_sum(pks)
    else:
        agg_sig = None
        for pt in pts:
            agg_sig = g1_add_affine(agg_sig, pt)
        agg_pk = None
        for pk in pks:
            agg_pk = g2_add_affine(agg_pk, pk)
    if agg_sig is None or agg_pk is None:
        return False
    return host_pairing_check([(agg_sig, NEG_G2), (hash_to_g1(msg), agg_pk)])


# ---------------------------------------------------------------------------
# provider-scheme glue (same surface as p256/ed25519 modules)
# ---------------------------------------------------------------------------

def sign_raw(sk, msg: bytes) -> bytes:
    return sign(sk, msg)


def make_item(msg: bytes, sig: bytes, pub: bytes):
    return (msg, sig, pub)


def verify_item(item) -> bool:
    msg, sig, pub = item
    return verify_int(pub, msg, sig)


# ---------------------------------------------------------------------------
# device backend: the same tower formulas over 16-bit-limb Montgomery arrays
# ---------------------------------------------------------------------------

import jax.numpy as jnp  # noqa: E402  (device section)
from jax import lax  # noqa: E402

from . import bignum as bn  # noqa: E402
from .bignum import MontCtx  # noqa: E402

CTX = MontCtx(P, NLIMBS)


class DeviceFp:
    """Backend over (..., NLIMBS) uint32 Montgomery-domain arrays; every op
    is natively batched over leading axes."""

    def __init__(self, ctx: MontCtx):
        self.ctx = ctx

    def add(self, a, b):
        return self.ctx.add(a, b)

    def sub(self, a, b):
        return self.ctx.sub(a, b)

    def mul(self, a, b):
        return self.ctx.mul(a, b)

    def sqr(self, a):
        return self.ctx.square(a)  # square_columns: ~47% fewer lane mults

    def neg(self, a):
        return self.ctx.neg(a)

    def inv(self, a):
        return self.ctx.inv(a)

    def small(self, k: int, a):
        acc = a
        for bit in bin(k)[3:]:  # skip leading 1
            acc = self.ctx.add(acc, acc)
            if bit == "1":
                acc = self.ctx.add(acc, a)
        return acc

    def zero(self, like=None):
        z = jnp.asarray(self.ctx.zero)
        return z if like is None else jnp.broadcast_to(z, like.shape)

    def one(self, like=None):
        o = jnp.asarray(self.ctx.one_mont)
        return o if like is None else jnp.broadcast_to(o, like.shape)

    def const(self, x: int, like=None):
        c = jnp.asarray(self.ctx.encode(x))
        return c if like is None else jnp.broadcast_to(c, like.shape)


DEV = DeviceFp(CTX)


def _tree_select(mask, a, b):
    """Elementwise select over matching nested tuples of limb arrays."""
    if isinstance(a, tuple):
        return tuple(_tree_select(mask, x, y) for x, y in zip(a, b))
    return bn.select(mask, a, b)


# -- stacked Fp12: (..., 12, NLIMBS) arrays ---------------------------------
#
# XLA compiles nested while-loops (the carry chains inside every Montgomery
# mult) far more slowly than data-parallel ops.  A naive port of the tower
# would emit ~330 sequential Fp mults per Miller step — thousands of nested
# loops.  Instead every INDEPENDENT Fp mult inside one Fp12 operation is
# gathered into a single batched Montgomery call over a stacked axis: one
# Fp12 mult = one (18-way) stacked Karatsuba Fp2 product + a handful of
# stacked add/sub chains, regardless of batch size.
#
# Row layout of a stacked element f = (a0 + a1 v + a2 v^2) + (b0 + ...) w:
#   rows 0..5  = a0re, a0im, a1re, a1im, a2re, a2im
#   rows 6..11 = b0re, b0im, b1re, b1im, b2re, b2im


def _stk_from_tuple(f):
    (a0, a1, a2), (b0, b1, b2) = f
    return jnp.stack(
        [a0[0], a0[1], a1[0], a1[1], a2[0], a2[1],
         b0[0], b0[1], b1[0], b1[1], b2[0], b2[1]], axis=-2
    )


def _stk_to_tuple(x):
    r = lambda i: x[..., i, :]
    return (
        ((r(0), r(1)), (r(2), r(3)), (r(4), r(5))),
        ((r(6), r(7)), (r(8), r(9)), (r(10), r(11))),
    )


def _stk_one(like):
    """1 in stacked form, broadcast to like's batch shape (like: (..., L))."""
    one = jnp.broadcast_to(jnp.asarray(CTX.one_mont), like.shape)
    zero = jnp.zeros_like(one)
    return jnp.stack([one] + [zero] * 11, axis=-2)


def _rows_mul(A, B):
    """Stacked Karatsuba Fp2 products: (..., K, 2, L) x (..., K, 2, L).

    3K Fp mults in ONE Montgomery call; 5 further stacked chains total.
    """
    ctx = CTX
    a0, a1 = A[..., 0, :], A[..., 1, :]
    b0, b1 = B[..., 0, :], B[..., 1, :]
    lhs = jnp.stack([a0, a1, ctx.add(a0, a1)], axis=-2)
    rhs = jnp.stack([b0, b1, ctx.add(b0, b1)], axis=-2)
    t = ctx.mul(lhs, rhs)
    t0, t1, t2 = t[..., 0, :], t[..., 1, :], t[..., 2, :]
    re = ctx.sub(t0, t1)
    im = ctx.sub(t2, ctx.add(t0, t1))
    return jnp.stack([re, im], axis=-2)


def _rows_xi(a):
    """xi * a for stacked fp2 rows (..., 2, L): (re - im, re + im)."""
    ctx = CTX
    re, im = a[..., 0, :], a[..., 1, :]
    return jnp.stack([ctx.sub(re, im), ctx.add(re, im)], axis=-2)


# -- tensor-driven Fp12 multiplication (lazy reduction) ----------------------
#
# The Fp12 multiplication tensor over the 12 Fp coordinates is generated once
# from the HOST tower (so it is correct by construction) as a static list of
# product slots (i, j, negate, output).  At runtime: gather operand rows, one
# batched mul_columns for ALL slots, vector-add columns per output into <= 9
# product buckets (the redc_cols bound), and ONE stacked Montgomery reduction
# for every output coordinate.  An Fp12 mult is ~8 sequential chains total —
# this is what makes the pairing kernel compile AND run fast.

# products per reduction; redc_cols requires k < R/N, and R/P = 2^384/P
# is ~9.84 for BLS12-381, so 9 buckets are safe (9 * P^2 < 2^384 * P)
_BUCKET_CAP = 9


def _coord_basis(i: int):
    """Host fp12 with a 1 in flat coordinate i (layout of _stk_from_tuple)."""
    flat = [0] * 12
    flat[i] = 1
    it = iter(flat)
    return tuple(
        tuple((next(it), next(it)) for _ in range(3)) for _ in range(2)
    )


def _flatten_host_fp12(f):
    return [c for half in f for pair in half for c in pair]


@functools.lru_cache(maxsize=4)
def _build_mul_tensor(y_support: tuple):
    """Static product-slot table for z = x * y with y zero outside
    ``y_support`` rows.  Returns (lhs_idx, rhs_idx, neg, out_slot, n_buckets)
    as numpy arrays / int."""
    slots_per_out: list[list[tuple[int, int, bool]]] = [[] for _ in range(12)]
    for i in range(12):
        for j in y_support:
            prod = _flatten_host_fp12(
                fp12_mul(HOST, _coord_basis(i), _coord_basis(j))
            )
            for k, c in enumerate(prod):
                if c == 0:
                    continue
                if c <= 4:
                    repeat, neg = c, False
                elif P - c <= 4:
                    repeat, neg = P - c, True
                else:  # pragma: no cover — tower structure guarantees small c
                    raise AssertionError(f"unexpected tensor coeff {c}")
                slots_per_out[k].extend([(i, j, neg)] * repeat)
    n_buckets = max(
        (len(s) + _BUCKET_CAP - 1) // _BUCKET_CAP for s in slots_per_out
    )
    lhs, rhs, neg, out = [], [], [], []
    for k, slots in enumerate(slots_per_out):
        for pos, (i, j, n) in enumerate(slots):
            lhs.append(i)
            rhs.append(j)
            neg.append(n)
            out.append((pos // _BUCKET_CAP) * 12 + k)
    return (
        np.asarray(lhs, np.int32),
        np.asarray(rhs, np.int32),
        np.asarray(neg, bool),
        np.asarray(out, np.int32),
        n_buckets,
    )


_FULL_SUPPORT = tuple(range(12))
#: line rows: l00 at fp2 coord 0 (rows 0-1), lx at coord 1 (rows 2-3),
#: ly at coord 4 (rows 8-9) — see _line_to_fp12
_LINE_SUPPORT = (0, 1, 2, 3, 8, 9)


def _mul12_tensor(x, y, y_support):
    """z = x * y over stacked (..., 12, L) coordinates; ~8 chains total."""
    ctx = CTX
    lhs_idx, rhs_idx, negmask, out_slot, n_buckets = _build_mul_tensor(y_support)
    yneg, _ = bn.sub_borrow(
        jnp.broadcast_to(jnp.asarray(ctx.N), y.shape), y
    )
    lhs = jnp.take(x, jnp.asarray(lhs_idx), axis=-2)
    rhs = jnp.where(
        jnp.asarray(negmask)[:, None],
        jnp.take(yneg, jnp.asarray(rhs_idx), axis=-2),
        jnp.take(y, jnp.asarray(rhs_idx), axis=-2),
    )
    cols = bn.mul_columns(lhs, rhs)  # (..., K, 2L)
    # vector-accumulate column arrays per output slot (static grouping)
    groups: dict[int, list[int]] = {}
    for pos, slot in enumerate(out_slot):
        groups.setdefault(int(slot), []).append(pos)
    slot_cols = []
    for slot in range(12 * n_buckets):
        members = groups.get(slot)
        if not members:
            slot_cols.append(jnp.zeros(cols.shape[:-2] + (cols.shape[-1],), bn.DTYPE))
            continue
        acc = cols[..., members[0], :]
        for pos in members[1:]:
            acc = acc + cols[..., pos, :]
        slot_cols.append(acc)
    stacked = jnp.stack(slot_cols, axis=-2)  # (..., 12*n_buckets, 2L)
    red = ctx.redc_cols(stacked)  # (..., 12*n_buckets, L)
    result = red[..., 0:12, :]
    for b in range(1, n_buckets):
        result = ctx.add(result, red[..., b * 12 : (b + 1) * 12, :])
    return result


def mul12(x, y):
    """Fp12 mult via the lazy-reduction tensor path."""
    return _mul12_tensor(x, y, _FULL_SUPPORT)


def mul12_line(f, line_rows):
    """f times a sparse line element (rows 0-3 and 8-9 only)."""
    return _mul12_tensor(f, line_rows, _LINE_SUPPORT)


def sqr12(x):
    return _mul12_tensor(x, x, _FULL_SUPPORT)


def conj12(x):
    """a - b w: negate rows 6..11 (one stacked chain)."""
    a = x[..., 0:6, :]
    b = CTX.neg(x[..., 6:12, :])
    return jnp.concatenate([a, b], axis=-2)


_FROB_COEFFS = None


def _frob_coeffs():
    """Stacked gamma constants for the p-power Frobenius: (5, 2, L)."""
    global _FROB_COEFFS
    if _FROB_COEFFS is None:
        g1g2 = fp2_mul(HOST, _G1F, _G2F)
        g1g4 = fp2_mul(HOST, _G1F, _G4F)
        _FROB_COEFFS = np.stack([
            _fp2_const_mont(_G2F),   # a1
            _fp2_const_mont(_G4F),   # a2
            _fp2_const_mont(_G1F),   # b0
            _fp2_const_mont(g1g2),   # b1
            _fp2_const_mont(g1g4),   # b2
        ])
    return _FROB_COEFFS


def frob12(x):
    """p-power Frobenius, stacked: conjugate all Fp2 rows then scale five of
    the six components by the gamma constants (one 5-way mult call)."""
    ctx = CTX
    re = x[..., 0::2, :]
    im = ctx.neg(x[..., 1::2, :])
    conj = jnp.stack([re, im], axis=-2)  # (..., 6, 2, L)
    a0 = conj[..., 0:1, :, :]
    rest = conj[..., 1:6, :, :]
    coeffs = jnp.broadcast_to(jnp.asarray(_frob_coeffs()), rest.shape)
    scaled = _rows_mul(rest, coeffs)
    out = jnp.concatenate([a0, scaled], axis=-3)  # (..., 6, 2, L)
    return out.reshape(out.shape[:-3] + (12, NLIMBS))


_HALF_SUPPORT = (0, 1, 2, 3, 4, 5)  # fp6 embedded in rows 0..5, w-half zero


def _stk_pad6(a):
    """(..., 6, L) fp6 rows -> (..., 12, L) fp12 with zero w-half."""
    return jnp.concatenate([a, jnp.zeros_like(a)], axis=-2)


def _stk_mul_v(a):
    """v * (c0 + c1 v + c2 v^2) = xi*c2 + c0 v + c1 v^2 on (..., 6, L) rows."""
    return jnp.concatenate([_rows_xi(a[..., 4:6, :]), a[..., 0:4, :]], axis=-2)


def inv12(x):
    """Fp12 inversion, stacked: the same norm-tower chain as the host
    :func:`fp12_inv` (fp12 -> fp6 -> fp2 -> one Fp Fermat inversion), but
    with every level's independent fp2 products gathered into stacked
    Montgomery calls — ~12 sequential chains + one exp scan, versus the
    ~100 chains the generic tuple tower emitted (which alone cost ~2 min
    of XLA compile)."""
    ctx = CTX
    a, b = x[..., 0:6, :], x[..., 6:12, :]
    # a^2, b^2 as fp6 products via the fp12 tensor on zero-w-half operands
    pa, pb = _stk_pad6(a), _stk_pad6(b)
    a2 = _mul12_tensor(pa, pa, _HALF_SUPPORT)[..., 0:6, :]
    b2 = _mul12_tensor(pb, pb, _HALF_SUPPORT)[..., 0:6, :]
    den = ctx.sub(a2, _stk_mul_v(b2))  # a^2 - v b^2 in fp6 rows
    d0, d1, d2 = (den[..., 0:2, :], den[..., 2:4, :], den[..., 4:6, :])
    # fp6 inversion (host fp6_inv formulas), fp2 ops stacked 3-wide
    s0, s1, s2 = _fp2_stk_sqr3(d0, d1, d2)  # d0^2, d1^2, d2^2
    p12, p01, p02 = _fp2_stk_mul([(d1, d2), (d0, d1), (d0, d2)])
    c0 = ctx.sub(s0, _rows_xi(p12))
    c1 = ctx.sub(_rows_xi(s2), p01)
    c2 = ctx.sub(s1, p02)
    q21, q12, q00 = _fp2_stk_mul([(d2, c1), (d1, c2), (d0, c0)])
    t = ctx.add(_rows_xi(ctx.add(q21, q12)), q00)  # (..., 2, L) fp2
    # fp2 inversion: 1/(tr + ti u) = (tr - ti u) / (tr^2 + ti^2)
    tr, ti = t[..., 0, :], t[..., 1, :]
    sq = ctx.square(jnp.stack([tr, ti], axis=-2))
    norm = ctx.add(sq[..., 0, :], sq[..., 1, :])
    ninv = ctx.inv(norm)  # the single Fp Fermat inversion (exp scan)
    ri = ctx.mul(jnp.stack([tr, ti], axis=-2),
                 jnp.stack([ninv, ninv], axis=-2))
    tinv = jnp.stack([ri[..., 0, :], ctx.neg(ri[..., 1, :])], axis=-2)
    e0, e1, e2 = _fp2_stk_mul([(c0, tinv), (c1, tinv), (c2, tinv)])
    e = jnp.concatenate([e0, e1, e2], axis=-2)  # fp6 = 1/(a^2 - v b^2)
    # (a - b w) * e  =  a e  -  (b e) w  =  x^-1
    return _mul12_tensor(conj12(x), _stk_pad6(e), _HALF_SUPPORT)


def _fp2_const_mont(c) -> np.ndarray:
    return np.stack([CTX.encode(c[0]), CTX.encode(c[1])])


# -- fixed -g2 Miller line tables (precomputed with host ints) ---------------

def _precompute_fixed_lines(q_affine):
    """Per-step raw line coefficients for the fixed-Q Miller loop, encoded
    into the Montgomery domain: two (steps, 3, 2, NLIMBS) arrays."""
    T = (q_affine[0], q_affine[1], (1, 0))
    dbl_rows, add_rows = [], []

    def enc(coeffs):
        return np.stack([_fp2_const_mont(c) for c in coeffs])

    for bit in _X_BITS:
        T, coeffs = _dbl_step(HOST, T)
        dbl_rows.append(enc(coeffs))
        if bit:
            T, coeffs = _add_step(HOST, T, q_affine)
            add_rows.append(enc(coeffs))
        else:
            add_rows.append(enc(((0, 0), (0, 0), (0, 0))))
    return np.stack(dbl_rows), np.stack(add_rows)


_FIXED_DBL, _FIXED_ADD = _precompute_fixed_lines(NEG_G2)
_X_BITS_ARR = np.asarray(_X_BITS, dtype=np.uint32)


def _fp2_stk_sqr3(a, b, c):
    """Square three independent stacked fp2 values in one Montgomery call."""
    s = jnp.stack([a, b, c], axis=-3)
    t = _rows_mul(s, s)
    return t[..., 0, :, :], t[..., 1, :, :], t[..., 2, :, :]


def _fp2_stk_mul(pairs):
    """[(a, b), ...] independent stacked-fp2 products in one call."""
    lhs = jnp.stack([jnp.broadcast_to(a, jnp.broadcast_shapes(a.shape, b.shape))
                     for a, b in pairs], axis=-3)
    rhs = jnp.stack([jnp.broadcast_to(b, jnp.broadcast_shapes(a.shape, b.shape))
                     for a, b in pairs], axis=-3)
    t = _rows_mul(lhs, rhs)
    return tuple(t[..., i, :, :] for i in range(len(pairs)))


def _stk_dbl_step(T):
    """Stacked Miller doubling (same formulas as :func:`_dbl_step`): four
    Montgomery calls total instead of one per field mult."""
    ctx = CTX
    X, Y, Z = T  # each (..., 2, L)
    X2, Y2, Z2 = _fp2_stk_sqr3(X, Y, Z)
    X2_3 = ctx.add(ctx.add(X2, X2), X2)
    P1, P2, YZ = _fp2_stk_mul([(X2_3, X), (X2_3, Z2), (Y, Z)])
    l00 = ctx.sub(P1, ctx.add(Y2, Y2))
    lxc = ctx.neg(P2)
    XpY2 = ctx.add(X, Y2)
    C, D2s, Fv = _fp2_stk_sqr3(Y2, XpY2, X2_3)
    D = ctx.sub(D2s, ctx.add(X2, C))
    D = ctx.add(D, D)
    X3 = ctx.sub(Fv, ctx.add(D, D))
    YZ2 = ctx.add(YZ, YZ)
    M1, lyc = _fp2_stk_mul([(X2_3, ctx.sub(D, X3)), (YZ2, Z2)])
    C2 = ctx.add(C, C)
    C4 = ctx.add(C2, C2)
    Y3 = ctx.sub(M1, ctx.add(C4, C4))
    return (X3, Y3, YZ2), (l00, lxc, lyc)


def _stk_add_step(T, Q):
    """Stacked Miller mixed addition (same formulas as :func:`_add_step`)."""
    ctx = CTX
    X, Y, Z = T
    xq, yq = Q  # stacked fp2 (..., 2, L)
    (Z2,) = _fp2_stk_mul([(Z, Z)])
    Z3c, U2 = _fp2_stk_mul([(Z2, Z), (xq, Z2)])
    (S2,) = _fp2_stk_mul([(yq, Z3c)])
    H = ctx.sub(U2, X)
    Rr = ctx.sub(S2, Y)
    HZ, H2, R2 = _fp2_stk_mul([(H, Z), (H, H), (Rr, Rr)])
    Rxq, HZyq, H3, UH2 = _fp2_stk_mul([(Rr, xq), (HZ, yq), (H2, H), (X, H2)])
    X3 = ctx.sub(ctx.sub(R2, H3), ctx.add(UH2, UH2))
    M1, M2 = _fp2_stk_mul([(Rr, ctx.sub(UH2, X3)), (Y, H3)])
    Y3 = ctx.sub(M1, M2)
    l00 = ctx.sub(Rxq, HZyq)
    lxc = ctx.neg(Rr)
    lyc = HZ
    return (X3, Y3, HZ), (l00, lxc, lyc)


def _line_rows(coeffs_fp2, xp, yp):
    """Stacked line: scale lxc by xp, lyc by yp (one 2-way mult call) and
    assemble the sparse rows [l00, lx, 0, | 0, ly, 0] as (..., 12, L)."""
    ctx = CTX
    l00, lxc, lyc = coeffs_fp2  # each (..., 2, L)
    ab = jnp.stack([lxc, lyc], axis=-3)  # (..., 2, 2, L)
    sc = jnp.stack(
        [jnp.stack([xp, xp], axis=-2), jnp.stack([yp, yp], axis=-2)], axis=-3
    )
    scaled = ctx.mul(ab, sc)
    lx, ly = scaled[..., 0, :, :], scaled[..., 1, :, :]
    z = jnp.zeros_like(lx)
    l00b = jnp.broadcast_to(l00, lx.shape)  # fixed-table coeffs are unbatched
    rows = jnp.concatenate([
        l00b[..., None, :, :], lx[..., None, :, :], z[..., None, :, :],
        z[..., None, :, :], ly[..., None, :, :], z[..., None, :, :],
    ], axis=-3)  # (..., 6, 2, L)
    return rows.reshape(rows.shape[:-3] + (12, NLIMBS))


def _dev_miller_fused(sig_x, sig_y, hm_x, hm_y, pk):
    """Fused dual Miller loop: e(sig, -g2) (table-driven) and e(hm, pk)
    (variable Q) share one accumulator — a single squaring chain.

    All coordinates are Montgomery-domain (..., NLIMBS) arrays; internally
    fp2 values are stacked as (..., 2, NLIMBS).
    """
    qx = jnp.stack([pk[0][0], pk[0][1]], axis=-2)  # (..., 2, L)
    qy = jnp.stack([pk[1][0], pk[1][1]], axis=-2)
    one = jnp.broadcast_to(jnp.asarray(CTX.one_mont), qx.shape[:-2] + (NLIMBS,))
    one2 = jnp.stack([one, jnp.zeros_like(one)], axis=-2)
    f0 = _stk_one(sig_x)
    T0 = (qx, qy, one2)
    xs = (
        jnp.asarray(_X_BITS_ARR),
        jnp.asarray(_FIXED_DBL),
        jnp.asarray(_FIXED_ADD),
    )

    def body(carry, x):
        f, T = carry
        bit, dbl_row, add_row = x
        mask = jnp.broadcast_to(bit, f.shape[:-2]).astype(bn.DTYPE)
        f = sqr12(f)
        # variable side: doubling + line at (hm_x, hm_y)
        T2, coeffs = _stk_dbl_step(T)
        f = mul12_line(f, _line_rows(coeffs, hm_x, hm_y))
        # fixed side: precomputed coefficients at (sig_x, sig_y)
        frow = (dbl_row[0], dbl_row[1], dbl_row[2])
        f = mul12_line(f, _line_rows(frow, sig_x, sig_y))
        # conditional addition step: select the LINES to identity when the
        # bit is 0 (select on 12 rows is far cheaper than a second mult path)
        Ta, acoeffs = _stk_add_step(T2, (qx, qy))
        ident = _stk_one(sig_x)
        la = _line_rows(acoeffs, hm_x, hm_y)
        lf = _line_rows((add_row[0], add_row[1], add_row[2]), sig_x, sig_y)
        mask_r = mask[..., None]
        f = mul12_line(f, _tree_select(mask_r, la, ident))
        f = mul12_line(f, _tree_select(mask_r, lf, ident))
        T = _tree_select(mask_r, Ta, T2)
        return (f, T), None

    # unroll=2: the tunnel TPU compiler miscompiles the single-iteration
    # loop-back of this scan at batch >= ~64 (the (B, 12, L) carry comes
    # back corrupted; batch 5 is fine, components all verify in
    # isolation).  Processing two steps per trip sidesteps the bad
    # relayout and is bit-exact vs the host at every batch size tested.
    (f, _), _ = lax.scan(body, (f0, T0), xs, unroll=2)
    return conj12(f)  # x < 0


def _dev_cyclo_exp_abs(m, bits_arr):
    """m^e (stacked) with e given MSB-first static bits; m cyclotomic."""

    def body(acc, bit):
        acc = sqr12(acc)
        mask = jnp.broadcast_to(bit, acc.shape[:-2]).astype(bn.DTYPE)[..., None]
        acc = _tree_select(mask, mul12(acc, m), acc)
        return acc, None

    # unroll=2: same tunnel-compiler scan-carry workaround as the Miller
    # loop (see _dev_miller_fused)
    acc, _ = lax.scan(body, m, jnp.asarray(bits_arr[1:]), unroll=2)
    return acc


_XP1_BITS_ARR = np.asarray(_XP1_BITS, dtype=np.uint32)
_X_BITS_FULL_ARR = np.asarray(_X_BITS_FULL, dtype=np.uint32)


def _dev_final_exp(f):
    """Device final exponentiation — same chain as :func:`host_final_exp`."""
    f = mul12(conj12(f), inv12(f))
    f = mul12(frob12(frob12(f)), f)
    m = f
    expx = lambda z: conj12(_dev_cyclo_exp_abs(z, _X_BITS_FULL_ARR))
    expxm1 = lambda z: conj12(_dev_cyclo_exp_abs(z, _XP1_BITS_ARR))
    a = expxm1(m)
    a = expxm1(a)
    b = expx(a)
    a = mul12(b, frob12(a))
    c = _dev_cyclo_exp_abs(_dev_cyclo_exp_abs(a, _X_BITS_FULL_ARR), _X_BITS_FULL_ARR)
    a = mul12(mul12(c, frob12(frob12(a))), conj12(a))
    m3 = mul12(sqr12(m), m)
    return mul12(a, m3)


def _dev_is_one(f):
    """Stacked equality with 1: row 0 == 1_mont, rows 1..11 == 0."""
    one = jnp.broadcast_to(jnp.asarray(CTX.one_mont), f[..., 0, :].shape)
    mask = bn.eq(f[..., 0, :], one)
    rest = f[..., 1:, :]
    zero = (jnp.max(rest, axis=(-1, -2)) == 0).astype(bn.DTYPE)
    return mask * zero


def bls_verify_kernel(sig_x, sig_y, hm_x, hm_y, pk_x0, pk_x1, pk_y0, pk_y1, ok):
    """Batched BLS12-381 verification.  Pure, jittable.

    Each lane checks e(sig, -g2) * e(H(m), pk) == 1 with one fused dual
    Miller loop + one final exponentiation.  A lane may hold a single
    signature or a whole aggregated quorum — same cost either way; that is
    the point.  All inputs are (..., NLIMBS) uint32 Montgomery-domain limb
    arrays (see :func:`verify_inputs`); ok is the host-side validity mask
    (decode/on-curve/subgroup failures).  Returns a (...,) uint32 mask.
    """
    pk = ((pk_x0, pk_x1), (pk_y0, pk_y1))
    f = _dev_miller_fused(sig_x, sig_y, hm_x, hm_y, pk)
    f = _dev_final_exp(f)
    return _dev_is_one(f) * ok


def _encode_g1(pt) -> tuple[np.ndarray, np.ndarray]:
    return CTX.encode(pt[0]), CTX.encode(pt[1])


def verify_inputs(items) -> tuple[np.ndarray, ...]:
    """[(msg, sig96, pub192), ...] -> batched kernel inputs.

    Host-side work per item: deserialize, on-curve + r-torsion subgroup
    checks (memoized for the small static pubkey set), hash-to-G1
    (memoized per digest), Montgomery encoding.  Invalid items become
    generator-dummy lanes with ok=0.
    """
    n = len(items)
    shape = (n, NLIMBS)
    sig_x = np.zeros(shape, np.uint32)
    sig_y = np.zeros(shape, np.uint32)
    hm_x = np.zeros(shape, np.uint32)
    hm_y = np.zeros(shape, np.uint32)
    pk_x0 = np.zeros(shape, np.uint32)
    pk_x1 = np.zeros(shape, np.uint32)
    pk_y0 = np.zeros(shape, np.uint32)
    pk_y1 = np.zeros(shape, np.uint32)
    ok = np.zeros((n,), np.uint32)
    g1m = _encode_g1((G1X, G1Y))
    g2xm = _fp2_const_mont(G2X)
    g2ym = _fp2_const_mont(G2Y)
    for i, (msg, sig, pub) in enumerate(items):
        try:
            pk = _checked_pub(pub)
            s = _checked_sig(sig)
        except ValueError:
            sig_x[i], sig_y[i] = g1m
            hm_x[i], hm_y[i] = g1m
            pk_x0[i], pk_x1[i] = g2xm
            pk_y0[i], pk_y1[i] = g2ym
            continue
        hm = hash_to_g1(msg)
        sig_x[i], sig_y[i] = _encode_g1(s)
        hm_x[i], hm_y[i] = _encode_g1(hm)
        pk_x0[i], pk_x1[i] = _fp2_const_mont(pk[0])
        pk_y0[i], pk_y1[i] = _fp2_const_mont(pk[1])
        ok[i] = 1
    return sig_x, sig_y, hm_x, hm_y, pk_x0, pk_x1, pk_y0, pk_y1, ok


def aggregate_items(items):
    """Collapse same-message items into ONE kernel lane
    [(msg, sig, pub), ...] -> (msg, agg_sig, agg_pub).

    This is the quorum path: Q-1 commit votes over one proposal digest
    become a single pairing-equation lane (BASELINE "1 pairing/quorum").
    """
    if not items:
        raise ValueError("no items")
    msg = items[0][0]
    if any(m != msg for m, _, _ in items):
        raise ValueError("aggregate_items requires a common message")
    agg_sig = aggregate_sigs([s for _, s, _ in items])
    agg_pub = aggregate_pubs([p for _, _, p in items])
    return (msg, agg_sig, agg_pub)


verify_kernel = bls_verify_kernel
