"""Ed25519 (RFC 8032): batched TPU verification, host-side signing.

Same design as :mod:`p256` — the alt-curve Signer/Verifier variant of
BASELINE.md configs[3].  The reference delegates signatures to the embedding
application (/root/reference/pkg/api/dependencies.go:47-71) and verifies one
commit vote per goroutine (/root/reference/internal/bft/view.go:537-541);
here a whole quorum of EdDSA votes is ONE jitted kernel launch:

* Field/scalar arithmetic: :mod:`bignum` Montgomery contexts for
  p = 2^255-19 and the group order L.
* Curve arithmetic: extended twisted-Edwards coordinates (X:Y:Z:T) with the
  unified a=-1 addition formula (Hisil-Wong-Carter-Dawson 2008,
  "add-2008-hwcd-3").  Because -1 is a square mod p and d is non-square,
  the formula is complete: one branch-free straight-line block covers
  addition, doubling, and the identity — ideal for XLA.
* Verification equation (cofactorless, as in Go's crypto/ed25519):
  [S]B == R + [h]A, evaluated as [S]B + [h](-A) == R with 2-bit-windowed
  Strauss-Shamir interleaving: a single ``lax.scan`` over 127 digit pairs —
  two doublings, one gather from the 16-entry joint table {iB + j(-A)}
  (the B multiples are host-precomputed constants), one unified addition
  per digit.

Hashing (SHA-512) and point decompression are host-side marshalling —
exactly like SHA-256 digesting in the P-256 path; the kernel re-checks both
points against the curve equation so a bad decompression can never validate.
"""

from __future__ import annotations

import functools
import hashlib
import secrets

import numpy as np

import jax.numpy as jnp


from . import bignum as bn
from .bignum import MontCtx

# --- curve constants (RFC 8032 §5.1) ---------------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, -1, P)) % P
BY = (4 * pow(5, -1, P)) % P
BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1) mod p

NLIMBS = 16
FP = MontCtx(P, NLIMBS)
FL = MontCtx(L, NLIMBS)

_D_MONT = FP.encode(D)
_D2_MONT = FP.encode((2 * D) % P)


def _aff_add(p1, p2):
    """Host affine Edwards addition (for the fixed-base table constants)."""
    x1, y1 = p1
    x2, y2 = p2
    den = D * x1 * x2 * y1 * y2 % P
    return ((x1 * y2 + x2 * y1) * pow(1 + den, -1, P) % P,
            (y1 * y2 + x1 * x2) * pow(1 - den, -1, P) % P)


def _ext_mont(x: int, y: int) -> np.ndarray:
    """Host affine ints -> extended (X:Y:1:XY) Montgomery limb stack."""
    return np.stack([FP.encode(x), FP.encode(y), FP.one_mont,
                     FP.encode(x * y % P)])


_B2_AFF = _aff_add((BX, BY), (BX, BY))
_B3_AFF = _aff_add(_B2_AFF, (BX, BY))
_B_MONT = _ext_mont(BX, BY)
_B2_MONT = _ext_mont(*_B2_AFF)
_B3_MONT = _ext_mont(*_B3_AFF)
# identity in extended coordinates: (0 : 1 : 1 : 0)
_ID_MONT = np.stack([FP.zero, FP.one_mont, FP.one_mont, FP.zero])


# ---------------------------------------------------------------------------
# extended twisted-Edwards ops (points are (..., 4, NLIMBS) Mont arrays)
# ---------------------------------------------------------------------------

def point_add(p, q):
    """Unified addition, add-2008-hwcd-3 (a = -1).  Complete on this curve.

    8 field mults + 1 mult by the 2d constant — level-scheduled: the
    independent ops of each dataflow level stack into single grouped
    Montgomery calls (3 mul groups + 4 add/sub groups of sequential
    depth; see :func:`bignum.grouped`).
    """
    f = FP
    x1, y1, z1, t1 = (p[..., i, :] for i in range(4))
    x2, y2, z2, t2 = (q[..., i, :] for i in range(4))

    s1, s2 = bn.grouped(f.sub, [(y1, x1), (y2, x2)])
    a1, a2, z1d = bn.grouped(f.add, [(y1, x1), (y2, x2), (z1, z1)])
    a, b, c1, d = bn.grouped(
        f.mul,
        [(s1, s2), (a1, a2), (t1, jnp.asarray(_D2_MONT)), (z1d, z2)],
    )
    c = f.mul(c1, t2)
    e, ff = bn.grouped(f.sub, [(b, a), (d, c)])
    g, h = bn.grouped(f.add, [(d, c), (b, a)])
    x3, y3, t3, z3 = bn.grouped(
        f.mul, [(e, ff), (g, h), (e, h), (ff, g)]
    )
    return jnp.stack([x3, y3, z3, t3], axis=-2)


def point_double(p):
    """Dedicated doubling, dbl-2008-hwcd with both output halves negated
    (a = -1).  Complete on this curve — the identity doubles to itself.

    4M + 4S versus the unified addition's 8M + 1mb, with the squarings in
    ONE grouped :func:`bignum.square_columns` call: with E = (X+Y)^2-A-B,
    G = B-A, F = 2Z^2-G, H = A+B it returns (EF : GH : FG : EH), which is
    the EFD formula's output scaled by -1 — the same projective point.
    The T1 input is unused (doubling never needs the extended coordinate).
    """
    f = FP
    x, y, z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    xy = f.add(x, y)
    a, b, zz, s = bn.grouped1(f.square, [x, y, z, xy])
    c, h = bn.grouped(f.add, [(zz, zz), (a, b)])
    g, e1 = bn.grouped(f.sub, [(b, a), (s, a)])
    e = f.sub(e1, b)
    ff = f.sub(c, g)
    x3, y3, z3, t3 = bn.grouped(
        f.mul, [(e, ff), (g, h), (ff, g), (e, h)]
    )
    return jnp.stack([x3, y3, z3, t3], axis=-2)


def point_neg(p):
    """-(X:Y:Z:T) = (-X:Y:Z:-T)."""
    return jnp.stack([
        FP.neg(p[..., 0, :]), p[..., 1, :], p[..., 2, :], FP.neg(p[..., 3, :])
    ], axis=-2)


def is_on_curve(xm, ym):
    """-x^2 + y^2 == 1 + d*x^2*y^2 in Mont domain; (...,) uint32 mask."""
    f = FP
    xx = f.mul(xm, xm)
    yy = f.mul(ym, ym)
    lhs = f.sub(yy, xx)
    one = jnp.broadcast_to(jnp.asarray(FP.one_mont), xx.shape)
    rhs = f.add(one, f.mul(jnp.asarray(_D_MONT), f.mul(xx, yy)))
    return bn.eq(lhs, rhs)


def _extended(xm, ym):
    """Affine Mont coords -> extended (X:Y:1:XY)."""
    one = jnp.broadcast_to(jnp.asarray(FP.one_mont), xm.shape)
    return jnp.stack([xm, ym, one, FP.mul(xm, ym)], axis=-2)


def shamir_double_scalar(s, h, nega):
    """[s]B + [h]*nega, 2-bit-windowed Shamir: 127 digits x (2 dbl + 1 add).

    s/h: (..., NLIMBS) standard-domain scalars (< 2^253 < 2^254); nega:
    (..., 4, NLIMBS) Mont domain.  B is fixed, so its window multiples are
    host-precomputed constants; the -A multiples build in two point_add
    depths and the 16 combination adds share ONE grouped call.
    """
    ident = jnp.broadcast_to(jnp.asarray(_ID_MONT), nega.shape)
    bs = [ident] + [
        jnp.broadcast_to(jnp.asarray(c), nega.shape)
        for c in (_B_MONT, _B2_MONT, _B3_MONT)
    ]
    na2 = point_add(nega, nega)
    na3 = point_add(na2, nega)
    table = bn.joint_table(
        point_add, bs, [ident, nega, na2, na3]
    )  # (..., 16, 4, n); entry 4i+j = iB + j*nega
    return bn.shamir_scan_w(
        point_add, table, ident,
        bn.digits_msb(s, 127, 2), bn.digits_msb(h, 127, 2), width=2,
        point_double=point_double,
    )


def eddsa_verify_kernel(s, h, rx, ry, ax, ay, ok_in):
    """Batched Ed25519 verification.  Pure, jittable.

    Inputs are (..., NLIMBS) uint32 limb vectors in the *standard* domain:
    ``s`` the signature scalar, ``h`` = SHA-512(R || A || M) mod L (host
    hashing, like the P-256 path's SHA-256), (rx, ry) and (ax, ay) the
    decompressed signature/public points, plus ``ok_in`` — a (...,) uint32
    host flag, 0 where decoding/decompression already failed (those lanes
    carry identity coordinates).  Returns a (...,) uint32 validity mask;
    invalid signatures yield 0, never an exception.
    """
    l_arr = jnp.asarray(FL.N)
    s_ok = jnp.uint32(1) - bn.geq(s, l_arr)  # RFC 8032: 0 <= s < L

    rxm, rym = FP.to_mont(rx), FP.to_mont(ry)
    axm, aym = FP.to_mont(ax), FP.to_mont(ay)
    oncurve = is_on_curve(rxm, rym) * is_on_curve(axm, aym)

    nega = point_neg(_extended(axm, aym))
    acc = shamir_double_scalar(s, h, nega)
    # [s]B - [h]A, extended coords; Z != 0 by completeness

    xz = acc[..., 0, :]
    yz = acc[..., 1, :]
    z = acc[..., 2, :]
    match = bn.eq(FP.mul(rxm, z), xz) * bn.eq(FP.mul(rym, z), yz)
    return match * s_ok * oncurve * ok_in


# ---------------------------------------------------------------------------
# host-side reference arithmetic (Python ints) — keygen, sign, CPU verify
# ---------------------------------------------------------------------------

# affine Edwards addition over GF(P); (0, 1) is the identity
_edwards_add_int = _aff_add


def scalar_mult_int(k: int, point):
    """Double-and-add with Python ints (host-side; keygen/sign only)."""
    acc = (0, 1)
    addend = point
    while k:
        if k & 1:
            acc = _edwards_add_int(acc, addend)
        addend = _edwards_add_int(addend, addend)
        k >>= 1
    return acc


def compress(point) -> bytes:
    x, y = point
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def decompress(data: bytes):
    """32-byte encoding -> affine point, or None if invalid (RFC 8032 §5.1.3).

    The sqrt mod p runs in the native C++ helper when available (~30 us vs
    ~150 us as a Python pow) — every Ed25519 verification decompresses R,
    making this the host-prep hot spot of the batch path."""
    if len(data) != 32:
        return None
    from .. import native

    if native.ed_available():
        return native.ed_decompress(data)
    val = int.from_bytes(data, "little")
    sign = val >> 255
    y = val & ((1 << 255) - 1)
    if y >= P:
        return None
    yy = y * y % P
    u = (yy - 1) % P
    v = (D * yy + 1) % P
    # candidate root of u/v: (u*v^3) * (u*v^7)^((p-5)/8)
    x = u * pow(v, 3, P) * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P) % P
    if v * x * x % P != u:
        x = x * SQRT_M1 % P
        if v * x * x % P != u:
            return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = P - x
    return (x, y)


def _clamp(raw: bytes) -> int:
    a = bytearray(raw)
    a[0] &= 248
    a[31] &= 127
    a[31] |= 64
    return int.from_bytes(bytes(a), "little")


def keygen(seed: bytes | None = None):
    """Returns (private_key_bytes, public_key_bytes).  Deterministic w/ seed."""
    if seed is None:
        priv = secrets.token_bytes(32)
    else:
        priv = hashlib.sha256(b"ed25519-keygen" + seed).digest()
    h = hashlib.sha512(priv).digest()
    a = _clamp(h[:32])
    return priv, compress(scalar_mult_int(a, (BX, BY)))


@functools.lru_cache(maxsize=256)
def _expand_key(priv: bytes) -> tuple[int, bytes, bytes]:
    """(clamped scalar, prefix, public key) — fixed per private key, so cache
    it instead of re-deriving A with a full scalar mult on every sign()."""
    h = hashlib.sha512(priv).digest()
    a = _clamp(h[:32])
    return a, h[32:], compress(scalar_mult_int(a, (BX, BY)))


def sign(priv: bytes, msg: bytes) -> bytes:
    """RFC 8032 §5.1.6 deterministic signature; returns 64 bytes R || S."""
    a, prefix, pub = _expand_key(priv)
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    r_enc = compress(scalar_mult_int(r, (BX, BY)))
    k = int.from_bytes(
        hashlib.sha512(r_enc + pub + msg).digest(), "little"
    ) % L
    s = (r + k * a) % L
    return r_enc + s.to_bytes(32, "little")


def verify_int(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Pure-Python Ed25519 verify — CPU reference / baseline engine path."""
    if len(sig) != 64:
        return False
    a_pt = decompress(pub)
    r_pt = decompress(sig[:32])
    if a_pt is None or r_pt is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    k = int.from_bytes(hashlib.sha512(sig[:32] + pub + msg).digest(), "little") % L
    lhs = scalar_mult_int(s, (BX, BY))
    rhs = _edwards_add_int(r_pt, scalar_mult_int(k, a_pt))
    return lhs == rhs


# ---------------------------------------------------------------------------
# host <-> kernel marshalling (scheme API used by the verify engines)
# ---------------------------------------------------------------------------

try:  # native signing fast path (RFC 8032 is deterministic, so OpenSSL
    # produces byte-identical signatures to the pure-Python sign(); the
    # pure path costs ~180 ms per signature on this host, OpenSSL ~50 us)
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey as _CgEd25519,
    )

    import functools as _ft

    @_ft.lru_cache(maxsize=256)  # bounded, like _expand_key
    def _cg_key(priv: bytes):
        return _CgEd25519.from_private_bytes(priv)

    def _sign_native(priv: bytes, msg: bytes) -> bytes:
        return _cg_key(priv).sign(msg)
except Exception as _exc:  # pragma: no cover — wheel absent/broken
    import logging as _logging

    _logging.getLogger("smartbft_tpu.crypto").warning(
        "native Ed25519 signer unavailable (%s); falling back to the "
        "pure-Python signer", _exc,
    )
    _sign_native = None


def sign_raw(priv: bytes, msg: bytes) -> bytes:
    if _sign_native is not None:
        return _sign_native(priv, msg)
    return sign(priv, msg)


def make_item(msg: bytes, sig: bytes, pub: bytes):
    return (msg, sig, pub)


def verify_item(item) -> bool:
    msg, sig, pub = item
    return verify_int(pub, msg, sig)


@functools.lru_cache(maxsize=1024)
def _decompress_pub(pub: bytes):
    """Signer pubkeys come from the small static membership set; memoize the
    sqrt-heavy decompression so the batched hot path pays it once per key.
    R decompression stays uncached — unique per signature."""
    return decompress(pub)


def verify_inputs(items) -> tuple[np.ndarray, ...]:
    """[(msg, sig64, pub32), ...] -> stacked (B, 16)x6 + (B,) kernel inputs."""
    n = len(items)
    s = np.zeros((n, NLIMBS), np.uint32)
    h = np.zeros((n, NLIMBS), np.uint32)
    rx = np.zeros((n, NLIMBS), np.uint32)
    ry = np.zeros((n, NLIMBS), np.uint32)
    ry[:, 0] = 1  # identity placeholder for invalid lanes
    ax = np.zeros((n, NLIMBS), np.uint32)
    ay = np.zeros((n, NLIMBS), np.uint32)
    ay[:, 0] = 1
    ok = np.zeros((n,), np.uint32)
    for i, (msg, sig, pub) in enumerate(items):
        if len(sig) != 64:
            continue
        r_pt = decompress(sig[:32])
        a_pt = _decompress_pub(pub)
        if r_pt is None or a_pt is None:
            continue
        s[i] = bn.to_limbs(int.from_bytes(sig[32:], "little") % (1 << 256), NLIMBS)
        k = int.from_bytes(
            hashlib.sha512(sig[:32] + pub + msg).digest(), "little"
        ) % L
        h[i] = bn.to_limbs(k, NLIMBS)
        rx[i], ry[i] = bn.to_limbs(r_pt[0], NLIMBS), bn.to_limbs(r_pt[1], NLIMBS)
        ax[i], ay[i] = bn.to_limbs(a_pt[0], NLIMBS), bn.to_limbs(a_pt[1], NLIMBS)
        ok[i] = 1
    return s, h, rx, ry, ax, ay, ok


verify_kernel = eddsa_verify_kernel
