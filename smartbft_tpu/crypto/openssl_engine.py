"""OpenSSL-backed verify engine: the optimized native CPU path.

The reference's baseline crypto is Go's `crypto/ecdsa` — optimized native
code, not interpreted arithmetic.  The honest CPU counterpart here is
OpenSSL via the `cryptography` wheel.  This engine is both the fair
baseline for the TPU benchmarks and a production-grade CPU fallback for
deployments without an accelerator.

Supports the P-256 and Ed25519 schemes (OpenSSL has no BLS12-381; the BLS
provider's host path covers that baseline).
"""

from __future__ import annotations

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PublicKey
from cryptography.hazmat.primitives.asymmetric.utils import encode_dss_signature

from . import ed25519, p256
from .provider import HostVerifyEngine


class OpenSSLVerifyEngine(HostVerifyEngine):
    """Sequential native verification through the `cryptography` wheel.

    Same engine surface as `HostVerifyEngine` (which supplies the verify
    loop + stats bookkeeping); only the per-item backend differs.
    """

    def __init__(self, scheme=p256):
        if scheme is p256:
            self._verify_one = self._verify_p256
        elif scheme is ed25519:
            self._verify_one = self._verify_ed25519
        else:
            raise ValueError("OpenSSLVerifyEngine supports p256 and ed25519")
        super().__init__(scheme=scheme)
        self._key_cache: dict = {}

    # -- per-scheme backends -------------------------------------------------

    def _verify_p256(self, item) -> bool:
        msg, r, s, pub = item
        key = self._key_cache.get(pub)
        if key is None:
            try:
                key = ec.EllipticCurvePublicNumbers(
                    pub[0], pub[1], ec.SECP256R1()
                ).public_key()
            except ValueError:
                return False
            self._key_cache[pub] = key
        try:
            key.verify(encode_dss_signature(r, s), msg, ec.ECDSA(hashes.SHA256()))
            return True
        except (InvalidSignature, ValueError):
            return False

    def _verify_ed25519(self, item) -> bool:
        msg, sig, pub = item
        key = self._key_cache.get(pub)
        if key is None:
            try:
                key = Ed25519PublicKey.from_public_bytes(pub)
            except ValueError:
                return False
            self._key_cache[pub] = key
        try:
            key.verify(sig, msg)
            return True
        except (InvalidSignature, ValueError):
            return False
