"""NIST P-256 ECDSA: batched TPU verification, host-side signing.

The verify kernel replaces the reference's per-signature goroutine fan-out
(/root/reference/internal/bft/view.go:519-551 spawns one goroutine per
commit vote, each doing one ``crypto/ecdsa`` verify).  Here a whole quorum
— across commits, replicas, and in-flight sequences — is verified as ONE
jitted call:

* Field/scalar arithmetic: :mod:`bignum` Montgomery contexts for p and n.
* Curve arithmetic: Renes–Costello–Batina 2015 complete addition formulas
  (Algorithm 4, a = -3) in homogeneous projective coordinates — branch-free
  and identity-safe, exactly what XLA wants: one straight-line formula for
  add, double, and infinity alike.
* Double-scalar multiplication u1*G + u2*Q: 2-bit-windowed Strauss–Shamir
  as a single ``lax.scan`` over 128 digit pairs — two doublings, one gather
  from the 16-entry joint table {i*G + j*Q}, one complete addition per
  digit.  No data-dependent control flow anywhere.

Signing stays on the host (one signature per decision — never a hot path)
with RFC 6979 deterministic nonces.
"""

from __future__ import annotations

import hashlib
import hmac
import os as _os
import secrets

import numpy as np

import jax.numpy as jnp


from . import bignum as bn
from .bignum import MontCtx

# --- curve constants (FIPS 186-4, D.1.2.3) ---------------------------------

P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5

NLIMBS = 16
FP = MontCtx(P, NLIMBS)
FN = MontCtx(N, NLIMBS)

_B_MONT = FP.encode(B)
_G_MONT = np.stack([FP.encode(GX), FP.encode(GY), FP.one_mont])
_INF_MONT = np.stack([FP.zero, FP.one_mont, FP.zero])


# ---------------------------------------------------------------------------
# projective curve ops (points are (..., 3, NLIMBS) Montgomery-domain arrays)
# ---------------------------------------------------------------------------

def point_add(p, q):
    """Complete addition, RCB15 Algorithm 4 (a = -3).

    Valid for every input pair: distinct points, doubling, and the identity
    (0 : 1 : 0).  12 field mults + 2 mults by b + 29 add/subs — but
    level-scheduled: independent ops stack into single grouped Montgomery
    calls (4 mul groups + 11 add/sub groups of sequential depth), ~3x
    fewer carry chains than executing the algorithm's 43 ops in sequence.
    The math is the original sequence SSA-renamed; nothing is reordered
    across a data dependency.
    """
    f = FP
    b_m = jnp.asarray(_B_MONT)
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    x2, y2, z2 = q[..., 0, :], q[..., 1, :], q[..., 2, :]

    # L1: cross-term preadds
    a1, a2, a4, a5, a7, a8 = bn.grouped(
        f.add, [(x1, y1), (x2, y2), (y1, z1), (y2, z2), (x1, z1), (x2, z2)]
    )
    # L2: all six products of the inputs
    t0, t1, t2, m1, m2, m3 = bn.grouped(
        f.mul, [(x1, x2), (y1, y2), (z1, z2), (a1, a2), (a4, a5), (a7, a8)]
    )
    # L3: pair sums + first doublings
    a3, a6, a9, u1, w1 = bn.grouped(
        f.add, [(t0, t1), (t1, t2), (t0, t2), (t2, t2), (t0, t0)]
    )
    # L4: Karatsuba recoveries
    t3, t4, y3a = bn.grouped(f.sub, [(m1, a3), (m2, a6), (m3, a9)])
    u2, w2 = bn.grouped(f.add, [(u1, t2), (w1, t0)])  # 3*t2, 3*t0
    # L5: the two b-multiples
    zb, yb = bn.grouped(f.mul, [(b_m, t2), (b_m, y3a)])
    # L6
    x3a, t0b, y3b = bn.grouped(f.sub, [(y3a, zb), (w2, u2), (yb, u2)])
    # L7
    z3a = f.add(x3a, x3a)
    y3c = f.sub(y3b, t0)
    # L8
    x3b, v1 = bn.grouped(f.add, [(x3a, z3a), (y3c, y3c)])
    # L9
    x3c, y3d = bn.grouped(f.add, [(t1, x3b), (v1, y3c)])
    z3b = f.sub(t1, x3b)
    # L10: all six closing products
    p1, p2, p3, p4, p5, p6 = bn.grouped(
        f.mul,
        [(t4, y3d), (t0b, y3d), (x3c, z3b), (t3, x3c), (t4, z3b), (t3, t0b)],
    )
    # L11
    y3, z3 = bn.grouped(f.add, [(p3, p2), (p5, p6)])
    x3 = f.sub(p4, p1)
    return jnp.stack([x3, y3, z3], axis=-2)


def point_double(p):
    """Complete doubling, RCB15 Algorithm 6 (a = -3).

    Valid for every input, including the identity.  8M + 3S + 2 mults by b
    versus the general addition's 12M + 2mb — and the squarings go through
    :func:`bignum.square_columns` at ~half the lane-mult cost.  Level-
    scheduled like :func:`point_add`: 4 mul groups + 8 add/sub groups.
    """
    f = FP
    b_m = jnp.asarray(_B_MONT)
    x, y, z = p[..., 0, :], p[..., 1, :], p[..., 2, :]

    t0, t1, t2 = bn.grouped1(f.square, [x, y, z])
    xy, xz, yz = bn.grouped(f.mul, [(x, y), (x, z), (y, z)])
    # doublings + first steps of the 3x chains
    t3, z3a, yz2, t2a, t0a = bn.grouped(
        f.add, [(xy, xy), (xz, xz), (yz, yz), (t2, t2), (t0, t0)]
    )
    t2_3, t0_3 = bn.grouped(f.add, [(t2a, t2), (t0a, t0)])
    bt2, bz3 = bn.grouped(f.mul, [(b_m, t2), (b_m, z3a)])
    y3a, z3b, t0d = bn.grouped(
        f.sub, [(bt2, z3a), (bz3, t2_3), (t0_3, t2_3)]
    )
    y3a2 = f.add(y3a, y3a)
    z3c = f.sub(z3b, t0)
    y3b, z3c2 = bn.grouped(f.add, [(y3a2, y3a), (z3c, z3c)])
    z3d, y3c = bn.grouped(f.add, [(z3c2, z3c), (t1, y3b)])
    x3a = f.sub(t1, y3b)
    y3d, x3b, t0b, zz, zt = bn.grouped(
        f.mul,
        [(x3a, y3c), (x3a, t3), (t0d, z3d), (yz2, z3d), (yz2, t1)],
    )
    y3, zt2 = bn.grouped(f.add, [(y3d, t0b), (zt, zt)])
    x3 = f.sub(x3b, zz)
    z3 = f.add(zt2, zt2)
    return jnp.stack([x3, y3, z3], axis=-2)


def is_on_curve(xm, ym):
    """y^2 == x^3 - 3x + b in Montgomery domain; (...,) uint32 mask."""
    f = FP
    lhs = f.mul(ym, ym)
    x3 = f.mul(f.mul(xm, xm), xm)
    threex = f.add(f.add(xm, xm), xm)
    rhs = f.add(f.sub(x3, threex), jnp.asarray(_B_MONT))
    return bn.eq(lhs, rhs)


def shamir_double_scalar(u1, u2, q):
    """u1*G + u2*Q, 2-bit-windowed Shamir: 128 digits x (2 dbl + 1 add).

    u1/u2: (..., NLIMBS) standard-domain scalars; q: (..., 3, NLIMBS) Mont
    domain.  The 16-entry joint table {i*G + j*Q} builds in three stacked
    point_add depths (the 16 combination adds share ONE grouped call).
    """
    g = jnp.broadcast_to(jnp.asarray(_G_MONT), q.shape)
    inf = jnp.broadcast_to(jnp.asarray(_INF_MONT), q.shape)
    two = point_add(jnp.stack([g, q]), jnp.stack([g, q]))
    three = point_add(two, jnp.stack([g, q]))
    table = bn.joint_table(
        point_add, [inf, g, two[0], three[0]], [inf, q, two[1], three[1]]
    )  # (..., 16, 3, n); entry 4i+j = i*G + j*Q
    return bn.shamir_scan_w(
        point_add, table, inf,
        bn.digits_msb(u1, 128, 2), bn.digits_msb(u2, 128, 2), width=2,
        point_double=point_double,
    )


def ecdsa_verify_kernel(e, r, s, qx, qy):
    """Batched ECDSA-P256 verification.  Pure, jittable.

    All inputs are (..., NLIMBS) uint32 limb vectors in the *standard*
    domain: e = 256-bit truncated message hash, (r, s) the signature,
    (qx, qy) the signer's affine public key.  Returns a (...,) uint32
    validity mask.  Invalid signatures yield 0 — never an exception — so a
    whole quorum batch survives one bad vote (the protocol layer maps the
    mask back to per-replica verdicts).
    """
    n_arr = jnp.asarray(FN.N)

    # 1 <= r, s < n
    r_ok = (jnp.uint32(1) - bn.is_zero(r)) * (jnp.uint32(1) - bn.geq(r, n_arr))
    s_ok = (jnp.uint32(1) - bn.is_zero(s)) * (jnp.uint32(1) - bn.geq(s, n_arr))

    # scalars: u1 = e/s, u2 = r/s (mod n)
    e_red = FN.reduce_once(e)  # e < 2^256 < 2n
    w = FN.inv(FN.to_mont(s))
    u1 = FN.from_mont(FN.mul(FN.to_mont(e_red), w))
    u2 = FN.from_mont(FN.mul(FN.to_mont(r), w))

    # curve: R = u1*G + u2*Q
    xm, ym = FP.to_mont(qx), FP.to_mont(qy)
    oncurve = is_on_curve(xm, ym)
    qpt = jnp.stack([xm, ym, jnp.broadcast_to(jnp.asarray(FP.one_mont), xm.shape)],
                    axis=-2)
    acc = shamir_double_scalar(u1, u2, qpt)

    xr, zr = acc[..., 0, :], acc[..., 2, :]
    not_inf = jnp.uint32(1) - bn.is_zero(zr)
    # Projective comparison — no field inversion of zr.  With x_aff =
    # xr/zr < p and p < 2n, "x_aff mod n == r" is exactly
    # x_aff ∈ {r, r+n} ∩ [0, p), and each candidate c tests as
    # c~ * zr == xr in the Montgomery domain (zr != 0 is masked above).
    # This replaces a 256-bit Fermat inversion with four multiplies.
    c = bn.add_raw(r, n_arr, NLIMBS + 1)
    c_in_range = (c[..., NLIMBS] == 0).astype(jnp.uint32)
    c16 = c[..., :NLIMBS]
    _, c_borrow = bn.sub_borrow(c16, jnp.asarray(FP.N))
    c_ok = c_in_range * c_borrow  # r + n < p
    r2 = jnp.asarray(FP.R2)
    r_m, c_m = bn.grouped(FP.mul, [(r, r2), (c16, r2)])
    m_r, m_c = bn.grouped(FP.mul, [(r_m, zr), (c_m, zr)])
    match = jnp.maximum(bn.eq(m_r, xr), c_ok * bn.eq(m_c, xr))
    return match * not_inf * r_ok * s_ok * oncurve


# ---------------------------------------------------------------------------
# host-side reference arithmetic (Python ints) — keygen, sign, CPU verify
# ---------------------------------------------------------------------------

def _inv_mod(a: int, m: int) -> int:
    return pow(a, -1, m)


def _point_add_int(p1, p2):
    """Affine addition over GF(P); None is the identity."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2 and (y1 + y2) % P == 0:
        return None
    if p1 == p2:
        lam = (3 * x1 * x1 - 3) * _inv_mod(2 * y1, P) % P
    else:
        lam = (y2 - y1) * _inv_mod(x2 - x1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def scalar_mult_int(k: int, point):
    """Double-and-add with Python ints (host-side; keygen/sign only)."""
    acc = None
    addend = point
    while k:
        if k & 1:
            acc = _point_add_int(acc, addend)
        addend = _point_add_int(addend, addend)
        k >>= 1
    return acc


def keygen(seed: bytes | None = None):
    """Returns (private_scalar, (qx, qy)).  Deterministic given a seed."""
    if seed is None:
        d = secrets.randbelow(N - 1) + 1
    else:
        d = (int.from_bytes(hashlib.sha256(b"p256-keygen" + seed).digest(), "big")
             % (N - 1)) + 1
    return d, scalar_mult_int(d, (GX, GY))


def _rfc6979_nonce(priv: int, h1: bytes) -> int:
    """Deterministic nonce, RFC 6979 §3.2 with HMAC-SHA256."""
    holen = 32
    bx = priv.to_bytes(32, "big") + (
        int.from_bytes(h1, "big") % N
    ).to_bytes(32, "big")
    v = b"\x01" * holen
    k = b"\x00" * holen
    k = hmac.new(k, v + b"\x00" + bx, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + bx, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def sign(priv: int, msg: bytes):
    """ECDSA-SHA256 sign; returns (r, s) Python ints.  Host-side."""
    h1 = hashlib.sha256(msg).digest()
    e = int.from_bytes(h1, "big")
    while True:
        k = _rfc6979_nonce(priv, h1)
        pt = scalar_mult_int(k, (GX, GY))
        r = pt[0] % N
        if r == 0:
            h1 = hashlib.sha256(h1).digest()
            continue
        s = _inv_mod(k, N) * (e + r * priv) % N
        if s == 0:
            h1 = hashlib.sha256(h1).digest()
            continue
        return r, s


def verify_int(pub, msg: bytes, r: int, s: int) -> bool:
    """Pure-Python ECDSA verify — the CPU reference the kernel is tested
    against and the single-threaded baseline for the benchmark harness."""
    if not (1 <= r < N and 1 <= s < N):
        return False
    e = int.from_bytes(hashlib.sha256(msg).digest(), "big")
    w = _inv_mod(s, N)
    u1, u2 = e * w % N, r * w % N
    pt = _point_add_int(
        scalar_mult_int(u1, (GX, GY)), scalar_mult_int(u2, pub)
    )
    if pt is None:
        return False
    return pt[0] % N == r


# ---------------------------------------------------------------------------
# host <-> kernel marshalling
# ---------------------------------------------------------------------------

def hash_to_limbs(msg: bytes) -> np.ndarray:
    """SHA-256(msg) as a 16-limb vector (the kernel's ``e`` input)."""
    return bn.to_limbs(int.from_bytes(hashlib.sha256(msg).digest(), "big"), NLIMBS)


def verify_inputs(items) -> tuple[np.ndarray, ...]:
    """[(msg, r, s, (qx,qy)), ...] -> stacked (B,16) kernel inputs."""
    e = np.stack([hash_to_limbs(m) for m, _, _, _ in items])
    r = bn.batch_to_limbs([r for _, r, _, _ in items], NLIMBS)
    s = bn.batch_to_limbs([s for _, _, s, _ in items], NLIMBS)
    qx = bn.batch_to_limbs([q[0] for _, _, _, q in items], NLIMBS)
    qy = bn.batch_to_limbs([q[1] for _, _, _, q in items], NLIMBS)
    return e, r, s, qx, qy


# ---------------------------------------------------------------------------
# scheme API (uniform surface the verify engines/providers program against)
# ---------------------------------------------------------------------------

try:  # native signing fast path: the reference signs with Go's native
    # crypto/ecdsa; pure-Python signing costs ~9.5 ms and dominated the
    # cluster protocol loop, OpenSSL via the cryptography wheel does it in
    # ~60 us.  Verification paths are unaffected (that is the TPU's job).
    from cryptography.hazmat.primitives import hashes as _cg_hashes
    from cryptography.hazmat.primitives.asymmetric import ec as _cg_ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature as _cg_decode_dss,
    )

    import functools as _ft

    @_ft.lru_cache(maxsize=256)  # bounded: like ed25519._expand_key
    def _cg_key(priv: int):
        return _cg_ec.derive_private_key(priv, _cg_ec.SECP256R1())

    def _sign_native(priv: int, msg: bytes):
        der = _cg_key(priv).sign(msg, _cg_ec.ECDSA(_cg_hashes.SHA256()))
        return _cg_decode_dss(der)
except Exception as _exc:  # pragma: no cover — wheel absent/broken
    import logging as _logging

    _logging.getLogger("smartbft_tpu.crypto").warning(
        "native P-256 signer unavailable (%s); falling back to the "
        "~150x slower pure-Python signer", _exc,
    )
    _sign_native = None


def sign_raw(priv: int, msg: bytes) -> bytes:
    """Sign and encode as fixed 64-byte big-endian r || s.

    Uses the native OpenSSL signer when available (non-deterministic k,
    like the reference's crypto/ecdsa); :func:`sign` remains the
    deterministic RFC 6979 pure-Python reference.  Set
    ``SMARTBFT_DETERMINISTIC_SIGN=1`` to force the RFC 6979 path so
    signature bytes for identical (priv, msg) are reproducible across
    environments regardless of whether the cryptography wheel imports."""
    if _sign_native is not None and _os.environ.get(
        "SMARTBFT_DETERMINISTIC_SIGN"
    ) != "1":
        r, s = _sign_native(priv, msg)
    else:
        r, s = sign(priv, msg)
    return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def make_item(msg: bytes, sig: bytes, pub):
    if len(sig) != 64:
        raise ValueError("bad signature length")
    return (msg, int.from_bytes(sig[:32], "big"),
            int.from_bytes(sig[32:], "big"), pub)


def verify_item(item) -> bool:
    msg, r, s, pub = item
    return verify_int(pub, msg, r, s)


verify_kernel = ecdsa_verify_kernel
