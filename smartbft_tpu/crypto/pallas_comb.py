"""Static-key comb-table Pallas kernel: P-256 verify in 32 point-op levels.

The fused scan kernel (:mod:`pallas_ecdsa`) treats every lane's public key
as unknown data: it builds a 16-entry joint table per launch and walks 128
Strauss–Shamir windows — 256 doublings + 128 adds per verify.  But in a BFT
deployment both bases are STATIC: G is the curve generator and Q is one of
n replica keys fixed at configuration time (the reference validates a
quorum of known-consenter signatures, /root/reference/internal/bft/
view.go:537-541, viewchanger.go:696-727).  This kernel exploits that:

* **Lim–Lee combs** (w=8 teeth, stride d=32): the host precomputes, once
  per key, a 256-entry table ``T[idx] = Σ_t bit_t(idx)·2^(32t)·K``.  The
  scan then needs only ``d=32`` iterations of (1 complete doubling + 2
  complete additions) for the full ``u1·G + u2·Q`` — 32 doublings + 64
  adds, a ~4× cut in point-operation count.
* **Table lookups ride the MXU.**  TPU has no per-lane gather; instead the
  per-lane digit becomes a one-hot column and the lookup is a matmul:
  ``dot(table (rows,256), onehot (256,B))``.  Entries are stored as SPLIT
  BYTES (16-bit limbs -> lo/hi rows) in bfloat16, so every product is
  0/1 × (<256) — exact in bf16×bf16->f32 — and the n-key table stack
  stays small: (npad·96, 256) bf16 = npad·49KB of VMEM.
* **Key validation moves to registration.**  The engine checks each
  replica key is on-curve ONCE at registration (host ints), so the
  per-signature on-curve check disappears from the kernel.

Layout/arithmetic building blocks (limb-major (NL, B), Montgomery fields,
complete RCB15 formulas, the Fermat inversion) are shared with
:mod:`pallas_ecdsa`.
"""

from __future__ import annotations

import functools
import hashlib
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from . import p256
from .bignum import to_limbs
from .p256 import B as CURVE_B, FP, GX, GY, N, NLIMBS, P
from .pallas_ecdsa import (
    INV_DIGITS,
    LIMB_BITS,
    NL,
    _add_rows,
    _B_MONT,
    _ccol,
    _eq,
    _Fld,
    _grp,
    _inv_n,
    _is_zero,
    _limbs,
    _N,
    _N_NPRIME,
    _N_ONE,
    _N_R2,
    _P,
    _P_NPRIME,
    _P_ONE,
    _P_R2,
    _point_add,
    _point_double,
    _select,
    _sub_borrow,
)

#: comb teeth (bits per table index) and stride (scan iterations)
TEETH = 8
STRIDE = 32  # = 256 / TEETH
TSIZE = 1 << TEETH  # 256 table entries per key
#: table rows: [0:48] = low bytes of (X,Y,Z) Montgomery limbs, [48:96] = high
ROWS = 6 * NL  # 96


# ---------------------------------------------------------------------------
# host-side table precomputation (Python ints; once per key per process)
# ---------------------------------------------------------------------------


def is_on_curve_int(pub) -> bool:
    """Host check y² = x³ - 3x + b (mod p) for an affine public key."""
    x, y = pub
    if not (0 <= x < P and 0 <= y < P):
        return False
    return (y * y - (x * x * x - 3 * x + CURVE_B)) % P == 0


def _comb_entries(point) -> list:
    """All 2^TEETH subset sums of {2^(STRIDE·t)·point : t < TEETH}."""
    bases = [point]
    for _ in range(TEETH - 1):
        b = bases[-1]
        for _ in range(STRIDE):
            b = p256._point_add_int(b, b)
        bases.append(b)
    table = [None] * TSIZE
    for idx in range(1, TSIZE):
        low = idx & -idx
        table[idx] = p256._point_add_int(table[idx ^ low], bases[low.bit_length() - 1])
    return table


def build_table(pub) -> np.ndarray:
    """(ROWS, TSIZE) float32 comb table for one affine point.

    Column = table index; rows split each Montgomery limb into lo/hi bytes
    so a one-hot matmul in bf16 selects entries exactly.  The identity
    (entry 0) is stored as the projective identity (0 : 1 : 0) in the
    Montgomery domain, which the complete addition formulas absorb without
    any masking.
    """
    entries = _comb_entries(pub)
    out = np.zeros((ROWS, TSIZE), dtype=np.float32)
    one_m = FP.encode(1)
    for idx, ent in enumerate(entries):
        if ent is None:
            limbs = np.concatenate([np.zeros(NL, np.uint32), one_m,
                                    np.zeros(NL, np.uint32)])
        else:
            limbs = np.concatenate([FP.encode(ent[0]), FP.encode(ent[1]), one_m])
        out[:48, idx] = limbs & 0xFF
        out[48:, idx] = limbs >> 8
    return out


@functools.lru_cache(maxsize=1)
def g_table() -> np.ndarray:
    """The generator's comb table (shared by every verification)."""
    return build_table((GX, GY))


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------


def pack_items(items, registry) -> tuple:
    """Fast host prep: items -> ((B,32) uint8 e/r/s little-endian, kidx).

    Transfers to the device at 96 B/sig instead of the 192 B/sig of padded
    uint32 limb arrays (the tunnel link is bandwidth-bound at large
    batches), and avoids the pure-Python per-limb conversion loops of
    :func:`p256.verify_inputs` (~17 us/sig) in favor of C-speed
    ``int.to_bytes`` + ``frombuffer`` (~1 us/sig).  Raises ValueError via
    the registry for unregistrable keys.
    """
    B = len(items)
    e8 = np.empty((B, 32), np.uint8)
    r8 = np.empty((B, 32), np.uint8)
    s8 = np.empty((B, 32), np.uint8)
    kidx = np.empty(B, np.int32)
    for i, (msg, r, s, pub) in enumerate(items):
        e8[i] = np.frombuffer(hashlib.sha256(msg).digest()[::-1], np.uint8)
        r8[i] = np.frombuffer(r.to_bytes(32, "little"), np.uint8)
        s8[i] = np.frombuffer(s.to_bytes(32, "little"), np.uint8)
        kidx[i] = registry.register(pub)
    return e8, r8, s8, kidx


def _maybe_unpack(a):
    """(B,32) uint8 little-endian bytes -> (B,16) uint32 limbs; uint32
    limb arrays pass through."""
    a = jnp.asarray(a)
    if a.dtype == jnp.uint8:
        a32 = a.astype(jnp.uint32)
        return a32[..., 0::2] | (a32[..., 1::2] << 8)
    return a


class _InvOps:
    """dig_at shim for the shared Fermat inversion (static-exponent reads)."""

    def __init__(self, digs_ref):
        self._digs_ref = digs_ref

    def dig_at(self, i):
        return self._digs_ref[0, i]  # SMEM scalar read


def _comb_digits(u, nb: int) -> list:
    """(NL, B) scalar -> STRIDE (B,) int32 comb indices, MSB-first.

    Row k selects column c = STRIDE-1-k: bits {c + STRIDE·t : t < TEETH}.
    """
    rows = []
    for k in range(STRIDE):
        c = STRIDE - 1 - k
        idx = jnp.zeros((nb,), jnp.uint32)
        for t in range(TEETH):
            p = c + STRIDE * t
            limb, off = p // LIMB_BITS, p % LIMB_BITS
            idx = idx | (((u[limb] >> jnp.uint32(off)) & jnp.uint32(1))
                         << jnp.uint32(t))
        rows.append(idx.astype(jnp.int32))
    return rows


def _sel_rows(table_f32):
    """(ROWS, B) f32 selected columns -> (3, NL, B) uint32 point."""
    lo = table_f32[:48, :]
    hi = table_f32[48:, :]
    # exact: values < 2^16; Mosaic has no f32->uint32 cast, go via int32
    limbs = (lo + hi * 256.0).astype(jnp.int32).astype(jnp.uint32)
    return jnp.stack([limbs[0:NL], limbs[NL:2 * NL], limbs[2 * NL:3 * NL]],
                     axis=-3)


def _kernel(nkeys, digs_ref, e_ref, r_ref, s_ref, kidx_ref, gtab_ref,
            qtab_ref, out_ref, idx_scratch):
    e, r, s = e_ref[:], r_ref[:], s_ref[:]
    kidx = kidx_ref[0, :]
    nb = e.shape[-1]
    fp = _Fld(_P, _P_NPRIME, nb)
    fn = _Fld(_N, _N_NPRIME, nb)
    b_m = _ccol(_B_MONT, nb)
    one_p = _ccol(_P_ONE, nb)
    one_n = _ccol(_N_ONE, nb)
    p_r2 = _ccol(_P_R2, nb)
    n_r2 = _ccol(_N_R2, nb)
    one_raw = _ccol(_limbs(1), nb)
    zero = jnp.zeros((NL, nb), jnp.uint32)
    inf = jnp.stack([zero, one_p, zero], axis=-3)

    # 1 <= r, s < n
    _, rb = _sub_borrow(r, fn.N)
    _, sb = _sub_borrow(s, fn.N)
    r_ok = (jnp.uint32(1) - _is_zero(r)) * rb
    s_ok = (jnp.uint32(1) - _is_zero(s)) * sb

    # u1 = e/s, u2 = r/s (mod n); shared Fermat inversion
    d, eb = _sub_borrow(e, fn.N)
    e_red = _select(eb, e, d)
    s_m, r_m_n, e_m_n = _grp(fn.mul, [(s, n_r2), (r, n_r2), (e_red, n_r2)])
    w = _inv_n(fn, one_n, s_m, _InvOps(digs_ref))
    u1m, u2m = _grp(fn.mul, [(e_m_n, w), (r_m_n, w)])
    u1, u2 = _grp(fn.mul, [(u1m, one_raw), (u2m, one_raw)])

    # stash comb digits: rows [0:STRIDE) = u1/G, [STRIDE:2*STRIDE) = u2/Q
    for k, v in enumerate(_comb_digits(u1, nb)):
        idx_scratch[k, :] = v
    for k, v in enumerate(_comb_digits(u2, nb)):
        idx_scratch[STRIDE + k, :] = v

    gtab = gtab_ref[:]
    qtab = qtab_ref[:]
    iota_t = lax.broadcasted_iota(jnp.int32, (TSIZE, nb), 0)

    def scan_body(i, acc):
        acc = _point_double(fp, b_m, acc)
        gd = idx_scratch[pl.ds(i, 1), :][0]
        qd = idx_scratch[pl.ds(i + STRIDE, 1), :][0]
        oh_g = (iota_t == gd[None, :]).astype(jnp.bfloat16)
        oh_q = (iota_t == qd[None, :]).astype(jnp.bfloat16)
        sel_g = jnp.dot(gtab, oh_g, preferred_element_type=jnp.float32)
        aq = jnp.dot(qtab, oh_q, preferred_element_type=jnp.float32)
        # per-key masked reduce over the stacked table rows (no gather,
        # no reshape across sublane tiles: nkeys static slices)
        sq = jnp.zeros((ROWS, nb), jnp.float32)
        for k in range(nkeys):
            mask = (kidx == k).astype(jnp.float32)[None, :]
            sq = sq + aq[k * ROWS:(k + 1) * ROWS, :] * mask
        tg = _sel_rows(sel_g)
        tq = _sel_rows(sq)
        # complete formulas absorb identities and coincidences, so the two
        # table adds need no special cases
        acc = _point_add(fp, b_m, acc, tg)
        return _point_add(fp, b_m, acc, tq)

    acc = lax.fori_loop(0, STRIDE, scan_body, inf)
    xr, zr = acc[..., 0, :, :], acc[..., 2, :, :]

    not_inf = jnp.uint32(1) - _is_zero(zr)
    # projective comparison: x_aff in {r, r+n} ∩ [0, p)
    c17 = _add_rows(r, fn.N)
    c_in_range = (c17[NL] == 0).astype(jnp.uint32)
    c16 = c17[:NL]
    _, c_lt_p = _sub_borrow(c16, fp.N)
    c_ok = c_in_range * c_lt_p
    r_mp, c_mp = _grp(fp.mul, [(r, p_r2), (c16, p_r2)])
    mr, mc = _grp(fp.mul, [(r_mp, zr), (c_mp, zr)])
    match = _eq(mr, xr) | (c_ok * _eq(mc, xr))
    out_ref[:] = (match * not_inf * r_ok * s_ok)[None, :]


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def ecdsa_verify_comb(e, r, s, kidx, gtab, qtab, tile: int = 128,
                      interpret: bool = False):
    """Batched P-256 verify against registered keys.

    ``e, r, s``: (B, 16) standard-domain uint32 limbs (as
    :func:`p256.verify_inputs`); ``kidx``: (B,) int32 index of each lane's
    key in the table stack; ``gtab``: (96, 256) generator comb table;
    ``qtab``: (nkeys*96, 256) stacked per-key comb tables (both float32 or
    bfloat16; cast to bf16 for the MXU one-hot select).  Returns (B,)
    uint32 validity mask.  Padded lanes (r = s = 0) always fail.
    """
    from jax.experimental.pallas import tpu as pltpu

    if tile % 128 and not interpret:
        raise ValueError(f"tile must be a multiple of 128 lanes, got {tile}")
    if qtab.shape[0] % ROWS:
        raise ValueError("qtab row count must be a multiple of 96")
    nkeys = qtab.shape[0] // ROWS

    e, r, s = _maybe_unpack(e), _maybe_unpack(r), _maybe_unpack(s)
    bsz = e.shape[0]
    pad = (-bsz) % tile
    if pad:
        e, r, s = (jnp.pad(jnp.asarray(a), ((0, pad), (0, 0)))
                   for a in (e, r, s))
        kidx = jnp.pad(jnp.asarray(kidx), (0, pad))
    total = e.shape[0]
    args = [jnp.transpose(jnp.asarray(a)).astype(jnp.uint32)
            for a in (e, r, s)]
    kidx = jnp.asarray(kidx, jnp.int32).reshape(1, total)
    gtab = jnp.asarray(gtab, jnp.bfloat16)
    qtab = jnp.asarray(qtab, jnp.bfloat16)

    spec = pl.BlockSpec((NL, tile), lambda i: (0, i))
    dig_spec = pl.BlockSpec((1, INV_DIGITS.shape[0]), lambda i: (0, 0),
                            memory_space=pltpu.SMEM)
    kidx_spec = pl.BlockSpec((1, tile), lambda i: (0, i))
    gtab_spec = pl.BlockSpec((ROWS, TSIZE), lambda i: (0, 0))
    qtab_spec = pl.BlockSpec((nkeys * ROWS, TSIZE), lambda i: (0, 0))

    out = pl.pallas_call(
        functools.partial(_kernel, nkeys),
        out_shape=jax.ShapeDtypeStruct((1, total), jnp.uint32),
        grid=(total // tile,),
        in_specs=[dig_spec, spec, spec, spec, kidx_spec, gtab_spec,
                  qtab_spec],
        out_specs=pl.BlockSpec((1, tile), lambda i: (0, i)),
        scratch_shapes=[pltpu.VMEM((2 * STRIDE, tile), jnp.int32)],
        interpret=interpret,
    )(jnp.asarray(INV_DIGITS).reshape(1, -1), *args, kidx, gtab, qtab)
    return out[0, :bsz]


# ---------------------------------------------------------------------------
# key registry + engine adapter
# ---------------------------------------------------------------------------


class CombRegistryFull(ValueError):
    """The registry's key cap was reached — NOT an invalid key.

    Callers distinguish this from key-validation failures: a full registry
    only means this engine's comb path can't serve the extra keys (the
    generic kernel still verifies them fine), whereas an invalid key is a
    configuration error worth failing loudly over.
    """


def _p256_validate(pub):
    if not is_on_curve_int(pub):
        raise ValueError("public key is not on the P-256 curve")


class CombKeyRegistry:
    """pub -> table index; tables built once per key, stacked and padded.

    Scheme-agnostic: ``validate``/``build`` default to the P-256 curve
    check and comb builder; :mod:`pallas_ed25519` instantiates it with
    Edwards equivalents.  The stack is padded to a power-of-two key count
    so jit re-traces at most log2(cap) times as membership grows.
    Padding tables are zero — their Z rows decode to 0 so any (buggy)
    reference to a padded index yields the point at infinity and a failed
    verify, never a false accept.
    """

    def __init__(self, cap: int = 128, validate=None, build=None):
        self.cap = cap
        self._validate = validate if validate is not None else _p256_validate
        self._build = build if build is not None else build_table
        self._index: dict = {}
        self._tables: list[np.ndarray] = []
        self._stack: np.ndarray | None = None

    def __len__(self) -> int:
        return len(self._tables)

    def register(self, pub) -> int:
        """Index for ``pub`` (validating + building its table on first use).

        Raises ValueError for invalid keys or when the cap is exceeded.
        """
        idx = self._index.get(pub)
        if idx is not None:
            return idx
        if len(self._tables) >= self.cap:
            raise CombRegistryFull(f"comb key registry full ({self.cap})")
        self._validate(pub)
        idx = len(self._tables)
        self._index[pub] = idx
        self._tables.append(self._build(pub))
        self._stack = None
        return idx

    def index_of(self, pub):
        """Registered index or None (no side effects)."""
        return self._index.get(pub)

    def stacked(self) -> np.ndarray:
        """(npad*96, 256) float32 stack, npad = next power of two."""
        if self._stack is None:
            npad = 1
            while npad < len(self._tables):
                npad *= 2
            stack = np.zeros((npad * ROWS, TSIZE), np.float32)
            for i, t in enumerate(self._tables):
                stack[i * ROWS:(i + 1) * ROWS] = t
            self._stack = stack
        return self._stack


class CombVerifier:
    """Engine adapter: items -> comb-kernel launch with cached device tables.

    ``verify(items)`` returns a bool list, or None when any item's key is
    unregistrable (caller falls back to the generic kernel).  The prewarm /
    device-table caching / pad-and-launch scaffolding is scheme-agnostic;
    subclasses (pallas_ed25519.Ed25519CombVerifier) override the four
    ``_...`` hooks.
    """

    def __init__(self, tile: int = 128, cap: int = 128):
        self.registry = self._make_registry(cap)
        self.tile = tile
        self._pending_prewarm: list = []
        self._dev_version: int = -1
        self._dev_gtab = None
        self._dev_qtab = None
        # Engines overlap flushes via asyncio.to_thread, so concurrent
        # verify() calls can race first-use registration: two threads both
        # computing idx=len(tables) would bind different keys to one index,
        # making signatures verify against the wrong replica's key.  All
        # registry / prewarm / device-table mutation happens under this
        # lock; only the kernel launch itself runs outside it.
        self._reg_lock = threading.RLock()
        self._warned_full = False

    # -- scheme hooks (P-256 defaults) --------------------------------------

    def _make_registry(self, cap: int) -> CombKeyRegistry:
        return CombKeyRegistry(cap=cap)

    def _validate_key(self, pub) -> None:
        _p256_validate(pub)

    def _base_table(self) -> np.ndarray:
        return g_table()

    def _pack(self, items):
        """items -> ([(B,32) uint8 arrays...], ok-mask-or-None, kidx)."""
        e8, r8, s8, kidx = pack_items(items, self.registry)
        return [e8, r8, s8], None, kidx

    def _launch(self, arrays, ok, kidx, gtab, qtab):
        return ecdsa_verify_comb(*arrays, kidx, gtab, qtab, tile=self.tile)

    # -- shared scaffolding --------------------------------------------------

    def prewarm_keys(self, pubs) -> None:
        """Record a known key set (e.g. the whole keyring) to register
        before the first verify, so membership growth never re-traces
        mid-protocol.  Validation is EAGER (an invalid key raises here, at
        provider construction); table building is DEFERRED — it costs
        ~2.4 ms/key of host EC arithmetic, which engines on non-TPU
        backends (where the comb path never runs) must not pay.  If the
        set exceeds remaining registry capacity, the fitting prefix is
        still queued and CombRegistryFull reports the overflow — callers
        degrade those keys to the generic kernel."""
        pubs = list(pubs)
        for pub in pubs:
            self._validate_key(pub)
        with self._reg_lock:
            known = set(self.registry._index) | set(self._pending_prewarm)
            room = self.registry.cap - len(known)
            fitting, overflow = [], 0
            for pub in pubs:
                if pub in known:
                    continue
                if len(fitting) < room:
                    fitting.append(pub)
                    known.add(pub)
                else:
                    overflow += 1
            # Queue what fits BEFORE signalling overflow: those keys still
            # get their tables built up front, avoiding the mid-protocol
            # build/retrace stall prewarm exists to prevent.  Chunks whose
            # signers are all registered keep the comb path; any chunk
            # containing an overflow key degrades wholly to the generic
            # kernel (verify short-circuits it rather than splitting the
            # launch).
            self._pending_prewarm.extend(fitting)
            if overflow:
                raise CombRegistryFull(
                    f"comb key registry full ({self.registry.cap}): "
                    f"{overflow} key(s) beyond capacity "
                    f"({len(fitting)} queued)")

    def _warn_registry_full(self, exc) -> None:
        """Warn ONCE per verifier when registration hits a full registry
        (prewarm drain or first-use) — chunks carrying unregistrable keys
        silently riding the generic kernel would hide the fast path dying."""
        if not self._warned_full:
            self._warned_full = True
            import logging

            logging.getLogger("smartbft_tpu.crypto").warning(
                "comb key registry full at verify time; chunks with "
                "unregistered keys fall back to the generic verify "
                "kernel: %s", exc,
            )

    def _device_tables(self):
        version = len(self.registry)
        if version != self._dev_version:
            self._dev_gtab = jnp.asarray(self._base_table(), jnp.bfloat16)
            self._dev_qtab = jnp.asarray(self.registry.stacked(), jnp.bfloat16)
            self._dev_version = version
        return self._dev_gtab, self._dev_qtab

    def verify(self, items, pad_to: int):
        # Registry mutation (drain + first-use registration) and the
        # device-table snapshot happen under the lock; the per-item
        # hash/pack and the launch run outside it, so concurrent flushes
        # only serialize on the (once-per-key) table builds, not on every
        # chunk's O(n) hashing.
        chunk_pubs = {it[-1] for it in items}
        with self._reg_lock:
            if self._pending_prewarm:
                pending, self._pending_prewarm = self._pending_prewarm, []
                try:
                    for pub in pending:
                        self.registry.register(pub)
                except CombRegistryFull as exc:
                    # Other engine users filled the registry after our
                    # prewarm passed its cap check.  Warn like the
                    # construction-time overflow does, but keep going:
                    # chunks whose signers are all registered still ride
                    # the comb path.
                    self._warn_registry_full(exc)
            try:
                # O(distinct signers) lock-held work, not O(items): a
                # quorum wave repeats each replica's key thousands of times
                for pub in chunk_pubs:
                    self.registry.register(pub)
            except CombRegistryFull as exc:
                # An unregistrable key sends the WHOLE chunk to the generic
                # kernel (splitting the launch would double the fixed
                # per-launch cost).  This raises before any hashing, and
                # must not escape — the engine's failure guard would
                # misread it as a kernel transient.
                self._warn_registry_full(exc)
                return None
            except ValueError:
                return None  # invalid key: generic kernel
            gtab, qtab = self._device_tables()
        try:
            # every key is now registered, so _pack's register calls are
            # pure dict hits — no shared-state mutation outside the lock
            arrays, ok, kidx = self._pack(items)
        except ValueError:
            return None
        n = len(items)
        if pad_to > n:
            z = np.zeros((pad_to - n, 32), np.uint8)
            arrays = [np.concatenate([a, z]) for a in arrays]
            if ok is not None:
                ok = np.concatenate([ok, np.zeros(pad_to - n, np.uint32)])
            kidx = np.concatenate([kidx, np.zeros(pad_to - n, np.int32)])
        mask = self._launch(arrays, ok, kidx, gtab, qtab)
        return mask[:n]
