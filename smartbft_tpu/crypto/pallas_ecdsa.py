"""Fused Pallas TPU kernel: the ENTIRE batched P-256 ECDSA verify.

The XLA kernel (:mod:`p256`) stores bignums batch-major ``(B, 16)`` — the
16-limb axis lands in the VPU's 128-wide lane dimension, so every limb-wise
product uses ~16 of 128 lanes.  This kernel owns the layout instead:
**limb-major ``(..., 16, B)``** — limbs on sublanes, the batch filling all
128 lanes — and keeps the whole verification (Montgomery arithmetic, the
windowed Strauss–Shamir scan, the scalar inversion, curve checks, final
projective comparison) inside ONE ``pallas_call`` so no XLA-chosen layout
ever touches an intermediate.  Replaces the same reference hot path as
:func:`p256.ecdsa_verify_kernel` (one goroutine per commit-signature
verify, /root/reference/internal/bft/view.go:537-541).

Two compile-size disciplines keep the (fully unrolled) carry chains from
exploding the graph for either compiler:

* every value carries arbitrary LEADING axes, so independent field ops
  stack into one call (:func:`_grp` / :func:`_grp1`) — the
  level-scheduling trick of the XLA kernels, which here also divides the
  emitted op count by the group width;
* the 16-entry joint table is built by ONE stacked point addition, and
  the per-digit table select is a masked accumulation (no per-lane
  gather, which TPU lacks).

Pallas kernels may not capture array constants, so every bignum constant
is rebuilt inside the kernel from Python ints (scalar broadcasts), and the
static inversion-exponent digit string enters as a small operand.

This kernel is the DEFAULT engine path on TPU backends (see
provider.JaxVerifyEngine): :func:`ecdsa_verify` (grid over batch tiles,
pads internally) is selected automatically when the backend is a TPU,
forced on elsewhere with ``SMARTBFT_PALLAS=1``, disabled with
``SMARTBFT_PALLAS=0``.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .p256 import B as CURVE_B, GX, GY, N, NLIMBS, P

LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1
NL = NLIMBS  # 16 limbs of 16 bits


def _limbs(x: int, n: int = NL) -> tuple:
    out = []
    for _ in range(n):
        out.append(x & LIMB_MASK)
        x >>= LIMB_BITS
    assert x == 0
    return tuple(out)


R = 1 << (LIMB_BITS * NL)

_P = _limbs(P)
_N = _limbs(N)
_P_R2 = _limbs((R * R) % P)
_N_R2 = _limbs((R * R) % N)
_P_NPRIME = _limbs((-pow(P, -1, R)) % R)
_N_NPRIME = _limbs((-pow(N, -1, R)) % R)
_P_ONE = _limbs(R % P)
_N_ONE = _limbs(R % N)
_B_MONT = _limbs((CURVE_B * R) % P)
_GX_MONT = _limbs((GX * R) % P)
_GY_MONT = _limbs((GY * R) % P)

_INV_E = N - 2
_INV_NDIG = (_INV_E.bit_length() + 3) // 4
INV_DIGITS = np.array(
    [(_INV_E >> (4 * i)) & 15 for i in range((_INV_NDIG - 1), -1, -1)],
    dtype=np.int32,
)

import os as _os_w

#: joint Strauss-Shamir window width (2 or 3).  w=3 runs 86 iterations of
#: (3 doublings + 1 add + sel64) vs w=2's 128 x (2 dbl + 1 add + sel16) —
#: ~12% fewer sequential point-op levels, but the 64-way masked table
#: select costs more than the saved levels on v5e (A/B, cached compiles,
#: min-of-10: w2 31-35 us/sig vs w3 35+): the kernel is throughput-bound,
#: so w=2 stays the default; w=3 is kept for latency-bound hardware.
WINDOW = int(_os_w.environ.get("SMARTBFT_PALLAS_WINDOW", "2"))
if WINDOW not in (2, 3):
    raise ValueError("SMARTBFT_PALLAS_WINDOW must be 2 or 3")
NDIGITS = -(-256 // WINDOW)  # MSB-first digit count for 256-bit scalars
#: idx scratch rows, padded to a sublane multiple for the VMEM scratch
_NDIG_PAD = -(-NDIGITS // 8) * 8


# ---------------------------------------------------------------------------
# limb-major bignum core.  Values are (..., NL, B) uint32: limb axis
# second-to-last (sublanes), batch last (lanes); leading axes are free
# batch/group dims shared by the unrolled chains.
# ---------------------------------------------------------------------------


def _ccol(limbs: tuple, nb: int):
    """Python-int limb tuple -> (len, nb) uint32 from scalar fills only."""
    return jnp.stack([jnp.full((nb,), int(v), jnp.uint32) for v in limbs])


def _row(a, i):
    return a[..., i, :]


def _stack_rows(rows):
    return jnp.stack(rows, axis=-2)


def _shift_rows_up(x, s: int):
    """Shift rows toward higher limb index along axis -2 (zero fill)."""
    pad = [(0, 0)] * (x.ndim - 2) + [(s, 0), (0, 0)]
    return jnp.pad(x, pad)[..., : x.shape[-2], :]


def _resolve_prefix(x, m: int):
    """Kogge–Stone resolution of 0/1 residual carries (values <= 2^16):
    log2(m) (generate, propagate) steps instead of an m-step ripple —
    the carry chains are the kernel's only sequential dependency, so this
    roughly halves the critical path of every Montgomery op."""
    g = x >> LIMB_BITS  # 0/1 by precondition
    b = x & LIMB_MASK
    p = (b == LIMB_MASK).astype(jnp.uint32)
    G, P = g, p
    s = 1
    while s < m:
        G = G | (P & _shift_rows_up(G, s))
        P = P & _shift_rows_up(P, s)
        s <<= 1
    return (b + _shift_rows_up(G, 1)) & LIMB_MASK, G[..., m - 1, :]


import os as _os

#: 'ripple' (default) — fully unrolled sequential carry steps; measured
#: slightly faster than 'prefix' on v5e (38.1 vs 40.6 us/sig at batch
#: 4096): the kernel is throughput-bound, and Kogge–Stone's extra total
#: ops outweigh its shorter dependence chains.  'prefix' compiles ~25%
#: faster and is kept for A/B on future hardware.
CHAIN = _os.environ.get("SMARTBFT_PALLAS_CHAIN", "ripple")


def _carry(cols):
    """Normalize (..., m, B) column sums (< 2^31) into 16-bit limbs.

    Each step is a full-lane (..., B) vector op; final carry must be
    zero.  See :data:`CHAIN` for the two implementations."""
    m = cols.shape[-2]
    if CHAIN == "prefix":
        x = cols
        for _ in range(2):
            x = (x & LIMB_MASK) + _shift_rows_up(x >> LIMB_BITS, 1)
        limbs, _ = _resolve_prefix(x, m)
        return limbs
    out = []
    c = jnp.zeros_like(_row(cols, 0))
    for i in range(m):
        t = _row(cols, i) + c
        out.append(t & LIMB_MASK)
        c = t >> LIMB_BITS
    return _stack_rows(out)


def _sub_borrow(a, b):
    """(a - b) limb-wise with borrow chain; returns (diff, (..., B) borrow)."""
    b = jnp.broadcast_to(b, a.shape)
    m = a.shape[-2]
    if CHAIN == "prefix":
        # a - b = a + ~b + 1; carry-out <=> a >= b
        x = a + (jnp.uint32(LIMB_MASK) - b)
        x = jnp.concatenate(
            [x[..., :1, :] + jnp.uint32(1), x[..., 1:, :]], axis=-2
        )
        hi = x >> LIMB_BITS  # top row's local carry is a real carry-out
        x = (x & LIMB_MASK) + _shift_rows_up(hi, 1)
        diff, carry = _resolve_prefix(x, m)
        return diff, jnp.uint32(1) - (carry | hi[..., m - 1, :])
    out = []
    borrow = jnp.zeros_like(_row(a, 0))
    big = jnp.uint32(1 << LIMB_BITS)
    for i in range(m):
        t = _row(a, i) + big - _row(b, i) - borrow
        out.append(t & LIMB_MASK)
        borrow = jnp.uint32(1) - (t >> LIMB_BITS)
    return _stack_rows(out), borrow


def _add_rows(a, b):
    """Plain limb addition -> (..., m+1, B) normalized."""
    cols = jnp.concatenate(
        [a + b, jnp.zeros_like(a[..., :1, :])], axis=-2
    )
    return _carry(cols)


def _select(mask, a, b):
    """Row-broadcast select: mask (..., B) 0/1 -> where(mask, a, b)."""
    return jnp.where(mask[..., None, :].astype(bool), a, b)


def _is_zero(a):
    # unrolled OR-fold over limb rows: Mosaic lacks unsigned reductions
    acc = _row(a, 0)
    for i in range(1, a.shape[-2]):
        acc = acc | _row(a, i)
    return (acc == 0).astype(jnp.uint32)


def _eq(a, b):
    return _is_zero(a ^ b)


def _grp(op, pairs):
    """Stack k independent binary ops into one call along a new leading
    axis — k results for one set of unrolled chains (and one k-fold
    smaller graph than k separate calls)."""
    shape = jnp.broadcast_shapes(*(x.shape for pr in pairs for x in pr))
    a = jnp.stack([jnp.broadcast_to(x, shape) for x, _ in pairs])
    b = jnp.stack([jnp.broadcast_to(y, shape) for _, y in pairs])
    out = op(a, b)
    return tuple(out[i] for i in range(len(pairs)))


def _grp1(op, items):
    shape = jnp.broadcast_shapes(*(x.shape for x in items))
    a = jnp.stack([jnp.broadcast_to(x, shape) for x in items])
    out = op(a)
    return tuple(out[i] for i in range(len(items)))


def _pad_rows(x, before: int, total: int):
    """Zero-pad along the limb axis to ``total`` rows, ``before`` leading.

    Plain pad+add accumulation — ``.at[].add`` lowers to scatter-add,
    which Mosaic does not implement."""
    after = total - before - x.shape[-2]
    spec = [(0, 0)] * (x.ndim - 2) + [(before, after), (0, 0)]
    return jnp.pad(x, spec)


def _mul_cols(a, b):
    """Product columns (..., 2*NL+1, B), unnormalized; sums < 2^22."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)
    total = None
    rows = 2 * NL + 1
    for i in range(NL):
        p = a[..., i : i + 1, :] * b  # (..., NL, B); row j -> column i+j
        contrib = _pad_rows(p & LIMB_MASK, i, rows) + _pad_rows(
            p >> LIMB_BITS, i + 1, rows
        )
        total = contrib if total is None else total + contrib
    return total


def _mul_cols_low(a, b):
    """Low NL product columns only — a*b mod 2^(16*NL), unnormalized.

    For the Montgomery m-step (m = T_lo * N' mod R) the high half of the
    product is discarded; skipping partial products with i+j >= NL halves
    the lane-mult count of this step."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)
    total = None
    for i in range(NL):
        p = a[..., i : i + 1, :] * b[..., : NL - i, :]  # columns i..NL-1
        lo = _pad_rows(p & LIMB_MASK, i, NL)
        hi = p >> LIMB_BITS  # column i+j+1; the top one (== NL) is dropped
        if i + 1 < NL:
            lo = lo + _pad_rows(hi[..., : NL - i - 1, :], i + 1, NL)
        total = lo if total is None else total + lo
    return total


def _sqr_cols(a):
    """Squaring columns: upper triangle, off-diagonal weight 2 (scalar)."""
    total = None
    rows = 2 * NL + 1
    two = jnp.uint32(2)
    for i in range(NL):
        p = a[..., i : i + 1, :] * a[..., i:, :]  # rows j=i.. -> col i+j
        lo, hi = p & LIMB_MASK, p >> LIMB_BITS
        if NL - i > 1:
            lo = jnp.concatenate([lo[..., :1, :], lo[..., 1:, :] * two], axis=-2)
            hi = jnp.concatenate([hi[..., :1, :], hi[..., 1:, :] * two], axis=-2)
        contrib = _pad_rows(lo, 2 * i, rows) + _pad_rows(hi, 2 * i + 1, rows)
        total = contrib if total is None else total + contrib
    return total


class _Fld:
    """Montgomery field mod a constant, limb-major; built inside the kernel."""

    def __init__(self, mod_limbs: tuple, nprime: tuple, nb: int):
        self.N = _ccol(mod_limbs, nb)
        self.Np = _ccol(nprime, nb)
        self.N_ext = jnp.concatenate([self.N, jnp.zeros((1, nb), jnp.uint32)])

    def _redc(self, cols):
        """(..., 2*NL+1, B) columns -> (..., NL, B) reduced, < N."""
        T = _carry(cols)
        m = _carry(_mul_cols_low(T[..., :NL, :], self.Np))
        mn = _mul_cols(m, self.N)
        z1 = jnp.zeros_like(T[..., :1, :])
        s = _carry(
            jnp.concatenate([T, z1], axis=-2)
            + jnp.concatenate([mn, z1], axis=-2)
        )
        r = s[..., NL : 2 * NL + 1, :]  # (..., NL+1, B), value < 2N
        d, borrow = _sub_borrow(r, self.N_ext)
        return _select(borrow, r, d)[..., :NL, :]

    def mul(self, a, b):
        return self._redc(_mul_cols(a, b))

    def sqr(self, a):
        return self._redc(_sqr_cols(a))

    def add(self, a, b):
        s = _add_rows(a, b)
        d, borrow = _sub_borrow(s, self.N_ext)
        return _select(borrow, s, d)[..., :NL, :]

    def sub(self, a, b):
        d, borrow = _sub_borrow(a, b)
        wrapped = _add_rows(d, self.N)[..., :NL, :]
        return _select(borrow, wrapped, d)


# ---------------------------------------------------------------------------
# curve ops: a point is (..., 3, NL, B); formulas are level-scheduled with
# _grp so each dataflow level is ONE stacked Montgomery call.
# ---------------------------------------------------------------------------


def _point_add(f, b_m, p, q):
    """RCB15 Algorithm 4 complete addition (a = -3); p256.point_add math."""
    x1, y1, z1 = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    x2, y2, z2 = q[..., 0, :, :], q[..., 1, :, :], q[..., 2, :, :]
    a1, a2, a4, a5, a7, a8 = _grp(
        f.add, [(x1, y1), (x2, y2), (y1, z1), (y2, z2), (x1, z1), (x2, z2)]
    )
    t0, t1, t2, m1, m2, m3 = _grp(
        f.mul, [(x1, x2), (y1, y2), (z1, z2), (a1, a2), (a4, a5), (a7, a8)]
    )
    a3, a6, a9, u1, w1 = _grp(
        f.add, [(t0, t1), (t1, t2), (t0, t2), (t2, t2), (t0, t0)]
    )
    t3, t4, y3a = _grp(f.sub, [(m1, a3), (m2, a6), (m3, a9)])
    u2, w2 = _grp(f.add, [(u1, t2), (w1, t0)])  # 3*t2, 3*t0
    zb, yb = _grp(f.mul, [(b_m, t2), (b_m, y3a)])
    x3a, t0b, y3b = _grp(f.sub, [(y3a, zb), (w2, u2), (yb, u2)])
    z3a = f.add(x3a, x3a)
    y3c = f.sub(y3b, t0)
    x3b, v1 = _grp(f.add, [(x3a, z3a), (y3c, y3c)])
    x3c, y3d = _grp(f.add, [(t1, x3b), (v1, y3c)])
    z3b = f.sub(t1, x3b)
    p1, p2, p3, p4, p5, p6 = _grp(
        f.mul,
        [(t4, y3d), (t0b, y3d), (x3c, z3b), (t3, x3c), (t4, z3b), (t3, t0b)],
    )
    y3, z3 = _grp(f.add, [(p3, p2), (p5, p6)])
    x3 = f.sub(p4, p1)
    return jnp.stack([x3, y3, z3], axis=-3)


def _point_double(f, b_m, p):
    """RCB15 Algorithm 6 complete doubling (a = -3); p256.point_double math."""
    x, y, z = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    t0, t1, t2 = _grp1(f.sqr, [x, y, z])
    xy, xz, yz = _grp(f.mul, [(x, y), (x, z), (y, z)])
    t3, z3a, yz2, t2a, t0a = _grp(
        f.add, [(xy, xy), (xz, xz), (yz, yz), (t2, t2), (t0, t0)]
    )
    t2_3, t0_3 = _grp(f.add, [(t2a, t2), (t0a, t0)])
    bt2, bz3 = _grp(f.mul, [(b_m, t2), (b_m, z3a)])
    y3a, z3b, t0d = _grp(f.sub, [(bt2, z3a), (bz3, t2_3), (t0_3, t2_3)])
    y3a2 = f.add(y3a, y3a)
    z3c = f.sub(z3b, t0)
    y3b, z3c2 = _grp(f.add, [(y3a2, y3a), (z3c, z3c)])
    z3d, y3c = _grp(f.add, [(z3c2, z3c), (t1, y3b)])
    x3a = f.sub(t1, y3b)
    y3d, x3b, t0b, zz, zt = _grp(
        f.mul,
        [(x3a, y3c), (x3a, t3), (t0d, z3d), (yz2, z3d), (yz2, t1)],
    )
    y3, zt2 = _grp(f.add, [(y3d, t0b), (zt, zt)])
    x3 = f.sub(x3b, zz)
    z3 = f.add(zt2, zt2)
    return jnp.stack([x3, y3, z3], axis=-3)


def _digits2(a, ndig: int):
    """(NL, B) scalar -> list of ndig (B,) MSB-first 2-bit digits."""
    rows = []
    for k in range(ndig):
        bitpos = 2 * (ndig - 1 - k)
        limb, off = bitpos // LIMB_BITS, bitpos % LIMB_BITS
        rows.append((a[limb] >> jnp.uint32(off)) & jnp.uint32(3))
    return rows


def _digits_w(a, ndig: int, width: int):
    """(NL, B) scalar -> list of ndig (B,) MSB-first ``width``-bit digits.

    Unlike :func:`_digits2`, windows may straddle a limb boundary (width 3
    on 16-bit limbs), so each read spans two limbs."""
    rows = []
    nl = a.shape[-2]
    mask = jnp.uint32((1 << width) - 1)
    for k in range(ndig):
        bitpos = width * (ndig - 1 - k)
        limb, off = bitpos // LIMB_BITS, bitpos % LIMB_BITS
        v = a[limb] >> jnp.uint32(off)
        if off + width > LIMB_BITS and limb + 1 < nl:
            v = v | (a[limb + 1] << jnp.uint32(LIMB_BITS - off))
        rows.append(v & mask)
    return rows


class _JaxOps:
    """Dynamic-lookup strategy for the plain-JAX (validation) path."""

    def __init__(self, digs):
        self._digs = digs
        self._idx = None

    def stash_idx(self, rows):
        self._idx = jnp.stack(rows)

    def idx_at(self, i):
        return lax.dynamic_index_in_dim(self._idx, i, axis=0, keepdims=False)

    def dig_at(self, i):
        return lax.dynamic_index_in_dim(self._digs, i, axis=0, keepdims=False)


class _PallasOps:
    """Dynamic lookups via refs — Mosaic cannot dynamic-slice values.

    The scan's per-step table indices are stashed in a VMEM scratch and
    read back one row at a time with ``pl.ds``; the static inversion
    digits are read along the lane axis of a (1, ndig) operand."""

    def __init__(self, digs_ref, idx_scratch):
        self._digs_ref = digs_ref
        self._idx = idx_scratch

    def stash_idx(self, rows):
        for k, v in enumerate(rows):
            self._idx[k, :] = v

    def idx_at(self, i):
        return self._idx[pl.ds(i, 1), :][0]

    def dig_at(self, i):
        return self._digs_ref[0, i]  # SMEM scalar read


def _inv_n(fn, one_n, s, ops):
    """1/s mod N via Fermat, 4-bit fixed window (static exponent N-2)."""
    pows = [one_n, s]
    while len(pows) < 16:
        have = len(pows)
        take = min(have - 1, 16 - have)
        new = _grp(fn.mul, [(pows[have - 1], pows[i + 1]) for i in range(take)])
        pows.extend(new)
    table = jnp.stack(pows)  # (16, NL, B)

    acc = table[int(INV_DIGITS[0])]

    def body(i, acc):
        for _ in range(4):
            acc = fn.sqr(acc)
        d = ops.dig_at(i)
        # masked accumulation over the 16 powers (d is a scalar)
        sel = jnp.zeros_like(acc)
        for k in range(16):
            sel = sel + table[k] * (d == k).astype(jnp.uint32)
        return fn.mul(acc, sel)

    return lax.fori_loop(1, _INV_NDIG, body, acc)


def _verify_block(ops, e, r, s, qx, qy):
    """The full verify on one (NL, B) limb-major block.  Returns (B,) mask."""
    nb = e.shape[-1]
    fp = _Fld(_P, _P_NPRIME, nb)
    fn = _Fld(_N, _N_NPRIME, nb)
    b_m = _ccol(_B_MONT, nb)
    one_p = _ccol(_P_ONE, nb)
    one_n = _ccol(_N_ONE, nb)
    p_r2 = _ccol(_P_R2, nb)
    n_r2 = _ccol(_N_R2, nb)
    one_raw = _ccol(_limbs(1), nb)
    zero = jnp.zeros((NL, nb), jnp.uint32)

    # 1 <= r, s < n
    _, rb = _sub_borrow(r, fn.N)
    _, sb = _sub_borrow(s, fn.N)
    r_ok = (jnp.uint32(1) - _is_zero(r)) * rb
    s_ok = (jnp.uint32(1) - _is_zero(s)) * sb

    # u1 = e/s, u2 = r/s  (mod n)
    d, eb = _sub_borrow(e, fn.N)
    e_red = _select(eb, e, d)  # e < 2n -> one conditional subtract
    s_m, r_m_n, e_m_n = _grp(fn.mul, [(s, n_r2), (r, n_r2), (e_red, n_r2)])
    w = _inv_n(fn, one_n, s_m, ops)
    u1m, u2m = _grp(fn.mul, [(e_m_n, w), (r_m_n, w)])
    u1, u2 = _grp(fn.mul, [(u1m, one_raw), (u2m, one_raw)])

    # curve points (Montgomery domain)
    xm, ym = _grp(fp.mul, [(qx, p_r2), (qy, p_r2)])
    # on-curve: y^2 == x^3 - 3x + b
    yy, xx = _grp1(fp.sqr, [ym, xm])
    x3v = fp.mul(xx, xm)
    threex = fp.add(fp.add(xm, xm), xm)
    oncurve = _eq(yy, fp.add(fp.sub(x3v, threex), b_m))

    gpt = jnp.stack([_ccol(_GX_MONT, nb), _ccol(_GY_MONT, nb), one_p],
                    axis=-3)
    qpt = jnp.stack([xm, ym, jnp.broadcast_to(one_p, xm.shape)], axis=-3)
    inf = jnp.stack([zero, one_p, zero], axis=-3)

    # joint table {i*G + j*Q : 0 <= i, j < 2^W}, built in O(W) stacked
    # point-op levels (the complete formula handles P+P and inf, so every
    # level is one grouped _point_add call); entry 0 is inf+inf = inf
    if WINDOW == 2:
        two = _point_double(fp, b_m, jnp.stack([gpt, qpt]))
        three = _point_add(fp, b_m, two, jnp.stack([gpt, qpt]))
        gs = [inf, gpt, two[0], three[0]]
        qs = [inf, qpt, two[1], three[1]]
    else:  # WINDOW == 3
        two = _point_double(fp, b_m, jnp.stack([gpt, qpt]))
        g2, q2 = two[0], two[1]
        l2 = _point_add(
            fp, b_m,
            jnp.stack([gpt, g2, qpt, q2]),
            jnp.stack([g2, g2, q2, q2]),
        )  # 3G, 4G, 3Q, 4Q
        g3, g4, q3, q4 = l2[0], l2[1], l2[2], l2[3]
        l3 = _point_add(
            fp, b_m,
            jnp.stack([gpt, g2, g3, qpt, q2, q3]),
            jnp.stack([g4, g4, g4, q4, q4, q4]),
        )  # 5G, 6G, 7G, 5Q, 6Q, 7Q
        gs = [inf, gpt, g2, g3, g4, l3[0], l3[1], l3[2]]
        qs = [inf, qpt, q2, q3, q4, l3[3], l3[4], l3[5]]
    base = 1 << WINDOW
    if WINDOW == 2:
        lhs = jnp.stack([g for g in gs for _ in range(base)])
        rhs = jnp.stack([q for _ in range(base) for q in qs])
        table = _point_add(fp, b_m, lhs, rhs)  # (16, 3, NL, B)
    else:
        # one 64-way stacked add would blow the 16MB VMEM budget (the
        # grouped internals are ~6x the stack size); 8 sequential 8-way
        # adds keep the live set at the w=2 scale for 7 extra one-time
        # point-op levels
        qstack = jnp.stack(qs)
        rows = [_point_add(fp, b_m, jnp.stack([g] * base), qstack)
                for g in gs]
        table = jnp.concatenate(rows)  # (64, 3, NL, B)

    d1 = _digits_w(u1, NDIGITS, WINDOW)
    d2 = _digits_w(u2, NDIGITS, WINDOW)
    ops.stash_idx([a * base + b for a, b in zip(d1, d2)])  # NDIGITS x (B,)

    def scan_body(i, acc):
        for _ in range(WINDOW):
            acc = _point_double(fp, b_m, acc)
        idx = ops.idx_at(i)  # (B,), batch-varying
        sel = jnp.zeros((3, NL, nb), jnp.uint32)
        for k in range(base * base):  # masked accumulation -- no gather
            mk = (idx == k).astype(jnp.uint32)[None, None, :]
            sel = sel + table[k] * mk
        return _point_add(fp, b_m, acc, sel)

    acc = lax.fori_loop(0, NDIGITS, scan_body, inf)
    xr, zr = acc[..., 0, :, :], acc[..., 2, :, :]

    not_inf = jnp.uint32(1) - _is_zero(zr)
    # projective comparison: x_aff in {r, r+n} n [0, p)
    c17 = _add_rows(r, fn.N)  # (NL+1, B)
    c_in_range = (c17[NL] == 0).astype(jnp.uint32)
    c16 = c17[:NL]
    _, c_lt_p = _sub_borrow(c16, fp.N)
    c_ok = c_in_range * c_lt_p
    r_mp, c_mp = _grp(fp.mul, [(r, p_r2), (c16, p_r2)])
    mr, mc = _grp(fp.mul, [(r_mp, zr), (c_mp, zr)])
    # 0/1 masks: bitwise OR (Mosaic cannot legalize unsigned max)
    match = _eq(mr, xr) | (c_ok * _eq(mc, xr))
    return match * not_inf * r_ok * s_ok * oncurve


# ---------------------------------------------------------------------------
# pallas entry
# ---------------------------------------------------------------------------


def _kernel(digs_ref, e_ref, r_ref, s_ref, qx_ref, qy_ref, out_ref,
            idx_scratch):
    ops = _PallasOps(digs_ref, idx_scratch)
    mask = _verify_block(
        ops, e_ref[:], r_ref[:], s_ref[:], qx_ref[:], qy_ref[:]
    )
    out_ref[:] = mask[None, :]


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def ecdsa_verify(e, r, s, qx, qy, tile: int = 128, interpret: bool = False):
    """Batched P-256 ECDSA verify as one fused Pallas kernel.

    Inputs are the same (B, 16) standard-domain uint32 limb arrays as
    :func:`p256.ecdsa_verify_kernel`; returns the same (B,) mask.  The
    batch is transposed to limb-major once at the boundary and processed
    in ``tile``-lane grid steps.  ``tile`` must be a multiple of 128: the
    batch axis fills the VPU lane dimension, and Mosaic requires block
    last-dims to be whole multiples of the 128-lane register width.
    """
    from jax.experimental.pallas import tpu as pltpu

    if tile % 128 and not interpret:
        raise ValueError(f"tile must be a multiple of 128 lanes, got {tile}")

    bsz = e.shape[0]
    pad = (-bsz) % tile
    if pad:
        e, r, s, qx, qy = (
            jnp.pad(jnp.asarray(a), ((0, pad), (0, 0)))
            for a in (e, r, s, qx, qy)
        )
    total = e.shape[0]
    args = [jnp.transpose(jnp.asarray(a)).astype(jnp.uint32)
            for a in (e, r, s, qx, qy)]

    spec = pl.BlockSpec((NL, tile), lambda i: (0, i))
    dig_spec = pl.BlockSpec(
        (1, INV_DIGITS.shape[0]), lambda i: (0, 0),
        memory_space=pltpu.SMEM,
    )
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((1, total), jnp.uint32),
        grid=(total // tile,),
        in_specs=[dig_spec] + [spec] * 5,
        out_specs=pl.BlockSpec((1, tile), lambda i: (0, i)),
        scratch_shapes=[pltpu.VMEM((_NDIG_PAD, tile), jnp.uint32)],
        interpret=interpret,
    )(jnp.asarray(INV_DIGITS).reshape(1, -1), *args)
    return out[0, :bsz]


verify_kernel_pallas = ecdsa_verify
