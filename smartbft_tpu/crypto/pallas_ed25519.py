"""Static-key comb-table Pallas kernel for Ed25519 verification.

The twisted-Edwards analogue of :mod:`pallas_comb` — replacing the same
reference hot path (one goroutine per commit-signature verify,
/root/reference/internal/bft/view.go:537-541) for the alt-curve variant of
BASELINE.md configs[3].  The cofactorless verification equation
``[S]B == R + [h]A`` is evaluated as ``[S]B + [h](-A) == R``:

* both bases are STATIC — B is the RFC 8032 base point and A is one of n
  replica keys fixed at configuration — so each gets a host-precomputed
  Lim-Lee comb table (w=8 teeth, stride 32; the key tables store the
  NEGATED public point so the scan only ever adds);
* there is NO scalar inversion anywhere, so the kernel is just the
  32-iteration comb walk (1 doubling + 2 unified additions each) plus the
  projective comparison against R — even simpler than P-256's;
* table entries are affine Edwards points (identity (0, 1) included — the
  a=-1 unified formulas are complete), stored as split-byte Montgomery
  rows [X, Y, T=x*y] with Z == 1 implicit, selected by one-hot bf16
  matmuls on the MXU exactly like pallas_comb;
* the public key's curve membership is checked once at registration
  (host ints), R's at every verify in-kernel (R arrives per signature).

Host-side marshalling (SHA-512, point decompression, the s < L range
check) mirrors the existing XLA kernel path (:mod:`ed25519`).
"""

from __future__ import annotations

import functools
import hashlib

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from . import ed25519 as ed
from .bignum import to_limbs
from .ed25519 import BX, BY, D, L, P
from .pallas_comb import (
    ROWS,
    STRIDE,
    TEETH,
    TSIZE,
    CombKeyRegistry,
    CombVerifier,
    _comb_digits,
    _maybe_unpack,
)
from .pallas_ecdsa import LIMB_BITS, NL, _ccol, _eq, _Fld, _grp, _grp1, \
    _is_zero, _limbs, _select, _sub_borrow

R_MONT = 1 << (LIMB_BITS * NL)

_P_ED = _limbs(P)
_L_ED = _limbs(L)
_P_NPRIME_ED = _limbs((-pow(P, -1, R_MONT)) % R_MONT)
_P_R2_ED = _limbs((R_MONT * R_MONT) % P)
_P_ONE_ED = _limbs(R_MONT % P)
_D_MONT_ED = _limbs((D * R_MONT) % P)
_D2_MONT_ED = _limbs((2 * D * R_MONT) % P)


# ---------------------------------------------------------------------------
# host-side tables
# ---------------------------------------------------------------------------


def is_on_curve_int(pt) -> bool:
    """-x² + y² == 1 + d x² y² (mod p) for an affine Edwards point."""
    x, y = pt
    if not (0 <= x < P and 0 <= y < P):
        return False
    return (y * y - x * x - 1 - D * x * x % P * (y * y % P)) % P == 0


def _comb_entries(point) -> list:
    """All 2^TEETH subset sums of {2^(STRIDE·t)·point : t < TEETH}."""
    bases = [point]
    for _ in range(TEETH - 1):
        b = bases[-1]
        for _ in range(STRIDE):
            b = ed._aff_add(b, b)
        bases.append(b)
    table = [(0, 1)] * TSIZE
    for idx in range(1, TSIZE):
        low = idx & -idx
        table[idx] = ed._aff_add(table[idx ^ low], bases[low.bit_length() - 1])
    return table


def _mont_limbs(v: int) -> np.ndarray:
    return np.asarray(to_limbs((v * R_MONT) % P, NL), np.uint32)


def build_table(point) -> np.ndarray:
    """(ROWS, TSIZE) float32 comb table for one affine Edwards point.

    Rows [0:48] are low bytes of (X, Y, T=x·y) Montgomery limbs, [48:96]
    the high bytes; Z == 1 for every entry (the identity (0, 1) is an
    ordinary affine point on this curve).
    """
    entries = _comb_entries(point)
    out = np.zeros((ROWS, TSIZE), dtype=np.float32)
    for idx, (x, y) in enumerate(entries):
        limbs = np.concatenate(
            [_mont_limbs(x), _mont_limbs(y), _mont_limbs(x * y % P)]
        )
        out[:48, idx] = limbs & 0xFF
        out[48:, idx] = limbs >> 8
    return out


def _neg_pub_table(pub_pt) -> np.ndarray:
    """Comb table of -A for a decompressed public point A."""
    x, y = pub_pt
    return build_table(((P - x) % P, y))


@functools.lru_cache(maxsize=1)
def b_table() -> np.ndarray:
    return build_table((BX, BY))


# ---------------------------------------------------------------------------
# limb-major twisted-Edwards ops (points are (..., 4, NL, B): X, Y, Z, T)
# ---------------------------------------------------------------------------


def _ed_add(fp, d2, p, q):
    """Unified add-2008-hwcd-3 (a = -1); complete, mirrors ed.point_add."""
    x1, y1, z1, t1 = (p[..., i, :, :] for i in range(4))
    x2, y2, z2, t2 = (q[..., i, :, :] for i in range(4))
    s1, s2 = _grp(fp.sub, [(y1, x1), (y2, x2)])
    a1, a2, z1d = _grp(fp.add, [(y1, x1), (y2, x2), (z1, z1)])
    a, b, c1, d = _grp(fp.mul, [(s1, s2), (a1, a2), (t1, d2), (z1d, z2)])
    c = fp.mul(c1, t2)
    e, ff = _grp(fp.sub, [(b, a), (d, c)])
    g, h = _grp(fp.add, [(d, c), (b, a)])
    x3, y3, t3, z3 = _grp(fp.mul, [(e, ff), (g, h), (e, h), (ff, g)])
    return jnp.stack([x3, y3, z3, t3], axis=-3)


def _ed_dbl(fp, p):
    """dbl-2008-hwcd with both halves negated (a = -1); mirrors
    ed.point_double.  T input unused."""
    x, y, z = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    xy = fp.add(x, y)
    a, b, zz, s = _grp1(fp.sqr, [x, y, z, xy])
    c, h = _grp(fp.add, [(zz, zz), (a, b)])
    g, e1 = _grp(fp.sub, [(b, a), (s, a)])
    e = fp.sub(e1, b)
    ff = fp.sub(c, g)
    x3, y3, z3, t3 = _grp(fp.mul, [(e, ff), (g, h), (ff, g), (e, h)])
    return jnp.stack([x3, y3, z3, t3], axis=-3)


def _sel_ed(table_f32, one_p):
    """(ROWS, B) selected columns -> (4, NL, B) extended point, Z = 1."""
    lo = table_f32[:48, :]
    hi = table_f32[48:, :]
    limbs = (lo + hi * 256.0).astype(jnp.int32).astype(jnp.uint32)
    x, y, t = limbs[0:NL], limbs[NL:2 * NL], limbs[2 * NL:3 * NL]
    return jnp.stack([x, y, jnp.broadcast_to(one_p, x.shape), t], axis=-3)


def _kernel(nkeys, s_ref, h_ref, rx_ref, ry_ref, ok_ref, kidx_ref, btab_ref,
            qtab_ref, out_ref, idx_scratch):
    s, h = s_ref[:], h_ref[:]
    rx, ry = rx_ref[:], ry_ref[:]
    kidx = kidx_ref[0, :]
    nb = s.shape[-1]
    fp = _Fld(_P_ED, _P_NPRIME_ED, nb)
    one_p = _ccol(_P_ONE_ED, nb)
    p_r2 = _ccol(_P_R2_ED, nb)
    d2 = _ccol(_D2_MONT_ED, nb)
    d_m = _ccol(_D_MONT_ED, nb)
    zero = jnp.zeros((NL, nb), jnp.uint32)
    ident = jnp.stack([zero, one_p, one_p, zero], axis=-3)

    for k, v in enumerate(_comb_digits(s, nb)):
        idx_scratch[k, :] = v
    for k, v in enumerate(_comb_digits(h, nb)):
        idx_scratch[STRIDE + k, :] = v

    # R into the Montgomery domain + on-curve check (A was checked at
    # registration; R arrives with every signature)
    rxm, rym = _grp(fp.mul, [(rx, p_r2), (ry, p_r2)])
    xx, yy = _grp1(fp.sqr, [rxm, rym])
    lhs = fp.sub(yy, xx)
    rhs = fp.add(one_p, fp.mul(d_m, fp.mul(xx, yy)))
    r_oncurve = _eq(lhs, rhs)

    btab = btab_ref[:]
    qtab = qtab_ref[:]
    iota_t = lax.broadcasted_iota(jnp.int32, (TSIZE, nb), 0)

    def scan_body(i, acc):
        acc = _ed_dbl(fp, acc)
        sd = idx_scratch[pl.ds(i, 1), :][0]
        hd = idx_scratch[pl.ds(i + STRIDE, 1), :][0]
        oh_b = (iota_t == sd[None, :]).astype(jnp.bfloat16)
        oh_q = (iota_t == hd[None, :]).astype(jnp.bfloat16)
        sel_b = jnp.dot(btab, oh_b, preferred_element_type=jnp.float32)
        aq = jnp.dot(qtab, oh_q, preferred_element_type=jnp.float32)
        sq = jnp.zeros((ROWS, nb), jnp.float32)
        for k in range(nkeys):
            mask = (kidx == k).astype(jnp.float32)[None, :]
            sq = sq + aq[k * ROWS:(k + 1) * ROWS, :] * mask
        acc = _ed_add(fp, d2, acc, _sel_ed(sel_b, one_p))
        return _ed_add(fp, d2, acc, _sel_ed(sq, one_p))

    acc = lax.fori_loop(0, STRIDE, scan_body, ident)
    xz, yz, z = acc[..., 0, :, :], acc[..., 1, :, :], acc[..., 2, :, :]
    # Z != 0 guard: complete Edwards formulas never produce Z = 0 from
    # valid inputs, but a zero (padding) table entry would drive the
    # accumulator to the all-zero point, which the projective comparison
    # below otherwise matches (0 == 0) for EVERY lane — a false accept
    not_zero = jnp.uint32(1) - _is_zero(z)
    mx, my = _grp(fp.mul, [(rxm, z), (rym, z)])
    match = _eq(mx, xz) * _eq(my, yz)
    out_ref[:] = (match * not_zero * r_oncurve * ok_ref[0, :])[None, :]


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def eddsa_verify_comb(s, h, rx, ry, ok, kidx, btab, qtab, tile: int = 128,
                      interpret: bool = False):
    """Batched Ed25519 verify against registered keys.

    ``s, h, rx, ry``: (B, 32) uint8 little-endian (or (B, 16) uint32
    limbs); ``ok``: (B,) host pre-check mask (decompression, s < L);
    ``kidx``: per-lane key index; ``btab``/``qtab``: comb tables.
    Returns the (B,) uint32 validity mask; padded lanes (ok = 0) fail.
    """
    from jax.experimental.pallas import tpu as pltpu

    if tile % 128 and not interpret:
        raise ValueError(f"tile must be a multiple of 128 lanes, got {tile}")
    if qtab.shape[0] % ROWS:
        raise ValueError("qtab row count must be a multiple of 96")
    nkeys = qtab.shape[0] // ROWS

    s, h, rx, ry = (_maybe_unpack(a) for a in (s, h, rx, ry))
    bsz = s.shape[0]
    pad = (-bsz) % tile
    if pad:
        s, h, rx, ry = (jnp.pad(jnp.asarray(a), ((0, pad), (0, 0)))
                        for a in (s, h, rx, ry))
        kidx = jnp.pad(jnp.asarray(kidx), (0, pad))
        ok = jnp.pad(jnp.asarray(ok), (0, pad))
    total = s.shape[0]
    args = [jnp.transpose(jnp.asarray(a)).astype(jnp.uint32)
            for a in (s, h, rx, ry)]
    kidx = jnp.asarray(kidx, jnp.int32).reshape(1, total)
    ok = jnp.asarray(ok, jnp.uint32).reshape(1, total)
    btab = jnp.asarray(btab, jnp.bfloat16)
    qtab = jnp.asarray(qtab, jnp.bfloat16)

    spec = pl.BlockSpec((NL, tile), lambda i: (0, i))
    lane_spec = pl.BlockSpec((1, tile), lambda i: (0, i))
    out = pl.pallas_call(
        functools.partial(_kernel, nkeys),
        out_shape=jax.ShapeDtypeStruct((1, total), jnp.uint32),
        grid=(total // tile,),
        in_specs=[spec] * 4 + [lane_spec, lane_spec,
                               pl.BlockSpec((ROWS, TSIZE), lambda i: (0, 0)),
                               pl.BlockSpec((nkeys * ROWS, TSIZE),
                                            lambda i: (0, 0))],
        out_specs=pl.BlockSpec((1, tile), lambda i: (0, i)),
        scratch_shapes=[pltpu.VMEM((2 * STRIDE, tile), jnp.int32)],
        interpret=interpret,
    )(*args, ok, kidx, btab, qtab)
    return out[0, :bsz]


# ---------------------------------------------------------------------------
# registry + engine adapter
# ---------------------------------------------------------------------------


def _validate_pub(pub: bytes):
    """Decompress + validate a compressed public key; returns the point."""
    pt = ed.decompress(pub)
    if pt is None or not is_on_curve_int(pt):
        raise ValueError("public key is not on the Ed25519 curve")
    return pt


def _build_key_table(pub: bytes) -> np.ndarray:
    return _neg_pub_table(_validate_pub(pub))


def pack_items(items, registry) -> tuple:
    """items -> ((B,32) uint8 s/h/rx/ry, ok, kidx) host prep.

    Host work mirrors ed25519.verify_inputs: SHA-512 binding hash mod L,
    R decompression, the RFC 8032 s < L check.  Lanes failing any host
    check get ok = 0 (the kernel returns 0 for them).
    """
    B = len(items)
    s8 = np.zeros((B, 32), np.uint8)
    h8 = np.zeros((B, 32), np.uint8)
    rx8 = np.zeros((B, 32), np.uint8)
    ry8 = np.zeros((B, 32), np.uint8)
    ok = np.zeros(B, np.uint32)
    kidx = np.zeros(B, np.int32)
    for i, (msg, sig, pub) in enumerate(items):
        kidx[i] = registry.register(pub)
        if len(sig) != 64:
            continue
        s_int = int.from_bytes(sig[32:], "little")
        if s_int >= L:
            continue
        rpt = ed.decompress(sig[:32])
        if rpt is None:
            continue
        h_int = int.from_bytes(
            hashlib.sha512(sig[:32] + pub + msg).digest(), "little") % L
        s8[i] = np.frombuffer(s_int.to_bytes(32, "little"), np.uint8)
        h8[i] = np.frombuffer(h_int.to_bytes(32, "little"), np.uint8)
        rx8[i] = np.frombuffer(rpt[0].to_bytes(32, "little"), np.uint8)
        ry8[i] = np.frombuffer(rpt[1].to_bytes(32, "little"), np.uint8)
        ok[i] = 1
    return s8, h8, rx8, ry8, ok, kidx


class Ed25519CombVerifier(CombVerifier):
    """Engine adapter: the Edwards hooks on CombVerifier's scaffolding."""

    def _make_registry(self, cap: int) -> CombKeyRegistry:
        return CombKeyRegistry(
            cap=cap, validate=_validate_pub, build=_build_key_table
        )

    def _validate_key(self, pub) -> None:
        _validate_pub(pub)

    def _base_table(self) -> np.ndarray:
        return b_table()

    def _pack(self, items):
        s8, h8, rx8, ry8, ok, kidx = pack_items(items, self.registry)
        return [s8, h8, rx8, ry8], ok, kidx

    def _launch(self, arrays, ok, kidx, btab, qtab):
        return eddsa_verify_comb(*arrays, ok, kidx, btab, qtab,
                                 tile=self.tile)
